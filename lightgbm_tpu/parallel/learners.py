"""Distributed tree learners over a JAX device mesh.

TPU-native counterparts of the reference's three parallel tree learners
(reference: src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp and
the socket/MPI collective layer they ride on, src/network/network.cpp).
Instead of hand-rolled Bruck/recursive-halving collectives over TCP, the
whole tree build runs as ONE ``shard_map`` program over a
``jax.sharding.Mesh`` and the three communication points lower onto XLA
collectives over ICI/DCN:

  reference                              here
  ---------------------------------     ------------------------------
  histogram ReduceScatter                ``lax.psum`` of the wave's
    (data_parallel_tree_learner.cpp:147)   [W, F, B, 3] histograms
  best-split AllReduce w/ max-gain       ``lax.all_gather`` of the
    reducer (parallel_tree_learner.h:183)  per-child SplitResult batch
                                           + per-child argmax
  top-k vote Allgather                   ``lax.psum`` of one-hot votes
    (voting_parallel_tree_learner.cpp:342) + elected-feature psum

All modes drive the round-2 wave grower (ops/wave_grower.py): a wave of
up to W leaves is split per step and ONE wave-histogram pass feeds every
mode's collective, so the communication volume per step is W leaves'
histograms instead of one — the same batching win as on-device compute.

Modes (tree_learner config, config.h tree_learner):
- data:    rows sharded across devices; wave histograms psummed; every
           device computes the same global best splits.
- feature: every device holds ALL rows (like the reference, where each
           worker has the full data, feature_parallel_tree_learner.cpp:31);
           each device builds wave histograms only for its own feature
           slice, finds local bests, and the global best per child is
           all_gather + argmax. No row movement at split time.
- voting:  data-parallel with PV-Tree communication compression: each
           device votes its local top-k features per child, the global
           top-2k by vote count are elected, and ONLY those features'
           histograms are summed (``psum`` of a [2W, 2k, B, 3] slice
           instead of the full [2W, F, B, 3]).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:                                   # jax >= 0.5
    _shard_map = jax.shard_map
except AttributeError:                 # jax 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def _shard_map(*args, **kwargs):
        # the replication check was named check_rep before jax 0.5
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04(*args, **kwargs)

from ..ops.hist_wave import wave_histogram
from ..ops.split import (FeatureMeta, SplitResult, best_gain_per_feature,
                         find_best_split)
from ..ops.wave_grower import WaveGrowerConfig, make_wave_grower

AXIS = "workers"

# Injectable collective overrides — the TPU-native analog of the
# reference's external-collective seam (src/network/network.cpp:41-54,
# LGBM_NetworkInitWithFunctions): tests and embedders can wrap or
# replace the histogram reduce-scatter and best-split allgather.
# An override is fn(value, default_collective) -> value and must be
# jax-traceable; it runs at trace time, once per collective site per
# compilation (collectives are compiled into the XLA program, so the
# seam observes/extends tracing rather than per-step execution).
_collective_overrides: dict = {}


def set_network_functions(reduce_scatter_fn=None,
                          allgather_fn=None) -> None:
    """Install (or with both None, clear) collective overrides."""
    _collective_overrides.clear()
    if reduce_scatter_fn is not None:
        _collective_overrides["reduce_scatter"] = reduce_scatter_fn
    if allgather_fn is not None:
        _collective_overrides["allgather"] = allgather_fn


def _psum_seam(x):
    """Histogram/scalar reduction through the injectable seam."""
    def base(v):
        return jax.lax.psum(v, AXIS)
    ov = _collective_overrides.get("reduce_scatter")
    return ov(x, base) if ov is not None else base(x)


# packed psum wire (config.tpu_psum_wire): the quantized histogram
# payload is integer-valued, so inside the 127*N wrap bound it crosses
# the collective in a narrow dtype — cast, psum, widen, all exact
_WIRE_DTYPES = {"int8": jnp.int8, "int16": jnp.int16,
                "int32": jnp.int32}


def _slot_psum(x, slots: int, psum=_psum_seam):
    """The overlap-structured histogram collective
    (config.tpu_async_psum): split a [W, F, B, C] payload along the
    feature axis into ``slots`` INDEPENDENT psums. psum is elementwise
    across shards, so the slot split is BIT-identical to the monolithic
    collective (for f32 and integer wires alike) — what it buys is
    scheduling freedom: XLA can launch slot 0's DCN reduction while
    slot 1's producer (and downstream per-slot consumers) still
    occupy the cores, instead of stalling the whole step on one fused
    collective. Payloads too small/low-rank to split fall back to the
    single psum."""
    slots = max(int(slots), 1)
    if slots == 1 or x.ndim < 2 or x.shape[1] < slots:
        return psum(x)
    F = x.shape[1]
    step = F // slots
    parts = []
    lo = 0
    for s in range(slots):
        hi = F if s == slots - 1 else lo + step
        parts.append(psum(jax.lax.slice_in_dim(x, lo, hi, axis=1)))
        lo = hi
    return jnp.concatenate(parts, axis=1)


def make_hist_reduce(cfg: WaveGrowerConfig):
    """The data-parallel wave-histogram collective, assembled from the
    config's wire + slot arms (both proven bit-identical to the plain
    ``psum`` — see _slot_psum and the tune_psum_wire bound,
    ops/autotune.py):

    - wire (quant_psum only): the deferred-dequant payload is
      integer-VALUED (int32 on the Pallas tier, integral f32 on the
      XLA oracle), so the narrowing cast to cfg.psum_wire, the integer
      psum and the widening cast back are all exact inside the 127*N
      bound;
    - slots: the feature axis splits into cfg.psum_slots independent
      collectives XLA can overlap with local compute.
    """
    wire = _WIRE_DTYPES.get(cfg.psum_wire, jnp.int32)
    narrow = bool(cfg.quant_psum) and cfg.psum_wire != "int32"
    slots = max(int(cfg.psum_slots), 1)

    def one(x):
        if narrow and x.dtype != wire:
            return _psum_seam(x.astype(wire)).astype(x.dtype)
        return _psum_seam(x)

    def hist_reduce(x):
        return _slot_psum(x, slots, psum=one)

    return hist_reduce


_meshes_logged: set = set()


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    from ..utils import log
    from ..utils.device import get_devices, get_global_devices
    if jax.process_count() > 1:
        # real multi-process cluster (parallel/cluster.py): the mesh
        # MUST span every process's devices — a psum over a subset
        # would leave the excluded ranks' programs waiting forever, so
        # per-caller device caps (num_machines) do not apply here
        devs = get_global_devices()
        if num_devices is not None and num_devices < len(devs):
            log.debug("multi-process mesh ignores the %d-device cap: "
                      "collectives must span all %d global devices",
                      num_devices, len(devs))
        n = len(devs)
    else:
        devs = get_devices()
        n = (len(devs) if num_devices is None
             else min(num_devices, len(devs)))
    kind = str(getattr(devs[0], "device_kind", None) or devs[0].platform)
    # one info line per distinct mesh per process (ingest + grower +
    # every CV fold all build the same mesh; size-1 meshes are about
    # to be discarded with a serial-fallback warning)
    emit = log.info if n > 1 and (n, kind) not in _meshes_logged \
        else log.debug
    _meshes_logged.add((n, kind))
    emit("mesh built: %d device(s) of kind %s on axis %r (%d process(es))",
         n, kind, AXIS, jax.process_count())
    return Mesh(np.asarray(devs[:n]), (AXIS,))


def training_mesh(config) -> Optional[Mesh]:
    """The >1-device mesh the configured tree learner trains over, or
    None (serial learner, or only one device available). ONE policy
    for every consumer — sharded ingest (io/ingest.py) must assemble
    bins under exactly the mesh the grower will shard_map over, or
    init pays the full-matrix reshard this path exists to avoid."""
    if getattr(config, "tree_learner", "serial") == "serial":
        return None
    want = (config.num_machines
            if getattr(config, "num_machines", 1) > 1 else None)
    mesh = make_mesh(want)
    return mesh if mesh.devices.size > 1 else None


def sync_best_splits(res: SplitResult) -> SplitResult:
    """Cross-device argmax of per-device best-split batches — the analog
    of SyncUpGlobalBestSplit (parallel_tree_learner.h:183-207) over a
    whole wave of children at once."""
    def base(v):
        return jax.lax.all_gather(v, AXIS)
    ov = _collective_overrides.get("allgather")
    gathered = (ov(res, base) if ov is not None
                else base(res))                   # pytree of [D, M, ...]
    best = jnp.argmax(gathered.gain, axis=0)      # [M]
    m = best.shape[0]
    return SplitResult(*[leaf[best, jnp.arange(m)] for leaf in gathered])


def _slice_meta(meta: FeatureMeta, start, size: int) -> FeatureMeta:
    # scalar-sentinel fields (is_cat/bundle/offset defaults) pass through
    return FeatureMeta(*[
        a if jnp.ndim(a) == 0
        else jax.lax.dynamic_slice_in_dim(jnp.asarray(a), start, size, 0)
        for a in meta])


def _hist(cfg: WaveGrowerConfig):
    def hist_fn(bins_t, g, h, leaf_ids, wave_leaves, gh_scale=None):
        return wave_histogram(bins_t, g, h, leaf_ids, wave_leaves,
                              num_bins=cfg.num_bins, chunk=cfg.chunk,
                              use_pallas=cfg.use_pallas,
                              precision=cfg.precision,
                              gh_scale=gh_scale)
    return hist_fn


def make_data_parallel_grower(cfg: WaveGrowerConfig, meta: FeatureMeta,
                              mesh: Mesh, hist_fn=None):
    """Rows sharded over the mesh; wave histograms psummed.

    (DataParallelTreeLearner semantics; the reference reduce-scatters so
    each worker reduces a feature subset — with XLA the psum IS the
    reduce+broadcast and the compiler picks the wire algorithm.)

    The collective rides the ``hist_reduce_fn`` seam, NOT a hist_fn
    override, so the grower keeps its default seams and the FUSED
    partition+histogram Pallas kernel stays live per shard — on a real
    mesh each chip runs the same single-chip kernel on its rows and
    only the [W, F, B, 3] histograms cross ICI.

    The histogram collective itself is built by ``make_hist_reduce``
    from the config's packed-wire + slot arms (tpu_psum_wire /
    tpu_async_psum) — bit-identical to the plain psum by construction;
    scalar reductions (root aggregates) keep the plain seam.
    """
    def reduce_fn(x):
        return _psum_seam(x)

    hist_reduce_fn = make_hist_reduce(cfg)

    def max_reduce_fn(x):
        # global int8 quantization scales: every shard must quantize
        # with the same (sg, sh) or the count-proxy bounds computed on
        # the psummed histogram would be scale-inconsistent and
        # shard-divergent (and same-seed parity with serial improves)
        return jax.lax.pmax(x, AXIS)

    def row_offset_fn(n_local):
        # global row index base: shard d holds the contiguous rows
        # [d*n_local, (d+1)*n_local) of the padded global matrix, so
        # the stochastic-rounding hash draws the SAME uniform for the
        # same row as the single-chip grower (serial quantized parity)
        return jax.lax.axis_index(AXIS) * jnp.int32(n_local)

    # hist_fn (e.g. the EFB bundle-expansion seam) composes: each shard
    # histograms its own rows through it, then the expanded [W, F, B, 3]
    # rides the psum exactly like the default seam's output
    grow = make_wave_grower(cfg, meta, hist_fn=hist_fn,
                            hist_reduce_fn=hist_reduce_fn,
                            reduce_fn=reduce_fn,
                            max_reduce_fn=max_reduce_fn,
                            row_offset_fn=row_offset_fn, jit=False)
    # meta rides the shard_map as a REPLICATED argument (not a trace
    # constant) so the compiled-step registry (ops/step_cache.py) can
    # share one compiled program between boosters binned on different
    # data; legacy 5-arg callers get the factory meta passed for them
    meta_dev = FeatureMeta(*[jnp.asarray(a) for a in meta])
    meta_specs = FeatureMeta(*[P(*([None] * jnp.ndim(a)))
                               for a in meta_dev])
    sharded = _shard_map(
        grow, mesh=mesh,
        in_specs=(P(None, AXIS), P(AXIS), P(AXIS), P(AXIS), P(None),
                  meta_specs),
        out_specs=(P(), P(AXIS)),
        check_vma=False)
    # jit-capture: ok(sharded) — shard_map-wrapped grower: the grow
    # factory's own jit site carries the capture audit (meta rides as
    # a replicated ARGUMENT, PR 4), and this jit is factory-scoped.
    jitted = jax.jit(sharded)

    def call(bins_t, g, h, mask, fmask, meta=None):
        return jitted(bins_t, g, h, mask, fmask,
                      meta_dev if meta is None else meta)

    def lower(*args):
        # jit-object surface for introspection tests/tools: legacy
        # 5-arg callers get the factory meta appended, like call()
        return jitted.lower(*(args if len(args) == 6
                              else args + (meta_dev,)))
    call.lower = lower
    return call


def make_feature_parallel_grower(cfg: WaveGrowerConfig, meta: FeatureMeta,
                                 mesh: Mesh, num_features: int):
    """Every device holds all rows; feature slice per device for the
    histogram/split work (FeatureParallelTreeLearner semantics)."""
    D = mesh.devices.size
    if num_features % D != 0:
        raise ValueError("feature-parallel requires padded features")
    Fd = num_features // D
    local_hist = _hist(cfg)

    def hist_fn(bins_t, g, h, leaf_ids, wave_leaves, gh_scale=None):
        # int8 quantization composes: every device holds ALL rows, so
        # the (global-max) scales and the stochastic-rounding key are
        # identical on every device and the feature-sliced histograms
        # dequantize consistently
        i = jax.lax.axis_index(AXIS)
        local_bins = jax.lax.dynamic_slice_in_dim(bins_t, i * Fd, Fd, 0)
        return local_hist(local_bins, g, h, leaf_ids, wave_leaves,
                          gh_scale=gh_scale)

    def split_fn(hists, sg, sh, nd, fmask, can):
        i = jax.lax.axis_index(AXIS)
        meta_l = _slice_meta(meta, i * Fd, Fd)
        fmask_l = jax.lax.dynamic_slice_in_dim(fmask, i * Fd, Fd, 0)
        res = jax.vmap(
            lambda hh, a, b, c, d: find_best_split(
                hh, a, b, c, fmask_l, meta_l, cfg.hp, d)
        )(hists, sg, sh, nd, can)
        res = res._replace(
            feature=jnp.where(res.feature >= 0, res.feature + i * Fd, -1))
        return sync_best_splits(res)

    grow = make_wave_grower(cfg, meta, hist_fn=hist_fn, split_fn=split_fn,
                            jit=False)
    sharded = _shard_map(
        grow, mesh=mesh,
        in_specs=(P(None, None), P(None), P(None), P(None), P(None)),
        out_specs=(P(), P()),
        check_vma=False)
    # jit-capture: ok(sharded) — shard_map-wrapped grower: the grow
    # factory's own jit site carries the capture audit (meta rides as
    # a replicated ARGUMENT, PR 4), and this jit is factory-scoped.
    return jax.jit(sharded)


def make_feature_parallel_bundled_grower(cfg: WaveGrowerConfig,
                                         meta: FeatureMeta, mesh: Mesh,
                                         efb):
    """Feature-parallel over EFB BUNDLE columns: every device holds all
    rows and histograms only its slice of the bundle matrix, expands
    that slice to its members' [W, F, B, 3] columns (zeros elsewhere),
    finds its local best with the full-F split kernel (zero histograms
    can never win), and the global best is the usual
    all_gather + argmax. Closes the reference's
    FeatureParallelTreeLearner x EFB composition without requiring the
    bundle count to divide the device count (tail slices clamp and
    overlap; duplicated work, identical elections)."""
    from ..io.efb import expand_bundle_histogram
    D = mesh.devices.size
    mb, mo, nb_m, db_m, Bb, B_out, num_bundles = efb
    Bd = max(1, -(-num_bundles // D))
    mb = jnp.asarray(mb)
    mo = jnp.asarray(mo)
    nb_m = jnp.asarray(nb_m)
    db_m = jnp.asarray(db_m)
    meta_dev = FeatureMeta(*[jnp.asarray(a) for a in meta])

    def hist_fn(bins_t, g, h, leaf_ids, wave_leaves, gh_scale=None):
        i = jax.lax.axis_index(AXIS)
        start = jnp.minimum(i * Bd,
                            jnp.int32(max(num_bundles - Bd, 0)))
        local = jax.lax.dynamic_slice_in_dim(bins_t, start, Bd, 0)
        bh = wave_histogram(local, g, h, leaf_ids, wave_leaves,
                            num_bins=Bb, chunk=cfg.chunk,
                            use_pallas=cfg.use_pallas,
                            precision=cfg.precision, gh_scale=gh_scale)
        mb_loc = jnp.clip(mb - start, 0, Bd - 1)
        owned = (mb >= start) & (mb < start + Bd)
        full = expand_bundle_histogram(bh, mb_loc, mo, nb_m, db_m,
                                       B_out)
        return full * owned[None, :, None, None]

    def split_fn(hists, sg, sh, nd, fmask, can):
        res = jax.vmap(
            lambda hh, a, b, c, d: find_best_split(
                hh, a, b, c, fmask, meta_dev, cfg.hp, d)
        )(hists, sg, sh, nd, can)
        return sync_best_splits(res)

    grow = make_wave_grower(cfg, meta, hist_fn=hist_fn,
                            split_fn=split_fn, jit=False)
    sharded = _shard_map(
        grow, mesh=mesh,
        in_specs=(P(None, None), P(None), P(None), P(None), P(None)),
        out_specs=(P(), P()),
        check_vma=False)
    # jit-capture: ok(sharded) — shard_map-wrapped grower: the grow
    # factory's own jit site carries the capture audit (meta rides as
    # a replicated ARGUMENT, PR 4), and this jit is factory-scoped.
    return jax.jit(sharded)


def make_voting_parallel_grower(cfg: WaveGrowerConfig, meta: FeatureMeta,
                                mesh: Mesh, num_features: int,
                                top_k: int = 20, hist_fn=None):
    """Data-parallel with PV-Tree vote compression
    (VotingParallelTreeLearner, voting_parallel_tree_learner.cpp:166-360):
    per child, local top-k vote -> elect 2k global features -> psum only
    elected histograms."""
    D = mesh.devices.size
    k = max(1, min(top_k, num_features))
    k2 = min(2 * k, num_features)
    meta_dev = FeatureMeta(*[jnp.asarray(a) for a in meta])
    # local-vote gates and totals scaled to the per-device shard, like the
    # reference's local_config (voting_parallel_tree_learner.cpp:53-55)
    hp_vote = cfg.hp._replace(
        min_data_in_leaf=cfg.hp.min_data_in_leaf / D,
        min_sum_hessian_in_leaf=cfg.hp.min_sum_hessian_in_leaf / D)

    # LOCAL histograms — no psum; the election decides what is summed.
    # No hist_fn override: the default seams keep the fused
    # partition+histogram kernel live per shard (its output is exactly
    # the local wave histogram the election wants).
    def reduce_fn(x):
        return _psum_seam(x)

    def split_fn(hists, sg, sh, nd, fmask, can):
        # 1. local per-feature gains over the LOCAL histograms with the
        #    TRUE local leaf sumups (the reference votes with local
        #    smaller_leaf_splits_, voting_parallel_tree_learner.cpp:151-160)
        #    — every row lands in exactly one bin of feature 0, so the
        #    bin-sum of any one feature's local histogram IS the local
        #    leaf aggregate; gates stay num_machines-scaled (:53-55)
        sg_l = hists[:, 0, :, 0].sum(axis=-1)             # [M]
        sh_l = hists[:, 0, :, 1].sum(axis=-1)
        nd_l = hists[:, 0, :, 2].sum(axis=-1)
        local_gain = jax.vmap(
            lambda hh, a, b, c, d: best_gain_per_feature(
                hh, a, b, c, fmask, meta_dev, hp_vote, d)
        )(hists, sg_l, sh_l, nd_l, can)                   # [M, F]
        _, local_top = jax.lax.top_k(local_gain, k)       # [M, k]
        # 2. global vote: one-hot count of each device's top-k per child
        m = local_gain.shape[0]
        votes = jnp.zeros((m, num_features), jnp.float32)
        votes = votes.at[jnp.arange(m)[:, None], local_top].add(1.0)
        votes = _psum_seam(votes)
        # exact lexicographic (votes, summed-local-gain) election: rank
        # the gain sums 0..F-1 per child, then score = votes*F + rank —
        # deterministic, no saturating squash
        # gated features contribute 0 (not -inf: one device's gate must
        # not veto a feature other devices can still split)
        finite_gain = jnp.where(jnp.isfinite(local_gain), local_gain, 0.0)
        gain_sum = _psum_seam(finite_gain)
        order = jnp.argsort(gain_sum, axis=1)             # low -> high
        rank = jnp.zeros_like(order).at[
            jnp.arange(m)[:, None], order].set(
                jnp.arange(num_features, dtype=order.dtype)[None, :])
        score = votes * num_features + rank.astype(jnp.float32)
        _, elected = jax.lax.top_k(score, k2)             # [M, 2k]
        # 3. aggregate ONLY the elected features' histograms
        elected_hist = _psum_seam(
            jnp.take_along_axis(
                hists, elected[:, :, None, None], axis=1))
        meta_e = FeatureMeta(*[
            a if jnp.ndim(a) == 0 else a[elected]
            for a in meta_dev])                               # [M, 2k]
        # scalar-sentinel fields broadcast, per-slot fields map
        meta_axes = FeatureMeta(*[
            None if jnp.ndim(a) == 0 else 0 for a in meta_e])
        fmask_e = fmask[elected]
        res = jax.vmap(
            lambda hh, a, b, c, fm, me, d: find_best_split(
                hh, a, b, c, fm, me, cfg.hp, d),
            in_axes=(0, 0, 0, 0, 0, meta_axes, 0),
        )(elected_hist, sg, sh, nd, fmask_e, meta_e, can)
        return res._replace(
            feature=jnp.where(
                res.feature >= 0,
                jnp.take_along_axis(
                    elected, jnp.maximum(res.feature, 0)[:, None],
                    axis=1)[:, 0],
                -1))

    def row_offset_fn(n_local):
        # shard-invariant stochastic-rounding stream (see the
        # data-parallel learner)
        return jax.lax.axis_index(AXIS) * jnp.int32(n_local)

    grow = make_wave_grower(cfg, meta, hist_fn=hist_fn,
                            split_fn=split_fn,
                            reduce_fn=reduce_fn,
                            max_reduce_fn=lambda x: jax.lax.pmax(x, AXIS),
                            row_offset_fn=row_offset_fn, jit=False)
    sharded = _shard_map(
        grow, mesh=mesh,
        in_specs=(P(None, AXIS), P(AXIS), P(AXIS), P(AXIS), P(None)),
        out_specs=(P(), P(AXIS)),
        check_vma=False)
    # jit-capture: ok(sharded) — shard_map-wrapped grower: the grow
    # factory's own jit site carries the capture audit (meta rides as
    # a replicated ARGUMENT, PR 4), and this jit is factory-scoped.
    return jax.jit(sharded)


def make_grower_for_mode(mode: str, cfg: WaveGrowerConfig,
                         meta: FeatureMeta, mesh: Optional[Mesh],
                         num_features: int, top_k: int = 20,
                         hist_fn=None, efb_feature=None):
    """Factory matching TreeLearner::CreateTreeLearner
    (src/treelearner/tree_learner.cpp:9-33) — {serial, feature, data,
    voting} on the tpu device type. ``hist_fn`` overrides the serial
    histogram seam (EFB bundle expansion, models/gbdt.py);
    ``efb_feature`` = (member_bundle, member_offset, num_bin,
    default_bin, B_bundle, B_out, num_bundles) routes feature-parallel
    over bundle columns instead."""
    if mode == "serial" or mesh is None or mesh.devices.size == 1:
        return make_wave_grower(cfg, meta, hist_fn=hist_fn)
    if mode == "data":
        return make_data_parallel_grower(cfg, meta, mesh, hist_fn=hist_fn)
    if mode == "feature":
        if efb_feature is not None:
            return make_feature_parallel_bundled_grower(
                cfg, meta, mesh, efb_feature)
        if hist_fn is not None:
            raise ValueError("feature-parallel does not compose with an "
                             "injected histogram seam (EFB bundles)")
        return make_feature_parallel_grower(cfg, meta, mesh, num_features)
    if mode == "voting":
        return make_voting_parallel_grower(cfg, meta, mesh, num_features,
                                           top_k, hist_fn=hist_fn)
    raise ValueError(f"Unknown tree_learner {mode!r}")
