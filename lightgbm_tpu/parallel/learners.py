"""Distributed tree learners over a JAX device mesh.

TPU-native counterparts of the reference's three parallel tree learners
(reference: src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp and
the socket/MPI collective layer they ride on, src/network/network.cpp).
Instead of hand-rolled Bruck/recursive-halving collectives over TCP, the
whole tree build runs as ONE ``shard_map`` program over a
``jax.sharding.Mesh`` and the three communication points lower onto XLA
collectives over ICI/DCN:

  reference                              here
  ---------------------------------     ------------------------------
  histogram ReduceScatter                ``lax.psum`` of leaf histograms
    (data_parallel_tree_learner.cpp:147)   (data parallel)
  best-split AllReduce w/ max-gain       ``lax.all_gather`` of the
    reducer (parallel_tree_learner.h:183)  SplitResult tuple + argmax
  top-k vote Allgather                   ``lax.all_gather`` of local
    (voting_parallel_tree_learner.cpp:342) top-k ids + psum vote count

Modes (tree_learner config, config.h tree_learner):
- data:    rows sharded across devices; per-leaf histograms summed with
           ``psum``; every device finds the same global best split.
- feature: every device holds ALL rows (like the reference, where each
           worker has the full data, feature_parallel_tree_learner.cpp:31);
           each device builds histograms only for its own feature slice,
           finds its local best, and the global best is ``all_gather`` +
           argmax. No row movement at split time.
- voting:  data-parallel with PV-Tree communication compression: each
           device votes its local top-k features, the global top-2k by
           vote count are elected, and ONLY those features' histograms
           are summed (``psum`` of a [2k, B, 3] slice instead of the
           full [F, B, 3]).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.grower import GrowerConfig, make_tree_grower
from ..ops.histogram import build_histogram
from ..ops.split import (FeatureMeta, SplitResult, best_gain_per_feature,
                         find_best_split)

AXIS = "workers"


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    from ..utils.device import get_devices
    devs = get_devices()
    n = len(devs) if num_devices is None else min(num_devices, len(devs))
    return Mesh(np.asarray(devs[:n]), (AXIS,))


def sync_best_split(res: SplitResult) -> SplitResult:
    """Cross-device argmax of per-device best splits — the analog of
    SyncUpGlobalBestSplit (parallel_tree_learner.h:183-207)."""
    gathered = jax.lax.all_gather(res, AXIS)      # pytree of [D, ...]
    best = jnp.argmax(gathered.gain)
    return SplitResult(*[leaf[best] for leaf in gathered])


def _slice_meta(meta: FeatureMeta, start, size: int) -> FeatureMeta:
    return FeatureMeta(*[
        jax.lax.dynamic_slice_in_dim(jnp.asarray(a), start, size, 0)
        for a in meta])


def make_data_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                              mesh: Mesh):
    """Rows sharded over the mesh; histograms psummed.

    (DataParallelTreeLearner semantics; the reference reduce-scatters so
    each worker reduces a feature subset — with XLA the psum IS the
    reduce+broadcast and the compiler picks the wire algorithm.)
    """
    B = cfg.num_bins

    def hist_fn(bins, w):
        local = build_histogram(bins, w, num_bins=B, chunk=cfg.chunk)
        return jax.lax.psum(local, AXIS)

    def reduce_fn(x):
        return jax.lax.psum(x, AXIS)

    grow = make_tree_grower(cfg, meta, hist_fn=hist_fn,
                            reduce_fn=reduce_fn, jit=False)
    sharded = jax.shard_map(
        grow, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS), P(AXIS), P(AXIS), P(None)),
        out_specs=(P(), P(AXIS)),
        check_vma=False)
    return jax.jit(sharded)


def make_feature_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                                 mesh: Mesh, num_features: int):
    """Every device holds all rows; feature slice per device for the
    histogram/split work (FeatureParallelTreeLearner semantics)."""
    B = cfg.num_bins
    D = mesh.devices.size
    if num_features % D != 0:
        raise ValueError("feature-parallel requires padded features")
    Fd = num_features // D

    def hist_fn(bins, w):
        i = jax.lax.axis_index(AXIS)
        local_bins = jax.lax.dynamic_slice_in_dim(bins, i * Fd, Fd, 1)
        return build_histogram(local_bins, w, num_bins=B, chunk=cfg.chunk)

    def split_fn(hist, sg, sh, nd, fmask, can):
        i = jax.lax.axis_index(AXIS)
        meta_l = _slice_meta(meta, i * Fd, Fd)
        fmask_l = jax.lax.dynamic_slice_in_dim(fmask, i * Fd, Fd, 0)
        res = find_best_split(hist, sg, sh, nd, fmask_l, meta_l,
                              cfg.hp, can)
        res = res._replace(
            feature=jnp.where(res.feature >= 0, res.feature + i * Fd, -1))
        return sync_best_split(res)

    grow = make_tree_grower(cfg, meta, hist_fn=hist_fn, split_fn=split_fn,
                            jit=False)
    sharded = jax.shard_map(
        grow, mesh=mesh,
        in_specs=(P(None, None), P(None), P(None), P(None), P(None)),
        out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(sharded)


def make_voting_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                                mesh: Mesh, num_features: int,
                                top_k: int = 20):
    """Data-parallel with PV-Tree vote compression
    (VotingParallelTreeLearner, voting_parallel_tree_learner.cpp:166-360):
    local top-k vote -> elect 2k global features -> psum only elected
    histograms."""
    B = cfg.num_bins
    D = mesh.devices.size
    k = max(1, min(top_k, num_features))
    k2 = min(2 * k, num_features)
    meta_dev = FeatureMeta(*[jnp.asarray(a) for a in meta])
    # local-vote gates and totals scaled to the per-device shard, like the
    # reference's local_config (voting_parallel_tree_learner.cpp:53-55)
    hp_vote = cfg.hp._replace(
        min_data_in_leaf=cfg.hp.min_data_in_leaf / D,
        min_sum_hessian_in_leaf=cfg.hp.min_sum_hessian_in_leaf / D)

    def hist_fn(bins, w):
        # LOCAL histograms — no psum here; election decides what is summed
        return build_histogram(bins, w, num_bins=B, chunk=cfg.chunk)

    def reduce_fn(x):
        return jax.lax.psum(x, AXIS)

    def split_fn(hist, sg, sh, nd, fmask, can):
        # 1. local per-feature gains over the LOCAL histogram with
        #    per-shard totals and gates (the reference votes with local
        #    leaf sumups and num_machines-scaled thresholds,
        #    voting_parallel_tree_learner.cpp:53-55,151-160)
        local_gain = best_gain_per_feature(hist, sg / D, sh / D, nd / D,
                                           fmask, meta_dev, hp_vote, can)
        _, local_top = jax.lax.top_k(local_gain, k)
        # 2. global vote: one-hot count of each device's top-k
        votes = jnp.zeros(num_features, jnp.float32).at[local_top].add(1.0)
        votes = jax.lax.psum(votes, AXIS)
        # deterministic tie-break by summed local gain
        finite_gain = jnp.where(jnp.isfinite(local_gain), local_gain, 0.0)
        gain_sum = jax.lax.psum(finite_gain, AXIS)
        score = votes + 1e-6 * jax.nn.sigmoid(gain_sum)
        _, elected = jax.lax.top_k(score, k2)        # [2k] global ids
        # 3. aggregate ONLY the elected features' histograms
        elected_hist = jax.lax.psum(hist[elected], AXIS)   # [2k, B, 3]
        meta_e = FeatureMeta(*[a[elected] for a in meta_dev])
        fmask_e = fmask[elected]
        res = find_best_split(elected_hist, sg, sh, nd, fmask_e, meta_e,
                              cfg.hp, can)
        return res._replace(
            feature=jnp.where(res.feature >= 0, elected[res.feature], -1))

    grow = make_tree_grower(cfg, meta, hist_fn=hist_fn, split_fn=split_fn,
                            reduce_fn=reduce_fn, jit=False)
    sharded = jax.shard_map(
        grow, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS), P(AXIS), P(AXIS), P(None)),
        out_specs=(P(), P(AXIS)),
        check_vma=False)
    return jax.jit(sharded)


def make_grower_for_mode(mode: str, cfg: GrowerConfig, meta: FeatureMeta,
                         mesh: Optional[Mesh], num_features: int,
                         top_k: int = 20):
    """Factory matching TreeLearner::CreateTreeLearner
    (src/treelearner/tree_learner.cpp:9-33) — {serial, feature, data,
    voting} on the tpu device type."""
    if mode == "serial" or mesh is None or mesh.devices.size == 1:
        return make_tree_grower(cfg, meta)
    if mode == "data":
        return make_data_parallel_grower(cfg, meta, mesh)
    if mode == "feature":
        return make_feature_parallel_grower(cfg, meta, mesh, num_features)
    if mode == "voting":
        return make_voting_parallel_grower(cfg, meta, mesh, num_features,
                                           top_k)
    raise ValueError(f"Unknown tree_learner {mode!r}")
