"""Windowed cache-admission training driver (the fork's application).

TPU-native counterpart of the fork's actual main program
(reference: src/test.cpp:39-341): a learning-relaxed-Belady loop that,
per fixed-size window of (id, size, cost) cache requests,

1. labels each request by an OPT-like volume ranking (calculateOPT,
   test.cpp:97-121): requests whose next-use volume fits the cache's
   byte-window budget get toCache = 1;
2. derives features (deriveFeatures, test.cpp:124-208): up to 50
   inter-arrival gaps, log2 object size, log2 available cache bytes,
   and the request cost, as a CSR matrix;
3. trains a FRESH booster on the window's sample with the fork's fixed
   parameter set (trainModel, test.cpp:240-298);
4. evaluates the previous booster on the next window, reporting
   false-positive / false-negative rates at ``cutoff`` plus the OPT
   object/byte hit ratios (evaluateModel, test.cpp:210-238).

Pipelined retrain-while-serve (``tpu_lrb_pipeline``, default on): the
reference loop is strictly sequential — every window blocks the serving
path for derive -> train -> evaluate. Here window K's training runs on a
background trainer thread while the main thread keeps ingesting window
K+1's requests, OPT-labeling them and deriving their features; the
finished model is published with an atomic swap (pre-warmed through
``GBDT.prepare_serving``), and a failed/degraded window publishes
nothing — the swap simply never happens and serving continues on the
previous model. The trainer is joined at the next window boundary
BEFORE that window's evaluation, so per-window results are
field-for-field identical to the sequential loop (model swaps take
effect at window boundaries either way). The per-request hot loops
(feature derivation's gap walk, the OPT admission scan) are vectorized
group-by-object numpy — the scalar reference transliterations are kept
as ``*_scalar`` test oracles, bit-identical by tests/test_lrb_pipeline.

Run: ``python -m lightgbm_tpu.lrb <trace> <cacheSize> <windowSize>
<sampleSize> <cutoff> <sampling> [result_file]`` — the same argv as the
reference binary. ``trace`` rows: ``seq id size cost`` (or
``id size cost``; a synthetic trace generator is included for testing).
"""
from __future__ import annotations

import concurrent.futures
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import capi
from .obs import export as obs_export
from .obs import flight as obs_flight
from .obs import registry as obs
from .obs import reqlog
from .obs import slo as obs_slo
from .obs import trace
from .utils import faults, log, retry
from .analysis import lockorder

HISTFEATURES = 50            # test.cpp:16
NUM_FEATURES = HISTFEATURES + 3

TRAIN_PARAMS = {             # test.cpp:67-87
    "boosting": "gbdt",
    "objective": "binary",
    "metric": "binary_logloss,auc",
    "metric_freq": "1",
    "is_provide_training_metric": "true",
    "max_bin": "255",
    "num_iterations": "50",
    "learning_rate": "0.1",
    "num_leaves": "31",
    "tree_learner": "serial",
    "feature_fraction": "0.8",
    "bagging_freq": "5",
    "bagging_fraction": "0.8",
    "min_data_in_leaf": "50",
    "min_sum_hessian_in_leaf": "5.0",
    "verbose": "-1",
}


class WindowBudgetExceeded(RuntimeError):
    """A window's training ran past the per-window wall budget — the
    degrade path treats it like any other window-train failure
    (serving continues on the previous model), and retry classifies
    it non-transient (re-running the same window would blow the same
    budget)."""


def _degrade_label(reason: Optional[str]) -> str:
    """Classify a degrade reason string into a small stable label set
    — the ``lrb/degraded_reason/<label>`` counter family (Prometheus
    needs bounded cardinality, the flight recorder needs *why*, not
    just *that*). The raw reason string still rides the result record
    and the wide event."""
    if not reason or reason == "degenerate_labels":
        return "degenerate_labels"
    head = reason.split(":", 1)[0].strip()
    if head == "WindowBudgetExceeded":
        return "budget"
    if head == "InjectedFault":
        return ("injected_fault_transient" if "action=transient" in reason
                else "injected_fault")
    import re as _re
    return _re.sub(r"[^A-Za-z0-9_]", "_", head) or "error"


class Window:
    """One window's trace + OPT bookkeeping (test.cpp globals)."""

    def __init__(self):
        self.ids: List[int] = []
        self.sizes: List[int] = []
        self.costs: List[float] = []
        self.to_cache: Optional[np.ndarray] = None
        self.has_next: List[bool] = []
        self.volume: List[int] = []
        self.byte_sum = 0
        self._feat_ctx = None   # sampling-independent derive arrays


class LrbDriver:
    """The windowed retraining loop (test.cpp:300-341 processRequest),
    pipelined: training runs behind the serving path (see module
    docstring)."""

    def __init__(self, cache_size: int, window_size: int,
                 sample_size: int, cutoff: float, sampling: int,
                 result_file=sys.stdout, seed: int = 0,
                 extra_params: Optional[dict] = None,
                 serve_batch: int = 64,
                 window_budget_s: Optional[float] = None,
                 serve_daemon: bool = False):
        self.cache_size = cache_size
        self.window_size = window_size
        self.sample_size = sample_size
        self.cutoff = cutoff
        self.sampling = sampling
        self.out = result_file
        self.rng = np.random.default_rng(seed)
        # per-window training params: the reference's fixed set plus
        # operator overrides (telemetry knobs, tpu_ingest for tests);
        # the telemetry daemons start HERE so window spans and live
        # metrics cover the whole loop, not just the boosters
        self.params = dict(TRAIN_PARAMS)
        self.params.update({k: str(v) for k, v in
                            (extra_params or {}).items()})
        trace.ensure_from_config(self.params)
        obs_export.ensure_from_config(self.params)
        # serving observability (the PR-12 layer): request-scoped wide
        # events, the SLO/error-budget engine the exporter evaluates,
        # and the always-on flight recorder — armed HERE so window 1's
        # requests already carry ids and a window-1 failure already
        # dumps a postmortem bundle
        reqlog.ensure_from_config(self.params)
        obs_slo.ensure_from_config(self.params)
        obs_flight.ensure_from_config(self.params)
        # fault-injection drills armed HERE so pre-booster points
        # (dataset ingest) are covered from window 1 (idempotent:
        # every window's booster init re-arms the same spec)
        if self.params.get("tpu_faults"):
            faults.configure(self.params["tpu_faults"],
                             int(self.params.get("tpu_fault_seed", 0)))
        # driver-OWNED window-wall instrument: this run's quantile
        # summary must not mix in an earlier driver's windows (the
        # process-global twin below feeds the live exporter, which IS
        # cumulative by design, like every registry counter)
        self._wall_hist = obs.latency_histogram(
            "lrb/window_wall_s", obs.MetricsRegistry())
        # serving-path instruments: every evaluation scores the
        # window's requests against the PREVIOUS window's model in
        # serve-bucket micro-batches (ops/predict_cache.py).
        # serve_latency is PER-REQUEST — a k-row micro-batch whose wall
        # is dt contributes k request latencies of dt (every request in
        # it waited the batch out), so p99 means what an operator
        # thinks it means; serve_batch keeps the per-CALL wall.
        # Driver-owned for the same reason as _wall_hist; the global
        # twins feed the live exporter.
        self.serve_batch = max(int(serve_batch), 1)
        self._serve_hist = obs.latency_histogram(
            "lrb/serve_latency_s", obs.MetricsRegistry())
        self._serve_batch_hist = obs.latency_histogram(
            "lrb/serve_batch_s", obs.MetricsRegistry())
        # degrade-don't-die bookkeeping: a window whose training fails
        # (exception, injected fault, or the per-window wall budget)
        # is marked degraded and serving continues on the previous
        # model; the staleness gauge counts windows since the last
        # successful retrain — the number an operator alarms on
        self.window_budget_s = (None if window_budget_s is None
                                else float(window_budget_s))
        self._windows_since_train = 0
        self._trained_window = 0      # index of the serving model's window
        self._retry_policy = retry.RetryPolicy(
            attempts=int(self.params.get("tpu_retry_attempts", 4)),
            seed=seed)
        # retrain-while-serve pipeline (tpu_lrb_pipeline: -1 auto=on /
        # 0 sequential / 1 on): one trainer thread, one window in
        # flight, atomic publish under the swap lock
        self.pipelined = int(self.params.get("tpu_lrb_pipeline",
                                             -1)) != 0
        self._swap_lock = lockorder.named_lock("lrb._swap_lock")
        # serializes the pending-window takeover: results/booster
        # drain from any thread, and two concurrent drains must not
        # both run the join body (double-counted staleness, duplicate
        # result lines)
        self._join_lock = lockorder.named_lock("lrb._join_lock")
        self._serving = None          # guarded-by: _swap_lock
        self._pending: Optional[dict] = None   # guarded-by: _join_lock
        self._executor: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._eval_executor: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        # test seam for liveness drills: when a test installs an Event
        # as _train_gate, the trainer signals _train_started and parks
        # on the gate — the main thread can then prove serving stays
        # live while a training is provably mid-window
        self._train_gate: Optional[threading.Event] = None
        self._train_started = threading.Event()
        self._ring = self._make_ring()
        self.window = Window()
        self.last_seen: Dict[Tuple[int, int], int] = {}
        # per-id inter-arrival history carried ACROSS windows is reset
        # with the window in the reference (statistics is local to
        # deriveFeatures) — mirrored here
        self.window_index = 0
        self._results: List[dict] = []
        self.trace_lines_skipped = 0
        # flight-recorder bundles are process-global; remember where
        # the dump list stood at init so ``flight_dumps`` reports only
        # THIS run's bundles (whether the fault trigger or the
        # degraded-window trigger produced them — the rate limiter
        # coalesces one incident into one bundle)
        self._flight_dumps_at_init = len(obs_flight.dump_paths())
        # --serve-daemon: score every window's requests through the
        # fleet scoring daemon (serve/) over localhost HTTP instead of
        # in-process capi predict — each published model is registered
        # as a new version of the one "lrb" tenant (warm atomic swap
        # on the daemon side). Degrade, don't die: a daemon that
        # cannot bind (or a request that fails past the retry policy)
        # falls back to in-process scoring.
        self._fleet_daemon = None
        self._fleet_client = None
        self._fleet_warned = 0
        if serve_daemon:
            from .serve import FleetClient
            from .serve.daemon import ScoringDaemon
            try:
                self._fleet_daemon = ScoringDaemon.from_config(
                    self.params).start()
                self._fleet_client = FleetClient(self._fleet_daemon.url)
            except RuntimeError as e:
                log.warning("serve-daemon unavailable (%s); scoring "
                            "in-process", e)

    def _make_ring(self):
        """Device-resident ingest chunk ring (io/ingest.py ChunkRing)
        for the per-window training matrix — every window's chunk
        slots reuse the previous window's resident device buffers and
        upload only the bucketed live-row region. tpu_lrb_ring: -1
        auto (on when the streamed device ingest path is active), 0
        off, 1 force."""
        rk = int(self.params.get("tpu_lrb_ring", -1))
        if rk == 0:
            return None
        from .io import ingest
        if rk == -1:
            from .config import Config
            cfg = Config()
            cfg.set({k: str(v) for k, v in self.params.items()})
            if not ingest.ingest_enabled(cfg):
                return None
        return ingest.ChunkRing()

    # -- published-model access ----------------------------------------------

    @property
    def booster(self):
        """The serving model's booster handle (None until a window
        trains successfully). Reading it drains any in-flight window
        training first, so callers always observe the final state of
        every completed window."""
        self.drain()
        with self._swap_lock:
            return self._serving

    @booster.setter
    def booster(self, handle) -> None:
        with self._swap_lock:
            self._serving = handle

    @property
    def results(self) -> List[dict]:
        """Per-window result records; drains the pipeline so the last
        window's training outcome is folded in."""
        self.drain()
        return self._results

    def predict_live(self, X: np.ndarray) -> Optional[np.ndarray]:
        """Score a request batch against the CURRENTLY published model
        — the live serving entry a request stream hits while the
        trainer thread may be mid-window. Thread-safe: the handle is
        snapshotted under the swap lock and a concurrent publish never
        mutates an already-published booster (every window trains a
        fresh one). None before the first successful window.

        Request-scoped (obs/reqlog.py): every call is issued a
        monotonic request id, carried through the predict stack in the
        thread-local context (trace spans and the serve-bucket seam
        tag themselves with it), and closed with ONE wide event."""
        with self._swap_lock:
            h = self._serving
        if h is None:
            return None
        rid = reqlog.next_request_id()
        t0 = time.monotonic()
        with reqlog.request(rid, window=self.window_index) as rctx, \
                trace.span("serve/request", cat="serve",
                           args={"req_id": rid,
                                 "window": self.window_index}):
            out = np.asarray(capi.LGBM_BoosterPredictForMat(
                h, X, predict_type=capi.C_API_PREDICT_NORMAL))
        reqlog.record(
            "request", req_id=rid, path="lrb/live",
            window=self.window_index, rows=int(len(X)),
            latency_ms=round(1e3 * (time.monotonic() - t0), 3),
            # the handle's OWN stamp (_train_model): a mid-window
            # publish serves the new model before _trained_window
            # advances at the boundary join — attribution follows the
            # handle actually scored against
            model_window=getattr(h, "_lrb_window",
                                 self._trained_window),
            serve_bucket=rctx.bucket,
            staleness_windows=self._windows_since_train)
        return out

    def training_in_flight(self) -> bool:
        """True while the trainer thread holds a window (the
        during-retrain tag of the streaming bench)."""
        p = self._pending
        return bool(p is not None and not p["future"].done())

    # -- request ingestion ---------------------------------------------------

    def process_request(self, seq: int, obj_id: int, size: int,
                        cost: float) -> None:
        w = self.window
        idx = (seq - 1) % self.window_size
        key = (obj_id, size)
        if size > 0 and key in self.last_seen:
            prev = self.last_seen[key]
            w.has_next[prev] = True
            w.volume[prev] = (idx - prev) * size
        w.byte_sum += size
        self.last_seen[key] = idx
        w.ids.append(obj_id)
        w.sizes.append(size)
        w.costs.append(cost)
        w.has_next.append(False)
        w.volume.append(np.iinfo(np.int64).max)
        if seq % self.window_size == 0:
            self._process_window()

    def _process_window(self) -> None:
        self.window_index += 1
        if self.pipelined:
            self._process_window_pipelined()
        else:
            self._process_window_sequential()
        self.window = Window()
        self.last_seen.clear()

    def _process_window_sequential(self) -> None:
        """The reference's strictly serial boundary: evaluate ->
        derive -> train, everything on the calling thread."""
        t_window = time.monotonic()
        wi = {"window": self.window_index}
        rec = {"window": self.window_index}
        with trace.span("window", cat="window", args=wi):
            self._calculate_opt()
            # per-window phase table: derive / train / evaluate wall
            # seconds land in the results AND as spans on the trace
            # timeline (evaluate derives the NEXT window's features on
            # the previous model — the serving half of the loop)
            if self._serving is not None:
                t0 = time.monotonic()
                with trace.span("lrb/evaluate", cat="window", args=wi):
                    labels, X = self._derive_features(0)
                    rec.update(self._score_window(
                        labels, X, window=self.window_index))
                rec["evaluate_s"] = round(time.monotonic() - t0, 3)
            t0 = time.monotonic()
            with trace.span("lrb/derive", cat="window", args=wi):
                labels, X = self._derive_features(self.sampling)
            rec["derive_s"] = round(time.monotonic() - t0, 3)
            rec["train_rows"] = len(labels)
            with trace.span("lrb/train", cat="window", args=wi):
                stats, handle, reason = self._attempt_window_train(
                    labels, X, self.window_index)
                if handle is not None:
                    self.booster = handle
                    self._daemon_register(handle, self.window_index)
                self._apply_train_outcome(rec, stats, reason)
            rec.update(self._opt_ratios())
        self._results.append(rec)
        self._finish_window(rec, time.monotonic() - t_window)

    def _process_window_pipelined(self) -> None:
        """The retrain-while-serve boundary. Everything that does NOT
        need the incoming model runs while the PREVIOUS window may
        still be training on the trainer thread: OPT labels, the
        train-sample features and the eval batch's features (all
        model-independent). The join lands right before the model
        snapshot, so the snapshot is exactly the model the sequential
        loop would evaluate against; THIS window's training is then
        handed to the trainer and the evaluation — the expensive
        serving loop — runs over the trainer's shoulder against the
        snapshot (a mid-scoring publish of this window's own model
        cannot leak into its evaluation). Field-for-field, the record
        matches the sequential loop's."""
        t_window = time.monotonic()
        wi = {"window": self.window_index}
        rec = {"window": self.window_index}
        with trace.span("window", cat="window", args=wi):
            self._calculate_opt()
            t0 = time.monotonic()
            with trace.span("lrb/derive", cat="window", args=wi):
                labels, X = self._derive_features(self.sampling)
            rec["derive_s"] = round(time.monotonic() - t0, 3)
            ev = None
            ev_derive_s = 0.0
            if self._serving is not None or self._pending is not None:
                # the eval batch's features are model-independent —
                # derive them NOW, over the trainer's shoulder
                t0 = time.monotonic()
                with trace.span("lrb/derive_eval", cat="window",
                                args=wi):
                    ev = self._derive_features(0)
                ev_derive_s = time.monotonic() - t0
            self._join_pending()
            with self._swap_lock:
                h = self._serving       # swap-at-boundary snapshot
            rec["train_rows"] = len(labels)
            rec.update(self._opt_ratios())
            # build the COMPLETE pending record — training future AND
            # eval future — before publishing it: a drain() racing in
            # from another thread (the results/booster properties)
            # between a train-only publish and a later eval attach
            # would join the window without its evaluation and the
            # record would silently lose its fp/fn/serve fields
            pending = self._submit_train(labels, X, rec, t_window)
            try:
                if h is not None and ev is not None:
                    # the evaluation — the expensive serving loop —
                    # runs on its own server thread, concurrent with
                    # BOTH this window's training and the next
                    # window's arrivals; the join-time snapshot pins
                    # the model, so the result is exactly the
                    # sequential loop's
                    pending["eval"] = self._submit_eval(
                        ev, h, ev_derive_s, wi)
            finally:
                # publish even if the eval submit failed — the
                # trainer future must stay joinable
                with self._join_lock:
                    self._pending = pending
        with self._join_lock:
            if self._pending is not None:
                self._pending["boundary_end"] = time.monotonic()
        self._results.append(rec)

    # -- OPT labeling (test.cpp:97-121) --------------------------------------

    def _calculate_opt(self) -> None:
        """Vectorized admission scan: stable argsort by next-use
        volume + exclusive cumsum over the would-be-admitted volumes.
        The scalar loop breaks at the first position whose running
        volume exceeds the budget and only admitted items grow it, so
        (the cumsum being monotone) admission is exactly ``has_next &
        (exclusive_cumsum <= budget)`` — bit-identical to
        ``_calculate_opt_scalar`` (the early cutoff is the mask; no
        per-item Python loop)."""
        w = self.window
        n = len(w.ids)
        volume = np.asarray(w.volume, np.int64)
        has_next = np.asarray(w.has_next, bool)
        sizes = np.asarray(w.sizes, np.int64)
        order = np.argsort(volume, kind="stable")
        cache_volume = self.cache_size * self.window_size
        hn_o = has_next[order]
        vol_o = np.where(hn_o, volume[order], 0)
        cum_before = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(vol_o)[:-1]])
        admit = hn_o & (cum_before <= cache_volume)
        to_cache = np.zeros(n, bool)
        to_cache[order[admit]] = True
        self._opt_hits = int(admit.sum())
        self._opt_byte_hits = int(sizes[order][admit].sum())
        w.to_cache = to_cache
        w._feat_ctx = None          # labels changed: derive ctx stale

    def _calculate_opt_scalar(self) -> None:
        """Reference transliteration (test.cpp:97-121) — kept as the
        bit-parity oracle for ``_calculate_opt``."""
        w = self.window
        n = len(w.ids)
        volume = np.asarray(w.volume, np.int64)
        has_next = np.asarray(w.has_next, bool)
        order = np.argsort(volume, kind="stable")
        cache_volume = self.cache_size * self.window_size
        to_cache = np.zeros(n, bool)
        cur = 0
        self._opt_hits = 0
        self._opt_byte_hits = 0
        sizes = np.asarray(w.sizes, np.int64)
        for i in order:
            if cur > cache_volume:
                break
            if has_next[i]:
                to_cache[i] = True
                self._opt_hits += 1
                self._opt_byte_hits += int(sizes[i])
                cur += int(volume[i])
        w.to_cache = to_cache
        w._feat_ctx = None          # labels changed: derive ctx stale

    def _opt_ratios(self) -> dict:
        w = self.window
        return {
            "opt_obj_hit_ratio": round(self._opt_hits
                                       / self.window_size, 4),
            "opt_byte_hit_ratio": round(self._opt_byte_hits
                                        / max(w.byte_sum, 1), 4),
        }

    # -- feature derivation (test.cpp:124-208) -------------------------------

    def _derive_features(self, sampling: int):
        """Vectorized feature derivation — bit-identical to
        ``_derive_features_scalar`` (the reference transliteration
        below, kept as the test oracle).

        The scalar loop's per-request deque walk is a group-by-object
        gap computation: a stable argsort by object id keeps arrival
        order within each group, so consecutive sorted slots of one
        object give the inter-arrival gaps, and request i's feature j
        is simply the group's (k-j)-th gap (k = i's occurrence index,
        capped at HISTFEATURES most-recent). The cache-occupancy
        column follows from the observation that an object is in
        cache after request r iff to_cache[r]: inserts are 0->1 label
        transitions (debit the size at the transition), evictions are
        1->0 transitions (credit the size recorded at the RUN'S first
        1 — the insertion), and available-bytes is the exclusive
        cumsum of those deltas in arrival order."""
        w = self.window
        n = len(w.ids)
        if n == 0:
            return (np.zeros(0, np.float32),
                    np.zeros((0, NUM_FEATURES), np.float64))
        # sampling flags: ONE rng draw per request in arrival order,
        # exactly the scalar loop's stream (Generator.random(n) is the
        # same double sequence as n scalar draws)
        if sampling == 1:
            flag = np.arange(n) >= (self.window_size - self.sample_size)
        elif sampling == 2:
            flag = self.rng.random(n) < (self.sample_size
                                         / self.window_size)
        else:
            flag = np.ones(n, bool)
        ids, sizes, costs, to_cache, gaps, inv, occ, avail = \
            self._derive_ctx()
        rows_idx = np.flatnonzero(flag)
        s = inv[rows_idx]
        k = np.minimum(occ[s], HISTFEATURES)
        J = np.arange(HISTFEATURES)
        valid = J[None, :] < k[:, None]
        src = np.clip(s[:, None] - J[None, :], 0, n - 1)
        feat = np.zeros((len(rows_idx), NUM_FEATURES), np.float64)
        feat[:, :HISTFEATURES] = np.where(valid, gaps[src], 0)
        feat[:, HISTFEATURES] = np.round(
            100.0 * np.log2(np.maximum(sizes[rows_idx], 1)))
        av = avail[rows_idx]
        feat[:, HISTFEATURES + 1] = np.where(
            av <= 0, 0.0,
            np.round(100.0 * np.log2(np.maximum(av, 1))))
        feat[:, HISTFEATURES + 2] = costs[rows_idx]
        return to_cache[rows_idx].astype(np.float32), feat

    def _derive_ctx(self):
        """The sampling-independent half of feature derivation —
        per-window group/gap/occupancy arrays, computed ONCE per
        window (the boundary derives twice: the training sample and
        the eval batch differ only in the final flag slice).
        Invalidated by ``_calculate_opt`` (labels feed the occupancy
        deltas) and implicitly by the per-boundary Window reset."""
        w = self.window
        ctx = getattr(w, "_feat_ctx", None)
        if ctx is not None:
            return ctx
        n = len(w.ids)
        ids = np.asarray(w.ids, np.int64)
        sizes = np.asarray(w.sizes, np.int64)
        costs = np.asarray(w.costs, np.float64)
        to_cache = np.asarray(w.to_cache, bool)

        order = np.argsort(ids, kind="stable")
        sid = ids[order]
        new_grp = np.concatenate([[True], sid[1:] != sid[:-1]])
        slot = np.arange(n)
        starts = np.flatnonzero(new_grp)
        grp_start = starts[np.cumsum(new_grp) - 1]
        occ = slot - grp_start              # occurrence index k
        # gap at sorted slot s (k >= 1): arrival-index difference of
        # consecutive occurrences of the same object
        gaps = np.zeros(n, np.int64)
        cont = ~new_grp
        gaps[cont] = order[cont] - order[np.flatnonzero(cont) - 1]
        inv = np.empty(n, np.int64)
        inv[order] = slot                   # arrival row -> sorted slot

        # cache-occupancy deltas (see _derive_features docstring); the
        # run-start insert a 1->0 eviction credits is found with a
        # global maximum.accumulate over insert slots — safe across
        # group boundaries because an eviction's own group always
        # contains a nearer insert (prev label 1 needs one)
        lo = to_cache[order]
        prev_l = np.concatenate([[False], lo[:-1]]) & cont
        insert = lo & ~prev_l
        evict = (~lo) & prev_l
        so = sizes[order]
        last_ins = np.maximum.accumulate(np.where(insert, slot, -1))
        delta_o = np.zeros(n, np.int64)
        delta_o[insert] = -so[insert]
        delta_o[evict] = so[last_ins[evict]]
        delta = np.zeros(n, np.int64)
        delta[order] = delta_o
        avail = self.cache_size + np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(delta)[:-1]])
        w._feat_ctx = ctx = (ids, sizes, costs, to_cache, gaps, inv,
                             occ, avail)
        return ctx

    def _derive_features_scalar(self, sampling: int):
        """Reference transliteration (test.cpp:124-208) — kept as the
        bit-parity oracle for the vectorized ``_derive_features``."""
        w = self.window
        n = len(w.ids)
        cache_avail = self.cache_size
        history: Dict[int, deque] = {}
        cache: Dict[int, int] = {}
        labels: List[float] = []
        rows: List[np.ndarray] = []
        for i in range(n):
            q = history.setdefault(w.ids[i], deque())
            if len(q) > HISTFEATURES:
                q.pop()
            flag = True
            if sampling == 1:
                flag = i >= (self.window_size - self.sample_size)
            elif sampling == 2:
                flag = self.rng.random() < self.sample_size \
                    / self.window_size
            if flag:
                labels.append(1.0 if w.to_cache[i] else 0.0)
                feat = np.zeros(NUM_FEATURES, np.float64)
                last = i
                for j, t in enumerate(q):
                    feat[j] = last - t
                    last = t
                feat[HISTFEATURES] = round(
                    100.0 * np.log2(max(w.sizes[i], 1)))
                feat[HISTFEATURES + 1] = (
                    0.0 if cache_avail <= 0
                    else round(100.0 * np.log2(cache_avail)))
                feat[HISTFEATURES + 2] = w.costs[i]
                rows.append(feat)
            # cache-occupancy bookkeeping (test.cpp:180-199)
            oid = w.ids[i]
            if oid not in cache:
                if w.to_cache[i]:
                    cache_avail -= w.sizes[i]
                    cache[oid] = w.sizes[i]
            else:
                if not w.to_cache[i]:
                    cache_avail += cache.pop(oid)
            q.appendleft(i)
        X = (np.stack(rows) if rows
             else np.zeros((0, NUM_FEATURES), np.float64))
        return np.asarray(labels, np.float32), X

    # -- train / evaluate (test.cpp:210-298) ---------------------------------

    def _attempt_window_train(self, labels: np.ndarray, X: np.ndarray,
                              widx: int):
        """Degrade-don't-die attempt at one window's training: a
        transient failure retries with bounded backoff
        (utils/retry.py); a persistent failure — exception, injected
        fault, or the per-window wall budget — is captured as the
        failure reason instead of propagating. Runs on the trainer
        thread in pipelined mode, inline otherwise.

        -> (stats dict or None, fresh booster handle or None, reason).
        """
        out = None
        reason = None
        # ONE deadline for the whole window, shared across transient
        # retries — a fresh clock per attempt would let one window
        # stall the serving loop for attempts x budget
        deadline = (time.monotonic() + self.window_budget_s
                    if self.window_budget_s is not None else None)
        try:
            def attempt():
                faults.check("lrb.window_train",
                             context=f"window {widx}")
                return self._train_model(labels, X, widx, deadline)
            out = retry.call(
                attempt, what=f"lrb window {widx} train",
                policy=self._retry_policy)
        except Exception as e:      # noqa: BLE001 — degrade, don't die
            obs.counter("lrb/windows_failed").add(1)
            reason = f"{type(e).__name__}: {e}"
            log.warning(
                "window %d: training failed (%s); serving continues on "
                "the model from window %d", widx, reason,
                self._trained_window)
        if out is None:
            return None, None, reason
        stats, handle = out
        return stats, handle, None

    def _apply_train_outcome(self, rec: dict, stats: Optional[dict],
                             reason: Optional[str]) -> None:
        """Window-ordered accounting of a training outcome (staleness
        gauge, degrade counters, result fields) — always on the main
        thread, at the point the outcome becomes part of the window's
        record."""
        # denominator of the degraded_window_rate SLO, counted BEFORE
        # the degraded counter below: with den leading num at the
        # producer and the engine reading num before den (obs/slo.py),
        # a concurrent ratio evaluation can never observe the new
        # degraded window without its denominator — which would
        # overshoot the rate and falsely latch budget exhaustion
        obs.counter("lrb/windows_total").add(1)
        if stats is not None:
            self._windows_since_train = 0
            self._trained_window = rec["window"]
            rec.update(stats)
        else:
            if self._serving is not None or self._trained_window:
                self._windows_since_train += 1
            obs.counter("lrb/windows_degraded").add(1)
            rec["degraded"] = True
            rec["degrade_reason"] = reason or "degenerate_labels"
            # WHY, not just THAT: the labeled counter family gives
            # Prometheus a rate per cause, the wide event gives the
            # flight recorder the full reason string, and the flight
            # dump captures the failing window's spans/requests NOW
            label = _degrade_label(reason)
            rec["degrade_label"] = label
            # bounded-cardinality: label comes from _degrade_label's
            # closed set (budget/injected_fault[_transient]/
            # degenerate_labels) plus exception CLASS names — bounded
            # by the code, not by request data
            obs.counter(f"lrb/degraded_reason/{label}").add(1)
            reqlog.record(
                "degraded_window", window=rec["window"], label=label,
                reason=rec["degrade_reason"],
                staleness_windows=self._windows_since_train)
            obs_flight.trigger(
                "degraded_window",
                {"window": rec["window"], "label": label,
                 "reason": rec["degrade_reason"],
                 "staleness_windows": self._windows_since_train})
        obs.gauge("lrb/model_staleness_windows").set(
            self._windows_since_train)
        rec["staleness_windows"] = self._windows_since_train

    # -- the trainer-thread pipeline -----------------------------------------

    def _submit_train(self, labels: np.ndarray, X: np.ndarray,
                      rec: dict, t_window: float) -> dict:
        """Hand one window's training to the trainer thread and
        return the UNPUBLISHED pending record — the boundary attaches
        the eval future and then publishes the complete record to
        ``self._pending`` in one locked write (see
        _process_window_pipelined)."""
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="lrb-trainer")
        self._train_started.clear()
        fut = self._executor.submit(self._train_async, labels, X,
                                    self.window_index)
        return {"window": self.window_index, "future": fut,
                "rec": rec, "t_window": t_window,
                "submit_t": time.monotonic()}

    def _submit_eval(self, ev, handle, ev_derive_s: float, wi: dict):
        """Queue one window's evaluation on the server thread (single
        worker: windows evaluate in order, so the cumulative serve
        histogram reads exactly like the sequential loop's).

        -> future of (eval fields dict, completion monotonic)."""
        if self._eval_executor is None:
            self._eval_executor = \
                concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="lrb-server")
        labels, X = ev

        def eval_job():
            t0 = time.monotonic()
            with trace.span("lrb/evaluate", cat="window", args=wi):
                out = self._score_window(labels, X, handle=handle,
                                         window=wi.get("window"))
            out["evaluate_s"] = round(
                time.monotonic() - t0 + ev_derive_s, 3)
            return out, time.monotonic()

        return self._eval_executor.submit(eval_job)

    def _train_async(self, labels: np.ndarray, X: np.ndarray,
                     widx: int):
        """Trainer-thread body: attempt the window, publish the fresh
        model on success (pre-warmed — see ``_publish``), and NEVER
        raise: every failure is folded into the returned reason so the
        join can only ever degrade the window, not kill the loop.

        -> (stats or None, reason or None, completion monotonic)."""
        try:
            if self._train_gate is not None:        # test seam
                self._train_started.set()
                self._train_gate.wait(timeout=60.0)
            with trace.span("lrb/train", cat="window",
                            args={"window": widx}):
                stats, handle, reason = self._attempt_window_train(
                    labels, X, widx)
                if handle is not None:
                    self._publish(handle, widx)
            return stats, reason, time.monotonic()
        except BaseException as e:  # noqa: BLE001 — the loop must live
            obs.counter("lrb/windows_failed").add(1)
            return None, f"{type(e).__name__}: {e}", time.monotonic()

    def _publish(self, handle, widx: int) -> None:
        """Publish-on-complete atomic model swap. The stacked serving
        path is built (and its serve-bucket program warmed) BEFORE the
        swap — on the trainer thread, under the booster's own serving
        lock — so a live request stream never pays the new model's
        cold tail; in-flight ``predict_live`` readers keep the old
        handle they snapshotted. A degraded window never reaches here:
        the swap simply does not happen."""
        try:
            handle.gbdt.prepare_serving(warm_rows=self.serve_batch)
        except Exception as e:  # noqa: BLE001 — never drop a good model
            log.warning("window %d: serving warm-up failed (%s); "
                        "publishing cold", widx, e)
        with self._swap_lock:
            self._serving = handle
        obs.counter("lrb/model_swaps").add(1)
        trace.instant("lrb/swap", cat="window", args={"window": widx})
        self._daemon_register(handle, widx)

    def _daemon_register(self, handle, widx: int) -> None:
        """--serve-daemon twin of the in-process swap: republish the
        freshly trained model as the next version of the daemon's
        "lrb" tenant (serve/tenants.py warms it before the atomic
        publish; in-flight daemon requests finish on the old
        version). A failed registration keeps the previous daemon
        version serving — same degrade-don't-die rule as training."""
        if self._fleet_client is None:
            return
        try:
            version = self._fleet_client.register(
                "lrb", capi.LGBM_BoosterSaveModelToString(handle),
                warm_rows=self.serve_batch)
            trace.instant("lrb/daemon_swap", cat="window",
                          args={"window": widx, "version": version})
        except Exception as e:  # noqa: BLE001 — never kill the loop
            # over the serving sidecar; the old version keeps serving
            log.warning("window %d: serve-daemon registration failed "
                        "(%s); daemon serves the previous version",
                        widx, e)

    _FLEET_WARN_CAP = 5

    def _daemon_score(self, Xb: np.ndarray) -> Optional[np.ndarray]:
        """Score one micro-batch through the fleet daemon client
        (--serve-daemon); None when the mode is off or the request
        failed past the client's retry policy — the caller falls back
        to in-process predict for that batch."""
        if self._fleet_client is None:
            return None
        try:
            return self._fleet_client.predict("lrb", Xb)
        except Exception as e:  # noqa: BLE001 — a dead sidecar must
            # degrade to in-process scoring, not kill the loop
            self._fleet_warned += 1
            if self._fleet_warned <= self._FLEET_WARN_CAP:
                log.warning("serve-daemon predict failed (%s); scoring "
                            "this batch in-process", e)
            elif self._fleet_warned == self._FLEET_WARN_CAP + 1:
                log.warning("further serve-daemon predict warnings "
                            "suppressed")
            return None

    def _join_pending(self) -> None:
        with self._join_lock:
            self._join_pending_locked()

    # guarded-by: _join_lock (called only from _join_pending's
    # locked region — the checker verifies every call site)
    def _join_pending_locked(self) -> None:
        p = self._pending
        if p is None:
            return
        t_join = time.monotonic()
        with trace.span("lrb/join", cat="window",
                        args={"window": p["window"]}):
            # _pending stays visible while we block here:
            # training_in_flight() must keep answering True to the
            # scorer for a trainer that overran the boundary — those
            # are exactly the during-retrain probes
            stats, reason, t_train = p["future"].result()
            t_done = t_train
            ev_fut = p.get("eval")
            if ev_fut is not None:
                ev_fields, t_eval = ev_fut.result()
                p["rec"].update(ev_fields)
                t_done = max(t_done, t_eval)
        self._pending = None
        rec = p["rec"]
        self._apply_train_outcome(rec, stats, reason)
        # overlap: how long the TRAINING ran while the main thread was
        # doing other work (ingesting/deriving the next window) — the
        # wall the pipeline reclaims vs the sequential loop; the eval
        # thread's tail is deliberately NOT counted here
        overlap = max(0.0, min(t_train, t_join) - p["submit_t"])
        rec["overlap_s"] = round(overlap, 3)
        obs.gauge("lrb/pipeline_overlap_s").set(round(overlap, 6))
        # window span: boundary open -> the LATEST of training
        # completion, evaluation completion and the boundary itself
        self._finish_window(
            rec, max(t_done, p.get("boundary_end", t_done))
            - p["t_window"])

    def drain(self) -> None:
        """Join any in-flight window training so ``results`` /
        ``booster`` reflect every completed window. No-op in
        sequential mode or between windows."""
        if self._pending is not None:
            self._join_pending()

    def close(self) -> None:
        """Drain and shut the trainer/server threads down (a later
        window would lazily restart them)."""
        self.drain()
        for attr in ("_executor", "_eval_executor"):
            ex = getattr(self, attr)
            if ex is not None:
                ex.shutdown(wait=True)
                setattr(self, attr, None)
        if self._fleet_daemon is not None:
            self._fleet_daemon.stop()
            self._fleet_daemon = None
            self._fleet_client = None

    # result-record fields replicated onto the per-window wide event
    # (the flight recorder and the reqlog file both see the window's
    # outcome without parsing the result line)
    _WINDOW_EVENT_FIELDS = (
        "eval_rows", "fp_rate", "fn_rate", "train_rows", "train_s",
        "compile_s", "degraded", "degrade_reason", "degrade_label",
        "staleness_windows", "serve_p99_ms", "window_wall_s",
        "overlap_s")

    def _finish_window(self, rec: dict, wall: float) -> None:
        """A window's record is complete (sequential: at the boundary;
        pipelined: when its training resolves): quantile-grade wall
        bookkeeping, the result line, one wide event, and a
        trace/result flush so a live loop can be inspected mid-run and
        a killed run keeps its last finished window."""
        rec["window_wall_s"] = round(wall, 3)
        self._wall_hist.observe(wall)
        obs.latency_histogram("lrb/window_wall_s").observe(wall)
        # (lrb/windows_total is counted in _apply_train_outcome, den
        # before num — see the ratio-race note there)
        reqlog.record("window", window=rec["window"],
                      **{k: rec[k] for k in self._WINDOW_EVENT_FIELDS
                         if k in rec})
        print(f"window {rec['window']}: "
              + " ".join(f"{k}={v}" for k, v in rec.items()),
              file=self.out)
        if hasattr(self.out, "flush"):
            self.out.flush()
        trace.write()

    def degraded_windows(self) -> int:
        """Windows that did not produce a fresh model (failed training,
        blown budget, degenerate labels)."""
        return sum(1 for r in self.results if r.get("degraded"))

    @property
    def flight_dumps(self) -> List[str]:
        """Flight-recorder bundles dumped since this driver started —
        the postmortem evidence for this run's faults/degraded
        windows, printed by main() next to the result summary."""
        return obs_flight.dump_paths()[self._flight_dumps_at_init:]

    def _train_model(self, labels: np.ndarray, X: np.ndarray,
                     widx: int,
                     deadline: Optional[float] = None):
        if len(labels) == 0 or len(np.unique(labels)) < 2:
            log.warning("window %d: degenerate labels; keeping previous "
                        "model", widx)
            return None
        from .ops import step_cache
        s0 = step_cache.stats()
        t0 = time.monotonic()
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=self.params,
                                            ring=self._ring)
        capi.LGBM_DatasetSetField(ds, "label", labels)
        # always a FRESH booster per window (test.cpp:281-295) — but
        # NOT a fresh compile: the windows' row counts, observed bin
        # counts and surviving feature counts all land in the same
        # shape buckets (ops/step_cache.py bucket_rows/bucket_bins +
        # the mult-of-8 feature pad), so every window reuses the first
        # window's compiled fused step and the same device bin-matrix
        # layout (identical [F_pad, n_bucket] shape means XLA reuses
        # the donated buffers instead of re-laying-out)
        booster = capi.LGBM_BoosterCreate(ds, self.params)
        for _ in range(int(self.params["num_iterations"])):
            if deadline is not None and time.monotonic() > deadline:
                # blown wall budget: the partial booster is DISCARDED
                # (the serving model is unchanged) — a half-trained
                # model must never serve
                raise WindowBudgetExceeded(
                    f"window {widx}: training exceeded "
                    f"the {self.window_budget_s:g}s wall budget; "
                    f"keeping the previous model")
            if capi.LGBM_BoosterUpdateOneIter(booster):
                break
        s1 = step_cache.stats()
        # per-window compile-vs-train split: the paper workload's whole
        # point is amortization — window 1 pays the compile, windows
        # 2.. should show compile ~0 and a registry hit
        train_s = time.monotonic() - t0
        compile_s = s1["compile_s"] - s0["compile_s"]
        log.info("window %d: %d rows trained in %.2fs (step compile "
                 "%.2fs, step cache +%d hit / +%d miss)",
                 widx, len(labels), train_s, compile_s,
                 s1["hits"] - s0["hits"], s1["misses"] - s0["misses"])
        # stamp the model's generation ON the handle: predict_live
        # reads the LIVE published handle, which in pipelined mode
        # can be newer than _trained_window (that field only advances
        # at the next boundary join) — the wide event's model
        # attribution must follow the handle, not the lagging field
        booster._lrb_window = widx
        return ({"train_s": round(train_s, 3),
                 "compile_s": round(compile_s, 3),
                 "step_cache_hits": s1["hits"] - s0["hits"]},
                booster)

    def window_wall_quantiles(self) -> Optional[dict]:
        """p50/p95/p99 window wall from THIS driver's log-bucketed
        latency instrument (obs/registry.py latency_histogram) —
        quantiles, not just means; None before the first window
        completes. Pipelined windows count boundary-to-publish."""
        self.drain()
        if not self._wall_hist.count:
            return None
        return {k: round(v, 3)
                for k, v in self._wall_hist.quantiles().items()
                if v is not None}

    def serve_latency_quantiles(self) -> Optional[dict]:
        """p50/p95/p99 PER-REQUEST serving latency from the driver's
        own instrument; None before the first evaluated window."""
        self.drain()
        if not self._serve_hist.count:
            return None
        return {k: round(v, 6)
                for k, v in self._serve_hist.quantiles().items()
                if v is not None}

    def _score_window(self, labels: np.ndarray, X: np.ndarray,
                      handle=None, window: Optional[int] = None) -> dict:
        # the serving half of the loop: this window's requests scored
        # against the previous window's model in micro-batches through
        # the geometry-keyed predict path (pow2 serve buckets,
        # ops/predict_cache.py) — every batch after the first rides a
        # warm compiled program. Each micro-batch's wall is ONE
        # serve_batch_s observation and `rows` serve_latency_s
        # observations (each request in it waited the batch out), so
        # the p99 an operator reads is a REQUEST quantile. ``handle``
        # pins the model (the pipelined boundary's join-time snapshot);
        # None = the currently published one. ``window`` stamps the
        # request identity: every micro-batch is issued a monotonic
        # request id, its trace span carries req_id/window, and one
        # wide event per batch records latency / serve bucket / model
        # generation / staleness (obs/reqlog.py).
        if handle is not None:
            h = handle
        else:
            with self._swap_lock:
                h = self._serving
        n = len(labels)
        b = self.serve_batch
        parts = []
        global_hist = obs.latency_histogram("lrb/serve_latency_s")
        global_batch = obs.latency_histogram("lrb/serve_batch_s")
        # model attribution for the wide events: prefer the pinned
        # handle's own generation stamp (_train_model). The fallback
        # fields are safe here too — they are updated ONLY by
        # _apply_train_outcome on the main thread, and the pipelined
        # boundary join resolves this evaluation's future BEFORE
        # applying the next outcome (_join_pending_locked), so they
        # describe the pinned ``handle`` even while the trainer
        # thread publishes mid-evaluation
        model_window = getattr(h, "_lrb_window", self._trained_window)
        staleness = self._windows_since_train
        for r0 in range(0, n, b):
            rows = min(b, n - r0)
            rid = reqlog.next_request_id()
            span_args = {"req_id": rid, "rows": rows}
            if window is not None:
                span_args["window"] = window
            t0 = time.monotonic()
            with reqlog.request(rid, window=window) as rctx, \
                    trace.span("serve/request", cat="serve",
                               args=span_args):
                preds_b = self._daemon_score(X[r0:r0 + b])
                if preds_b is None:
                    preds_b = np.asarray(capi.LGBM_BoosterPredictForMat(
                        h, X[r0:r0 + b],
                        predict_type=capi.C_API_PREDICT_NORMAL))
                parts.append(preds_b)
            dt = time.monotonic() - t0
            self._serve_batch_hist.observe(dt)
            global_batch.observe(dt)
            self._serve_hist.observe_n(dt, rows)
            global_hist.observe_n(dt, rows)
            reqlog.record(
                "request", req_id=rid, path="lrb/serve", window=window,
                rows=rows, latency_ms=round(1e3 * dt, 3),
                model_window=model_window, serve_bucket=rctx.bucket,
                staleness_windows=staleness)
        preds = (np.concatenate(parts) if parts
                 else np.zeros(0, np.float64))
        fp = ((labels < self.cutoff) & (preds >= self.cutoff)).sum()
        fn = ((labels >= self.cutoff) & (preds < self.cutoff)).sum()
        out = {"eval_rows": len(labels),
               "fp_rate": round(float(fp) / max(len(labels), 1), 4),
               "fn_rate": round(float(fn) / max(len(labels), 1), 4)}
        p99 = self._serve_hist.percentile(0.99)
        if p99 is not None:
            # cumulative across the run so far — the number a live
            # operator watches; the final summary prints the full set
            out["serve_p99_ms"] = round(1e3 * p99, 3)
        return out


# ---------------------------------------------------------------------------
# trace IO + synthetic generator
# ---------------------------------------------------------------------------

_MALFORMED_WARN_CAP = 10       # per-line warnings before going quiet


def run_trace_file(path: str, cache_size: int, window_size: int,
                   sample_size: int, cutoff: float, sampling: int,
                   result_file=sys.stdout,
                   extra_params: Optional[dict] = None,
                   window_budget_s: Optional[float] = None,
                   serve_daemon: bool = False) -> LrbDriver:
    """Drive the loop from a trace file. Malformed lines are SKIPPED
    with a warning carrying the line number (capped at
    ``_MALFORMED_WARN_CAP`` detail lines + a total-skipped summary) —
    one bad record in a multi-day trace must not kill the run."""
    driver = LrbDriver(cache_size, window_size, sample_size, cutoff,
                       sampling, result_file, extra_params=extra_params,
                       window_budget_s=window_budget_s,
                       serve_daemon=serve_daemon)
    seq = 0
    skipped = 0
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            parts = line.split()
            if not parts:
                continue
            try:
                if len(parts) >= 4:
                    _, obj_id, size, cost = parts[:4]
                else:
                    obj_id, size, cost = parts[:3]
                req = (int(obj_id), int(float(size)), float(cost))
            except (ValueError, IndexError) as e:
                skipped += 1
                if skipped <= _MALFORMED_WARN_CAP:
                    log.warning("%s:%d: malformed trace line skipped "
                                "(%s): %r", path, lineno, e,
                                line.rstrip()[:80])
                elif skipped == _MALFORMED_WARN_CAP + 1:
                    log.warning("%s: further malformed-line warnings "
                                "suppressed (summary at end)", path)
                continue
            seq += 1
            driver.process_request(seq, *req)
    driver.drain()
    driver.trace_lines_skipped = skipped
    if skipped:
        log.warning("%s: skipped %d malformed trace line(s) in total "
                    "(%d served)", path, skipped, seq)
    return driver


def synthetic_trace(n_requests: int, n_objects: int = 200,
                    seed: int = 7):
    """Zipf-ish request stream for tests: popular objects recur."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_objects + 1)
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    ids = rng.choice(n_objects, size=n_requests, p=p)
    sizes = (2 ** rng.integers(6, 14, n_objects))
    for i, oid in enumerate(ids):
        yield i + 1, int(oid), int(sizes[oid]), 1.0


def _run_main(argv, out, serve_daemon: bool = False) -> None:
    trace_path, cache_size, window_size, sample_size, cutoff, sampling = \
        argv[0], int(argv[1]), int(argv[2]), int(argv[3]), \
        float(argv[4]), int(argv[5])
    driver = run_trace_file(trace_path, cache_size, window_size,
                            sample_size, cutoff, sampling, out,
                            serve_daemon=serve_daemon)
    driver.close()
    q = driver.window_wall_quantiles()
    if q:
        print("window_wall " + " ".join(f"{k}={v}s"
                                        for k, v in q.items()),
              file=out)
    sq = driver.serve_latency_quantiles()
    if sq:
        print("serve_latency " + " ".join(f"{k}={1e3 * v:.3f}ms"
                                          for k, v in sq.items()),
              file=out)
    dw = driver.degraded_windows()
    if dw:
        print(f"degraded_windows={dw} "
              f"model_staleness_windows={driver._windows_since_train}",
              file=out)
    if driver.flight_dumps:
        # the black box's postmortem bundles, findable from the result
        # file (tools/trace_summary.py renders them)
        print("flight_dumps " + " ".join(driver.flight_dumps),
              file=out)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    # the one optional flag rides alongside the reference's positional
    # CLI: strip it before the positional parse
    serve_daemon = "--serve-daemon" in argv
    argv = [a for a in argv if a != "--serve-daemon"]
    if len(argv) < 6:
        print("parameters: tracePath cacheSize windowSize sampleSize "
              "cutoff sampling [resultFile] [--serve-daemon]",
              file=sys.stderr)
        sys.exit(1)
    if len(argv) > 6:
        # context-managed: a crash mid-run must not strand buffered
        # tail windows in a never-closed handle (the driver also
        # flushes after every finished window)
        with open(argv[6], "w") as out:
            _run_main(argv, out, serve_daemon)
    else:
        _run_main(argv, sys.stdout, serve_daemon)


if __name__ == "__main__":
    main()
