"""Windowed cache-admission training driver (the fork's application).

TPU-native counterpart of the fork's actual main program
(reference: src/test.cpp:39-341): a learning-relaxed-Belady loop that,
per fixed-size window of (id, size, cost) cache requests,

1. labels each request by an OPT-like volume ranking (calculateOPT,
   test.cpp:97-121): requests whose next-use volume fits the cache's
   byte-window budget get toCache = 1;
2. derives features (deriveFeatures, test.cpp:124-208): up to 50
   inter-arrival gaps, log2 object size, log2 available cache bytes,
   and the request cost, as a CSR matrix;
3. trains a FRESH booster on the window's sample with the fork's fixed
   parameter set (trainModel, test.cpp:240-298);
4. evaluates the previous booster on the next window, reporting
   false-positive / false-negative rates at ``cutoff`` plus the OPT
   object/byte hit ratios (evaluateModel, test.cpp:210-238).

Run: ``python -m lightgbm_tpu.lrb <trace> <cacheSize> <windowSize>
<sampleSize> <cutoff> <sampling> [result_file]`` — the same argv as the
reference binary. ``trace`` rows: ``seq id size cost`` (or
``id size cost``; a synthetic trace generator is included for testing).
"""
from __future__ import annotations

import sys
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import capi
from .obs import export as obs_export
from .obs import registry as obs
from .obs import trace
from .utils import faults, log, retry

HISTFEATURES = 50            # test.cpp:16
NUM_FEATURES = HISTFEATURES + 3

TRAIN_PARAMS = {             # test.cpp:67-87
    "boosting": "gbdt",
    "objective": "binary",
    "metric": "binary_logloss,auc",
    "metric_freq": "1",
    "is_provide_training_metric": "true",
    "max_bin": "255",
    "num_iterations": "50",
    "learning_rate": "0.1",
    "num_leaves": "31",
    "tree_learner": "serial",
    "feature_fraction": "0.8",
    "bagging_freq": "5",
    "bagging_fraction": "0.8",
    "min_data_in_leaf": "50",
    "min_sum_hessian_in_leaf": "5.0",
    "verbose": "-1",
}


class WindowBudgetExceeded(RuntimeError):
    """A window's training ran past the per-window wall budget — the
    degrade path treats it like any other window-train failure
    (serving continues on the previous model), and retry classifies
    it non-transient (re-running the same window would blow the same
    budget)."""


class Window:
    """One window's trace + OPT bookkeeping (test.cpp globals)."""

    def __init__(self):
        self.ids: List[int] = []
        self.sizes: List[int] = []
        self.costs: List[float] = []
        self.to_cache: Optional[np.ndarray] = None
        self.has_next: List[bool] = []
        self.volume: List[int] = []
        self.byte_sum = 0


class LrbDriver:
    """The windowed retraining loop (test.cpp:300-341 processRequest)."""

    def __init__(self, cache_size: int, window_size: int,
                 sample_size: int, cutoff: float, sampling: int,
                 result_file=sys.stdout, seed: int = 0,
                 extra_params: Optional[dict] = None,
                 serve_batch: int = 64,
                 window_budget_s: Optional[float] = None):
        self.cache_size = cache_size
        self.window_size = window_size
        self.sample_size = sample_size
        self.cutoff = cutoff
        self.sampling = sampling
        self.out = result_file
        self.rng = np.random.default_rng(seed)
        # per-window training params: the reference's fixed set plus
        # operator overrides (telemetry knobs, tpu_ingest for tests);
        # the telemetry daemons start HERE so window spans and live
        # metrics cover the whole loop, not just the boosters
        self.params = dict(TRAIN_PARAMS)
        self.params.update({k: str(v) for k, v in
                            (extra_params or {}).items()})
        trace.ensure_from_config(self.params)
        obs_export.ensure_from_config(self.params)
        # fault-injection drills armed HERE so pre-booster points
        # (dataset ingest) are covered from window 1 (idempotent:
        # every window's booster init re-arms the same spec)
        if self.params.get("tpu_faults"):
            faults.configure(self.params["tpu_faults"],
                             int(self.params.get("tpu_fault_seed", 0)))
        # driver-OWNED window-wall instrument: this run's quantile
        # summary must not mix in an earlier driver's windows (the
        # process-global twin below feeds the live exporter, which IS
        # cumulative by design, like every registry counter)
        self._wall_hist = obs.latency_histogram(
            "lrb/window_wall_s", obs.MetricsRegistry())
        # serving-path instrument: every evaluation scores the window's
        # requests against the PREVIOUS window's model in serve-bucket
        # micro-batches (the retrain-while-serve shape, ROADMAP item
        # 3); each call's wall lands here as one request latency.
        # Driver-owned for the same reason as _wall_hist; the global
        # twin feeds the live exporter.
        self.serve_batch = max(int(serve_batch), 1)
        self._serve_hist = obs.latency_histogram(
            "lrb/serve_latency_s", obs.MetricsRegistry())
        self.booster = None
        # degrade-don't-die bookkeeping: a window whose training fails
        # (exception, injected fault, or the per-window wall budget)
        # is marked degraded and serving continues on the previous
        # model; the staleness gauge counts windows since the last
        # successful retrain — the number an operator alarms on
        self.window_budget_s = (None if window_budget_s is None
                                else float(window_budget_s))
        self._windows_since_train = 0
        self._trained_window = 0      # index of the serving model's window
        self._retry_policy = retry.RetryPolicy(
            attempts=int(self.params.get("tpu_retry_attempts", 4)),
            seed=seed)
        self.window = Window()
        self.last_seen: Dict[Tuple[int, int], int] = {}
        # per-id inter-arrival history carried ACROSS windows is reset
        # with the window in the reference (statistics is local to
        # deriveFeatures) — mirrored here
        self.window_index = 0
        self.results: List[dict] = []
        self.trace_lines_skipped = 0

    # -- request ingestion ---------------------------------------------------

    def process_request(self, seq: int, obj_id: int, size: int,
                        cost: float) -> None:
        w = self.window
        idx = (seq - 1) % self.window_size
        key = (obj_id, size)
        if size > 0 and key in self.last_seen:
            prev = self.last_seen[key]
            w.has_next[prev] = True
            w.volume[prev] = (idx - prev) * size
        w.byte_sum += size
        self.last_seen[key] = idx
        w.ids.append(obj_id)
        w.sizes.append(size)
        w.costs.append(cost)
        w.has_next.append(False)
        w.volume.append(np.iinfo(np.int64).max)
        if seq % self.window_size == 0:
            self._process_window()

    def _process_window(self) -> None:
        self.window_index += 1
        t_window = time.monotonic()
        wi = {"window": self.window_index}
        rec = {"window": self.window_index}
        with trace.span("window", cat="window", args=wi):
            self._calculate_opt()
            # per-window phase table: derive / train / evaluate wall
            # seconds land in the results AND as spans on the trace
            # timeline (evaluate derives the NEXT window's features on
            # the previous model — the serving half of the loop)
            if self.booster is not None:
                t0 = time.monotonic()
                with trace.span("lrb/evaluate", cat="window", args=wi):
                    rec.update(self._evaluate_model())
                rec["evaluate_s"] = round(time.monotonic() - t0, 3)
            t0 = time.monotonic()
            with trace.span("lrb/derive", cat="window", args=wi):
                labels, X = self._derive_features(self.sampling)
            rec["derive_s"] = round(time.monotonic() - t0, 3)
            rec["train_rows"] = len(labels)
            with trace.span("lrb/train", cat="window", args=wi):
                rec.update(self._train_window(labels, X))
            rec.update(self._opt_ratios())
        wall = time.monotonic() - t_window
        rec["window_wall_s"] = round(wall, 3)
        # quantile-grade window-wall latency (obs/registry.py preset):
        # the exporter publishes p50/p95/p99 live, the final summary
        # prints them — the instrument ROADMAP §3's streaming bench
        # will judge retrain-while-serve against
        self._wall_hist.observe(wall)
        obs.latency_histogram("lrb/window_wall_s").observe(wall)
        self.results.append(rec)
        print(f"window {self.window_index}: "
              + " ".join(f"{k}={v}" for k, v in rec.items()),
              file=self.out)
        # keep the on-disk trace current: a live loop can be inspected
        # mid-run, and a killed run keeps its last window
        trace.write()
        self.window = Window()
        self.last_seen.clear()

    # -- OPT labeling (test.cpp:97-121) --------------------------------------

    def _calculate_opt(self) -> None:
        w = self.window
        n = len(w.ids)
        volume = np.asarray(w.volume, np.int64)
        has_next = np.asarray(w.has_next, bool)
        order = np.argsort(volume, kind="stable")
        cache_volume = self.cache_size * self.window_size
        to_cache = np.zeros(n, bool)
        cur = 0
        self._opt_hits = 0
        self._opt_byte_hits = 0
        sizes = np.asarray(w.sizes, np.int64)
        for i in order:
            if cur > cache_volume:
                break
            if has_next[i]:
                to_cache[i] = True
                self._opt_hits += 1
                self._opt_byte_hits += int(sizes[i])
                cur += int(volume[i])
        w.to_cache = to_cache

    def _opt_ratios(self) -> dict:
        w = self.window
        return {
            "opt_obj_hit_ratio": round(self._opt_hits
                                       / self.window_size, 4),
            "opt_byte_hit_ratio": round(self._opt_byte_hits
                                        / max(w.byte_sum, 1), 4),
        }

    # -- feature derivation (test.cpp:124-208) -------------------------------

    def _derive_features(self, sampling: int):
        w = self.window
        n = len(w.ids)
        cache_avail = self.cache_size
        history: Dict[int, deque] = {}
        cache: Dict[int, int] = {}
        labels: List[float] = []
        rows: List[np.ndarray] = []
        for i in range(n):
            q = history.setdefault(w.ids[i], deque())
            if len(q) > HISTFEATURES:
                q.pop()
            flag = True
            if sampling == 1:
                flag = i >= (self.window_size - self.sample_size)
            elif sampling == 2:
                flag = self.rng.random() < self.sample_size \
                    / self.window_size
            if flag:
                labels.append(1.0 if w.to_cache[i] else 0.0)
                feat = np.zeros(NUM_FEATURES, np.float64)
                last = i
                for j, t in enumerate(q):
                    feat[j] = last - t
                    last = t
                feat[HISTFEATURES] = round(
                    100.0 * np.log2(max(w.sizes[i], 1)))
                feat[HISTFEATURES + 1] = (
                    0.0 if cache_avail <= 0
                    else round(100.0 * np.log2(cache_avail)))
                feat[HISTFEATURES + 2] = w.costs[i]
                rows.append(feat)
            # cache-occupancy bookkeeping (test.cpp:180-199)
            oid = w.ids[i]
            if oid not in cache:
                if w.to_cache[i]:
                    cache_avail -= w.sizes[i]
                    cache[oid] = w.sizes[i]
            else:
                if not w.to_cache[i]:
                    cache_avail += cache.pop(oid)
            q.appendleft(i)
        X = (np.stack(rows) if rows
             else np.zeros((0, NUM_FEATURES), np.float64))
        return np.asarray(labels, np.float32), X

    # -- train / evaluate (test.cpp:210-298) ---------------------------------

    def _train_window(self, labels: np.ndarray, X: np.ndarray) -> dict:
        """Degrade-don't-die wrapper around one window's training: a
        transient failure retries with bounded backoff (utils/retry.py);
        a persistent failure — exception, injected fault, or the
        per-window wall budget — marks the window ``degraded`` and the
        loop keeps serving the previous model instead of dying. The
        staleness gauge and the windows_failed/degraded counters flow
        to the live Prometheus export (obs/export.py)."""
        out = None
        reason = None
        # ONE deadline for the whole window, shared across transient
        # retries — a fresh clock per attempt would let one window
        # stall the serving loop for attempts x budget
        deadline = (time.monotonic() + self.window_budget_s
                    if self.window_budget_s is not None else None)
        try:
            def attempt():
                faults.check("lrb.window_train",
                             context=f"window {self.window_index}")
                return self._train_model(labels, X, deadline)
            out = retry.call(
                attempt, what=f"lrb window {self.window_index} train",
                policy=self._retry_policy)
        except Exception as e:      # noqa: BLE001 — degrade, don't die
            obs.counter("lrb/windows_failed").add(1)
            reason = f"{type(e).__name__}: {e}"
            log.warning(
                "window %d: training failed (%s); serving continues on "
                "the model from window %d", self.window_index, reason,
                self._trained_window)
        rec: dict = {}
        if out is not None:
            self._windows_since_train = 0
            self._trained_window = self.window_index
            rec.update(out)
        else:
            if self.booster is not None or self._trained_window:
                self._windows_since_train += 1
            obs.counter("lrb/windows_degraded").add(1)
            rec["degraded"] = True
            rec["degrade_reason"] = reason or "degenerate_labels"
        obs.gauge("lrb/model_staleness_windows").set(
            self._windows_since_train)
        rec["staleness_windows"] = self._windows_since_train
        return rec

    def degraded_windows(self) -> int:
        """Windows that did not produce a fresh model (failed training,
        blown budget, degenerate labels)."""
        return sum(1 for r in self.results if r.get("degraded"))

    def _train_model(self, labels: np.ndarray, X: np.ndarray,
                     deadline: Optional[float] = None) -> Optional[dict]:
        if len(labels) == 0 or len(np.unique(labels)) < 2:
            log.warning("window %d: degenerate labels; keeping previous "
                        "model", self.window_index)
            return None
        from .ops import step_cache
        s0 = step_cache.stats()
        t0 = time.monotonic()
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=self.params)
        capi.LGBM_DatasetSetField(ds, "label", labels)
        # always a FRESH booster per window (test.cpp:281-295) — but
        # NOT a fresh compile: the windows' row counts, observed bin
        # counts and surviving feature counts all land in the same
        # shape buckets (ops/step_cache.py bucket_rows/bucket_bins +
        # the mult-of-8 feature pad), so every window reuses the first
        # window's compiled fused step and the same device bin-matrix
        # layout (identical [F_pad, n_bucket] shape means XLA reuses
        # the donated buffers instead of re-laying-out)
        booster = capi.LGBM_BoosterCreate(ds, self.params)
        for _ in range(int(self.params["num_iterations"])):
            if deadline is not None and time.monotonic() > deadline:
                # blown wall budget: the partial booster is DISCARDED
                # (self.booster unchanged) — a half-trained model must
                # never serve
                raise WindowBudgetExceeded(
                    f"window {self.window_index}: training exceeded "
                    f"the {self.window_budget_s:g}s wall budget; "
                    f"keeping the previous model")
            if capi.LGBM_BoosterUpdateOneIter(booster):
                break
        s1 = step_cache.stats()
        # per-window compile-vs-train split: the paper workload's whole
        # point is amortization — window 1 pays the compile, windows
        # 2.. should show compile ~0 and a registry hit
        train_s = time.monotonic() - t0
        compile_s = s1["compile_s"] - s0["compile_s"]
        log.info("window %d: %d rows trained in %.2fs (step compile "
                 "%.2fs, step cache +%d hit / +%d miss)",
                 self.window_index, len(labels), train_s, compile_s,
                 s1["hits"] - s0["hits"], s1["misses"] - s0["misses"])
        self.booster = booster
        return {"train_s": round(train_s, 3),
                "compile_s": round(compile_s, 3),
                "step_cache_hits": s1["hits"] - s0["hits"]}

    def window_wall_quantiles(self) -> Optional[dict]:
        """p50/p95/p99 window wall from THIS driver's log-bucketed
        latency instrument (obs/registry.py latency_histogram) —
        quantiles, not just means; None before the first window
        completes."""
        if not self._wall_hist.count:
            return None
        return {k: round(v, 3)
                for k, v in self._wall_hist.quantiles().items()
                if v is not None}

    def serve_latency_quantiles(self) -> Optional[dict]:
        """p50/p95/p99 per-request serving latency from the driver's
        own instrument; None before the first evaluated window."""
        if not self._serve_hist.count:
            return None
        return {k: round(v, 6)
                for k, v in self._serve_hist.quantiles().items()
                if v is not None}

    def _evaluate_model(self) -> dict:
        labels, X = self._derive_features(0)
        # the serving half of the loop: this window's requests scored
        # against the previous window's model in micro-batches through
        # the geometry-keyed predict path (pow2 serve buckets,
        # ops/predict_cache.py) — every batch after the first rides a
        # warm compiled program, and each call's wall is one request
        # latency in the driver-owned histogram
        n = len(labels)
        b = self.serve_batch
        parts = []
        global_hist = obs.latency_histogram("lrb/serve_latency_s")
        for r0 in range(0, n, b):
            t0 = time.monotonic()
            parts.append(np.asarray(capi.LGBM_BoosterPredictForMat(
                self.booster, X[r0:r0 + b],
                predict_type=capi.C_API_PREDICT_NORMAL)))
            dt = time.monotonic() - t0
            self._serve_hist.observe(dt)
            global_hist.observe(dt)
        preds = (np.concatenate(parts) if parts
                 else np.zeros(0, np.float64))
        fp = ((labels < self.cutoff) & (preds >= self.cutoff)).sum()
        fn = ((labels >= self.cutoff) & (preds < self.cutoff)).sum()
        out = {"eval_rows": len(labels),
               "fp_rate": round(float(fp) / max(len(labels), 1), 4),
               "fn_rate": round(float(fn) / max(len(labels), 1), 4)}
        p99 = self._serve_hist.percentile(0.99)
        if p99 is not None:
            # cumulative across the run so far — the number a live
            # operator watches; the final summary prints the full set
            out["serve_p99_ms"] = round(1e3 * p99, 3)
        return out


# ---------------------------------------------------------------------------
# trace IO + synthetic generator
# ---------------------------------------------------------------------------

_MALFORMED_WARN_CAP = 10       # per-line warnings before going quiet


def run_trace_file(path: str, cache_size: int, window_size: int,
                   sample_size: int, cutoff: float, sampling: int,
                   result_file=sys.stdout,
                   extra_params: Optional[dict] = None,
                   window_budget_s: Optional[float] = None) -> LrbDriver:
    """Drive the loop from a trace file. Malformed lines are SKIPPED
    with a warning carrying the line number (capped at
    ``_MALFORMED_WARN_CAP`` detail lines + a total-skipped summary) —
    one bad record in a multi-day trace must not kill the run."""
    driver = LrbDriver(cache_size, window_size, sample_size, cutoff,
                       sampling, result_file, extra_params=extra_params,
                       window_budget_s=window_budget_s)
    seq = 0
    skipped = 0
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            parts = line.split()
            if not parts:
                continue
            try:
                if len(parts) >= 4:
                    _, obj_id, size, cost = parts[:4]
                else:
                    obj_id, size, cost = parts[:3]
                req = (int(obj_id), int(float(size)), float(cost))
            except (ValueError, IndexError) as e:
                skipped += 1
                if skipped <= _MALFORMED_WARN_CAP:
                    log.warning("%s:%d: malformed trace line skipped "
                                "(%s): %r", path, lineno, e,
                                line.rstrip()[:80])
                elif skipped == _MALFORMED_WARN_CAP + 1:
                    log.warning("%s: further malformed-line warnings "
                                "suppressed (summary at end)", path)
                continue
            seq += 1
            driver.process_request(seq, *req)
    driver.trace_lines_skipped = skipped
    if skipped:
        log.warning("%s: skipped %d malformed trace line(s) in total "
                    "(%d served)", path, skipped, seq)
    return driver


def synthetic_trace(n_requests: int, n_objects: int = 200,
                    seed: int = 7):
    """Zipf-ish request stream for tests: popular objects recur."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_objects + 1)
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    ids = rng.choice(n_objects, size=n_requests, p=p)
    sizes = (2 ** rng.integers(6, 14, n_objects))
    for i, oid in enumerate(ids):
        yield i + 1, int(oid), int(sizes[oid]), 1.0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 6:
        print("parameters: tracePath cacheSize windowSize sampleSize "
              "cutoff sampling [resultFile]", file=sys.stderr)
        sys.exit(1)
    trace_path, cache_size, window_size, sample_size, cutoff, sampling = \
        argv[0], int(argv[1]), int(argv[2]), int(argv[3]), \
        float(argv[4]), int(argv[5])
    out = open(argv[6], "w") if len(argv) > 6 else sys.stdout
    driver = run_trace_file(trace_path, cache_size, window_size,
                            sample_size, cutoff, sampling, out)
    q = driver.window_wall_quantiles()
    if q:
        print("window_wall " + " ".join(f"{k}={v}s"
                                        for k, v in q.items()),
              file=out)
    sq = driver.serve_latency_quantiles()
    if sq:
        print("serve_latency " + " ".join(f"{k}={1e3 * v:.3f}ms"
                                          for k, v in sq.items()),
              file=out)
    dw = driver.degraded_windows()
    if dw:
        print(f"degraded_windows={dw} "
              f"model_staleness_windows={driver._windows_since_train}",
              file=out)


if __name__ == "__main__":
    main()
