"""Per-phase wall-clock accounting.

TPU-native counterpart of the reference's TIMETAG instrumentation
(reference: src/treelearner/serial_tree_learner.cpp:14-41 init/hist/
split timers, src/boosting/gbdt.cpp:253-256 per-iteration elapsed).
A process-global accumulator keyed by phase name; training drivers log
the table when a run finishes. jax dispatch is async, so a phase's
bucket holds the HOST time it spent issuing work; queued device time
lands in whichever later phase first synchronizes. Callers that need
exact device attribution should block_until_ready inside the phase.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from contextlib import contextmanager

from . import log

_acc: "OrderedDict[str, float]" = OrderedDict()
_counts: "OrderedDict[str, int]" = OrderedDict()


@contextmanager
def phase(name: str):
    """Accumulate the wall time spent inside the block."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        _acc[name] = _acc.get(name, 0.0) + (time.monotonic() - t0)
        _counts[name] = _counts.get(name, 0) + 1


def add(name: str, seconds: float) -> None:
    _acc[name] = _acc.get(name, 0.0) + seconds
    _counts[name] = _counts.get(name, 0) + 1


def reset() -> None:
    _acc.clear()
    _counts.clear()


def report() -> str:
    """One line per phase: total seconds, calls, mean ms."""
    lines = []
    for name, total in _acc.items():
        n = max(_counts.get(name, 1), 1)
        lines.append(f"  {name:<24s} {total:9.3f} s  ({n} calls, "
                     f"{1000.0 * total / n:.2f} ms avg)")
    return "\n".join(lines)


def log_report(header: str = "phase timings") -> None:
    """Log and RESET — each report covers one run's deltas."""
    if _acc:
        log.info("%s:\n%s", header, report())
        reset()
