"""Per-phase wall-clock accounting.

TPU-native counterpart of the reference's TIMETAG instrumentation
(reference: src/treelearner/serial_tree_learner.cpp:14-41 init/hist/
split timers, src/boosting/gbdt.cpp:253-256 per-iteration elapsed).
A process-global accumulator keyed by phase name; training drivers log
the table when a run finishes. jax dispatch is async, so a phase's
bucket holds the HOST time it spent issuing work; queued device time
lands in whichever later phase first synchronizes. Callers that need
exact device attribution should block_until_ready inside the phase.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from contextlib import contextmanager

from . import log

_acc: "OrderedDict[str, float]" = OrderedDict()
_counts: "OrderedDict[str, int]" = OrderedDict()


class _PhaseHandle:
    """Yielded by ``phase``; lets device phases register the output
    whose completion the phase should wait for at exit."""
    __slots__ = ("out",)

    def __init__(self):
        self.out = None

    def watch(self, out):
        """Register a (pytree of) device array(s): the phase blocks on
        it at exit, so queued device time is attributed HERE instead of
        leaking into whichever later phase first synchronizes."""
        self.out = out
        return out


@contextmanager
def phase(name: str):
    """Accumulate the wall time spent inside the block.

    jax dispatch is async: a phase that merely ISSUES device work
    records only the issue time, and the device time lands in whichever
    later phase first synchronizes — silently misattributed. Device
    phases therefore ``.watch(out)`` their output on the yielded
    handle, which forces completion at phase exit, before the clock
    stops."""
    t0 = time.monotonic()
    h = _PhaseHandle()
    try:
        yield h
    finally:
        if h.out is not None:
            _sync(h.out)
        _acc[name] = _acc.get(name, 0.0) + (time.monotonic() - t0)
        _counts[name] = _counts.get(name, 0) + 1


def add(name: str, seconds: float) -> None:
    _acc[name] = _acc.get(name, 0.0) + seconds
    _counts[name] = _counts.get(name, 0) + 1


def reset() -> None:
    _acc.clear()
    _counts.clear()


def seconds(prefix: str) -> float:
    """Total accumulated seconds of every phase whose name starts with
    ``prefix`` (e.g. "autotune" sums all per-kernel tuning phases)."""
    return sum(v for k, v in _acc.items() if k.startswith(prefix))


def _sync(out) -> None:
    """Force completion of a dispatched jax computation with a real
    device->host scalar readback: block_until_ready alone has been
    observed returning early on RPC-tunneled backends (bench.py), and
    the transfer stream is ordered, so one scalar drains the queue."""
    import numpy as np
    try:
        import jax
        leaves = [x for x in jax.tree_util.tree_leaves(out)
                  if hasattr(x, "dtype")]
    except ImportError:
        leaves = []
    if leaves:
        x = leaves[0]
        np.asarray(x.ravel()[:1] if getattr(x, "ndim", 0) else x)


def measure(fn, *args, repeats: int = 5, warmup: int = 1) -> float:
    """Median-of-``repeats`` wall seconds of ``fn(*args)`` with a device
    sync per call — the autotuner's measurement harness (the reference
    times its GPU kernel variants the same way, docs/GPU-Performance).
    ``warmup`` untimed calls absorb compilation."""
    for _ in range(max(warmup, 0)):
        _sync(fn(*args))
    times = []
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        _sync(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def report() -> str:
    """One line per phase: total seconds, calls, mean ms."""
    lines = []
    for name, total in _acc.items():
        n = max(_counts.get(name, 1), 1)
        lines.append(f"  {name:<24s} {total:9.3f} s  ({n} calls, "
                     f"{1000.0 * total / n:.2f} ms avg)")
    return "\n".join(lines)


def log_report(header: str = "phase timings") -> None:
    """Log and RESET — each report covers one run's deltas."""
    if _acc:
        log.info("%s:\n%s", header, report())
        reset()
