"""Per-phase wall-clock accounting.

TPU-native counterpart of the reference's TIMETAG instrumentation
(reference: src/treelearner/serial_tree_learner.cpp:14-41 init/hist/
split timers, src/boosting/gbdt.cpp:253-256 per-iteration elapsed).
A process-global accumulator keyed by phase name; training drivers log
the table when a run finishes. On-device time is attributed to the
phase that issued the work (jax dispatch is async — phases that need
exact device time call ``block=True``).
"""
from __future__ import annotations

import time
from collections import OrderedDict
from contextlib import contextmanager

from . import log

_acc: "OrderedDict[str, float]" = OrderedDict()
_counts: "OrderedDict[str, int]" = OrderedDict()


@contextmanager
def phase(name: str, block_on=None):
    """Accumulate the wall time of a phase; ``block_on`` (a jax array /
    pytree) is block_until_ready'd before the clock stops so device
    work lands in the right bucket."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        if block_on is not None:
            import jax
            jax.block_until_ready(block_on)
        _acc[name] = _acc.get(name, 0.0) + (time.monotonic() - t0)
        _counts[name] = _counts.get(name, 0) + 1


def add(name: str, seconds: float) -> None:
    _acc[name] = _acc.get(name, 0.0) + seconds
    _counts[name] = _counts.get(name, 0) + 1


def reset() -> None:
    _acc.clear()
    _counts.clear()


def report() -> str:
    """One line per phase: total seconds, calls, mean ms."""
    lines = []
    for name, total in _acc.items():
        n = max(_counts.get(name, 1), 1)
        lines.append(f"  {name:<24s} {total:9.3f} s  ({n} calls, "
                     f"{1000.0 * total / n:.2f} ms avg)")
    return "\n".join(lines)


def log_report(header: str = "phase timings") -> None:
    """Log and RESET — each report covers one run's deltas."""
    if _acc:
        log.info("%s:\n%s", header, report())
        reset()
