"""Per-phase wall-clock accounting.

TPU-native counterpart of the reference's TIMETAG instrumentation
(reference: src/treelearner/serial_tree_learner.cpp:14-41 init/hist/
split timers, src/boosting/gbdt.cpp:253-256 per-iteration elapsed).
Phase accumulation lives in the obs metrics registry
(obs/registry.py) — thread-safe, so the ingest prefetch worker can
record from off-thread while the main thread accumulates training
phases — and every phase lands in the run report's phase table
(obs/recorder.py). jax dispatch is async, so a phase's bucket holds the
HOST time it spent issuing work; queued device time lands in whichever
later phase first synchronizes. Callers that need exact device
attribution ``.watch(out)`` their output (sync at phase exit).

When profiling is active (obs/profiler.py ProfileWindow), each phase
additionally wraps its block in a ``jax.profiler.TraceAnnotation`` so
the engine's phase names show up as spans in XLA/Perfetto traces.
When the engine's own tracer is active (obs/trace.py, config
``tpu_trace``), each phase also records a span on the calling thread's
trace row — one file shows the ingest worker's phases interleaved with
the main thread's.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from ..obs import registry as _obs
from ..obs import trace as _trace
from . import log

# emit jax TraceAnnotations around phases (toggled by the profiler
# window; off by default — the annotation objects are cheap but not
# free, and most runs are not being traced)
_annotate = False


def set_trace_annotations(on: bool) -> None:
    global _annotate
    _annotate = bool(on)


class _PhaseHandle:
    """Yielded by ``phase``; lets device phases register the output
    whose completion the phase should wait for at exit."""
    __slots__ = ("out",)

    def __init__(self):
        self.out = None

    def watch(self, out):
        """Register a (pytree of) device array(s): the phase blocks on
        it at exit, so queued device time is attributed HERE instead of
        leaking into whichever later phase first synchronizes."""
        self.out = out
        return out


@contextmanager
def phase(name: str):
    """Accumulate the wall time spent inside the block.

    jax dispatch is async: a phase that merely ISSUES device work
    records only the issue time, and the device time lands in whichever
    later phase first synchronizes — silently misattributed. Device
    phases therefore ``.watch(out)`` their output on the yielded
    handle, which forces completion at phase exit, before the clock
    stops."""
    ann = None
    if _annotate:
        try:
            import jax
            ann = jax.profiler.TraceAnnotation(f"lgbm/{name}")
            ann.__enter__()
        except Exception:               # noqa: BLE001 — annotation is
            ann = None                  # an aid, never a failure mode
    tracer = _trace.active()
    span_t0 = tracer.now_us() if tracer is not None else 0.0
    t0 = time.monotonic()
    h = _PhaseHandle()
    try:
        yield h
    finally:
        if h.out is not None:
            _sync(h.out)
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:           # noqa: BLE001
                pass
        # bounded-cardinality: phase names are call-site string
        # literals (the timing.phase sites in this repo)
        _obs.timer(name).add(time.monotonic() - t0)
        if tracer is not None:
            # same block, same clock stop: every phase is also a span
            # in the cross-thread trace (obs/trace.py) — the ingest
            # worker's phases land on their own tid row
            tracer.complete(name, "phase", span_t0)


def add(name: str, seconds: float) -> None:
    # bounded-cardinality: caller-literal timer names (bench phases)
    _obs.timer(name).add(seconds)


def reset() -> None:
    _obs.default_registry().reset_timers()


def seconds(prefix: str) -> float:
    """Total accumulated seconds of every phase whose name starts with
    ``prefix`` (e.g. "autotune" sums all per-kernel tuning phases)."""
    return sum(total for name, total, _, _ in
               _obs.default_registry().timer_items()
               if name.startswith(prefix))


def _sync(out) -> None:
    """Force completion of a dispatched jax computation with a real
    device->host scalar readback: block_until_ready alone has been
    observed returning early on RPC-tunneled backends (bench.py), and
    the transfer stream is ordered, so one scalar drains the queue."""
    import numpy as np
    try:
        import jax
        leaves = [x for x in jax.tree_util.tree_leaves(out)
                  if hasattr(x, "dtype")]
    except ImportError:
        leaves = []
    if leaves:
        x = leaves[0]
        np.asarray(x.ravel()[:1] if getattr(x, "ndim", 0) else x)
        _obs.counter("transfer/d2h_syncs").add(1)


def measure(fn, *args, repeats: int = 5, warmup: int = 1) -> float:
    """Median-of-``repeats`` wall seconds of ``fn(*args)`` with a device
    sync per call — the autotuner's measurement harness (the reference
    times its GPU kernel variants the same way, docs/GPU-Performance).
    ``warmup`` untimed calls absorb compilation."""
    for _ in range(max(warmup, 0)):
        _sync(fn(*args))
    times = []
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        _sync(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def report() -> str:
    """One line per phase, sorted by total seconds DESCENDING so the
    dominant phase is always the first line; columns: total, calls,
    mean, max."""
    items = sorted(_obs.default_registry().timer_items(),
                   key=lambda r: -r[1])
    lines = []
    for name, total, n, mx in items:
        n = max(n, 1)
        lines.append(f"  {name:<24s} {total:9.3f} s  ({n} calls, "
                     f"{1000.0 * total / n:.2f} ms avg, "
                     f"{1000.0 * mx:.2f} ms max)")
    return "\n".join(lines)


def log_report(header: str = "phase timings") -> None:
    """Log and RESET — each report covers one run's deltas."""
    body = report()
    if body:
        log.info("%s:\n%s", header, body)
        reset()
