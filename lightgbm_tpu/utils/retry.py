"""Bounded retry with exponential backoff + jitter for transient
failures.

The transfer and ingest paths talk to a device runtime over RPC; under
memory pressure or a busy tunnel those calls fail with *transient*
errors (``RESOURCE_EXHAUSTED``, ``DEADLINE_EXCEEDED``, ``UNAVAILABLE``)
that succeed moments later. This module is the one policy for
absorbing them: retry with exponential backoff and deterministic
jitter, give up after a bounded number of attempts, and count every
decision in the obs registry (``retry/attempts``, ``retry/retries``,
``retry/giveups``) so a live run's flakiness is visible in the
Prometheus export instead of buried in logs.

Classification is conservative: only errors that *say* they are
transient (the grpc/absl status strings above, the jax.distributed /
DCN bootstrap strings — coordinator connect refused, barrier timeout,
heartbeat loss — stdlib connection timeouts, or an injected
``InjectedFault(transient=True)`` from utils/faults.py) are retried —
a genuine bug fails fast on attempt 1.

Stdlib + obs only; importing this module never touches jax.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional

from . import log
from .faults import InjectedFault

# substrings of transient device-runtime/RPC failures (grpc/absl status
# names surface verbatim in XlaRuntimeError messages)
TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "Connection reset",
    "Socket closed",
    # jax.distributed / DCN bootstrap blips (parallel/cluster.py): a
    # coordinator that is still binding its port, restarting after a
    # preemption, or mid-handshake surfaces these — worth backoff, not
    # an attempt-1 giveup. Kept SPECIFIC (full service/phrase strings),
    # so a genuine config error ("connection" in some unrelated text)
    # still fails fast.
    "Connection refused",               # coordinator not listening yet
    "failed to connect to all addresses",   # grpc channel not up
    "Barrier timed out",                # peers still arriving
    "heartbeat timeout",                # coordination-service blip
    "Heartbeat timeout",
    "coordination service",             # service restarting
    "Coordination service",
    # fleet scoring-daemon client blips (serve/client.py): a daemon
    # mid model-swap or mid-restart drops the socket with these exact
    # stdlib phrases (http.client.RemoteDisconnected / socket.timeout
    # surfaced through urllib). Scoring requests are idempotent, so a
    # bounded retry is always safe.
    "Remote end closed connection",     # daemon dropped mid-response
    "Read timed out",                   # response overdue, socket alive
)


def is_transient(exc: BaseException) -> bool:
    """True when ``exc`` is worth retrying (see module docstring)."""
    if isinstance(exc, InjectedFault):
        return bool(exc.transient)
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    msg = str(exc)
    return any(m in msg for m in TRANSIENT_MARKERS)


class RetryPolicy:
    """Backoff shape: ``attempts`` total tries, delay
    ``base_s * 2**k`` capped at ``max_s``, plus up to ``jitter`` of
    that delay from a seeded RNG (deterministic for a given seed —
    drills reproduce; production leaves seed=None for wall-clock
    entropy)."""

    def __init__(self, attempts: int = 4, base_s: float = 0.05,
                 max_s: float = 2.0, jitter: float = 0.5,
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.attempts = max(int(attempts), 1)
        self.base_s = max(float(base_s), 0.0)
        self.max_s = max(float(max_s), self.base_s)
        self.jitter = max(float(jitter), 0.0)
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delay_s(self, retry_index: int) -> float:
        """Backoff before retry ``retry_index`` (0-based)."""
        d = min(self.base_s * (2.0 ** retry_index), self.max_s)
        return d * (1.0 + self.jitter * self._rng.random())

    def sleep(self, retry_index: int) -> float:
        d = self.delay_s(retry_index)
        if d > 0:
            self._sleep(d)
        return d


DEFAULT_POLICY = RetryPolicy()


def call(fn: Callable, *, what: str = "operation",
         policy: Optional[RetryPolicy] = None,
         classify: Callable[[BaseException], bool] = is_transient):
    """Run ``fn()``; retry transient failures per ``policy``. The final
    transient failure (or any non-transient one) re-raises unchanged —
    callers see the real error, plus a ``gave up`` log line carrying
    ``what`` and the attempt count."""
    from ..obs import registry as obs
    p = policy or DEFAULT_POLICY
    for attempt in range(1, p.attempts + 1):
        obs.counter("retry/attempts").add(1)
        try:
            return fn()
        except BaseException as e:      # noqa: BLE001 — classified below
            if not classify(e):
                raise
            if attempt >= p.attempts:
                obs.counter("retry/giveups").add(1)
                log.warning("%s: gave up after %d attempts (%s: %s)",
                            what, attempt, type(e).__name__, e)
                raise
            obs.counter("retry/retries").add(1)
            d = p.sleep(attempt - 1)
            log.warning("%s: transient failure (attempt %d/%d, retrying "
                        "in %.2fs): %s", what, attempt, p.attempts, d, e)
