"""Resumable training checkpoints: the model text PLUS the state the
model text lacks.

The ``snapshot_freq`` model snapshots are *predict*-grade: restarting
from one loses the bagging RNG stream, the early-stopping bookkeeping
and the eval history, so the restarted run diverges from the run that
died. A *checkpoint bundle* captures everything ``GBDT.train`` needs to
continue **bit-identically** (the repo's house parity bar — proven by
tests/test_faults.py's kill-and-resume drill, serial and sharded):

- the serialized model text (device TreeRecords are rebuilt from it on
  resume, exactly like ``init_from_loaded``);
- the live train/valid SCORE BUFFERS, verbatim, in a compressed
  ``.scores.npz`` sidecar. This is the one piece of state that CANNOT
  be re-derived: XLA fuses each iteration's shrinkage fold into the
  score gather-add (contraction skips the stored outputs' intermediate
  rounding), so replaying the saved trees lands within ~1 ulp of — but
  not bit-equal to — the live scores, and ulp drift in scores becomes
  ulp drift in every later tree. Saving the buffers makes resume
  bit-identical by construction, on every backend;
- the iteration index and every host RNG stream: bagging, feature
  fraction, the GOSS hook RNG and DART's drop RNG (numpy Generator
  ``bit_generator.state`` dicts — plain ints, JSON-safe);
- the *current* bagging mask (``bagging_freq > 1`` reuses one draw for
  several iterations; a resume inside the window must reuse the same
  mask, not redraw);
- early-stopping bookkeeping (best score/iteration/message per metric)
  and the run's eval history, in the uninterrupted run's global
  iteration numbering;
- DART's tree-weight algebra and live shrinkage;
- the training config fingerprint (mismatch = refusal with an
  actionable message, not a silent divergence) and a step-cache
  geometry summary for diagnostics.

Format: one versioned JSON document per ``ckpt_iter_<N>.json`` plus a
``ckpt_iter_<N>.scores.npz`` sidecar, both written via
``utils/fileio.atomic_write`` — sidecar FIRST, bundle second, so the
bundle is the commit point (a crash between the writes leaves an
orphan sidecar, never a bundle pointing at a missing one) — and pruned
to the last ``tpu_snapshot_keep``. Readers follow the run-report
discipline
(obs/recorder.py): schema/version are checked first and a future or
corrupt layout is refused with a one-line error naming the file, what
is malformed and the expected version.

This module is a *friend* of models/gbdt.py — it reaches into the
booster's private training state deliberately, so the whole
gather/apply inventory lives in one reviewable place.
"""
from __future__ import annotations

import base64
import glob
import hashlib
import json
import os
import re
import time
from typing import List, Optional, Tuple

import numpy as np

from . import faults, log
from .fileio import atomic_write, prune_numbered

CHECKPOINT_SCHEMA = "lightgbm-tpu/checkpoint"
CHECKPOINT_VERSION = 1

_CKPT_RE = re.compile(r"ckpt_iter_(\d+)\.json$")

# config fields excluded from the resume fingerprint: paths, telemetry
# and the fault-tolerance knobs themselves — none shape the training
# math, and a resumed run must be free to redirect its artifacts (or
# extend num_iterations) without tripping the mismatch refusal
VOLATILE_KNOBS = frozenset({
    "config", "data", "valid", "task", "num_iterations",
    "output_model", "snapshot_freq", "input_model", "output_result",
    "verbosity",
    "tpu_run_report", "tpu_trace", "tpu_trace_buffer",
    "tpu_metrics_export", "tpu_metrics_interval_s", "tpu_metrics_port",
    "tpu_profile_dir", "tpu_profile_iters", "tpu_watchdog_factor",
    "tpu_autotune", "tpu_tuning_cache", "tpu_compile_cache",
    "tpu_checkpoint_dir", "tpu_checkpoint_freq", "tpu_snapshot_keep",
    "tpu_resume_from", "tpu_faults", "tpu_fault_seed",
    "tpu_retry_attempts",
    "tpu_reqlog", "tpu_reqlog_sample", "tpu_slo", "tpu_flight_buffer",
    "tpu_flight_dir", "tpu_cluster_obs",
    # cluster topology (parallel/cluster.py): ELASTIC resume is the
    # whole point — a checkpoint written by a 4-process run must
    # restore under 2 processes (or 1) without a fingerprint refusal,
    # and every process carries its own rank. num_machines (the
    # reference alias, doubling as the in-process virtual-mesh cap) is
    # topology too: the autoscale controller (parallel/elastic.py)
    # re-shards across it at window boundaries
    "num_machines", "tpu_num_machines", "tpu_machine_rank",
    "tpu_coordinator", "tpu_collective_timeout_s",
    # transport/scheduling knobs (parallel/learners.py packed wire,
    # slot psum; this module's background writer): every setting is
    # proven BIT-identical to its synchronous/wide twin, so none shape
    # the training math — a checkpoint written under int16 wire +
    # async slots restores under the legacy wire and vice versa
    "tpu_psum_wire", "tpu_async_psum", "tpu_ckpt_async",
    # fleet-serving topology (serve/): where the scoring daemon
    # listens, how long the coalescer lingers, queue depths and
    # admission-SLO thresholds — pure serving-plane settings; the
    # models a checkpoint restores are trained identically under any
    # of them
    "tpu_fleet_port", "tpu_fleet_coalesce_us", "tpu_fleet_max_batch",
    "tpu_fleet_queue", "tpu_fleet_slo_p99_ms", "tpu_fleet_shed_budget",
})


def config_fingerprint(config) -> str:
    """Short sha256 over the training-relevant config fields (sorted
    ``name=value`` lines, VOLATILE_KNOBS excluded)."""
    import dataclasses
    lines = []
    for f in sorted(dataclasses.fields(config), key=lambda f: f.name):
        if f.name in VOLATILE_KNOBS or f.name.startswith("_"):
            continue
        v = getattr(config, f.name)
        if isinstance(v, list):
            v = ",".join(str(x) for x in v)
        lines.append(f"{f.name}={v}")
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()[:16]


def checkpoint_path(directory: str, iteration: int) -> str:
    return os.path.join(directory, f"ckpt_iter_{int(iteration)}.json")


def scores_path(bundle_path: str) -> str:
    """The score-buffer sidecar next to a bundle path."""
    return bundle_path[: -len(".json")] + ".scores.npz" \
        if bundle_path.endswith(".json") else bundle_path + ".scores.npz"


def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """(iteration, path) pairs under ``directory``, newest first.
    The directory is caller data — escaped, so a path containing
    glob metacharacters still lists its own checkpoints."""
    out = []
    for p in glob.glob(os.path.join(glob.escape(directory),
                                    "ckpt_iter_*.json")):
        m = _CKPT_RE.search(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out, reverse=True)


def prune_checkpoints(directory: str, keep: int) -> None:
    """Delete all but the newest ``keep`` checkpoints, sidecars
    included (best-effort; utils/fileio.prune_numbered — the same
    helper the model-snapshot prune uses). Orphan sidecars — a crash
    between the sidecar write and the bundle commit leaves a
    ``.scores.npz`` with no bundle — are swept too: they are multi-MB
    and no bundle will ever claim their iteration number again."""
    prune_numbered(os.path.join(directory, ""), "ckpt_iter_*.json",
                   r"ckpt_iter_(\d+)\.json$", keep,
                   companions=lambda p: [scores_path(p)])
    for p in glob.glob(os.path.join(glob.escape(directory),
                                    "ckpt_iter_*.scores.npz")):
        if not os.path.isfile(p[: -len(".scores.npz")] + ".json"):
            try:
                os.unlink(p)
            except OSError:
                pass


def mapper_fingerprint(mappers) -> str:
    """Short sha256 over the serialized bin mappers — restore refuses
    a dataset binned differently from the checkpointed run (device
    TreeRecords are rebuilt from model text THROUGH the resuming
    dataset's mappers, so silently different boundaries would shift
    every restored threshold)."""
    blob = json.dumps([m.to_dict() for m in mappers], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def mappers_from_bundle(bundle: dict):
    """The checkpointed run's bin mappers as a FULL per-real-column
    list (trivial placeholders on unused columns), ready for
    ``construct_from_matrix(mappers=...)`` — how an elastic resume
    onto a different world size reconstructs the EXACT binning of the
    original run (parallel/elastic.py). None when the bundle predates
    the mapper record."""
    rec = bundle.get("mappers")
    if not rec:
        return None
    from ..io.binning import BinMapper
    used = [int(j) for j in rec["used"]]
    full = [BinMapper() for _ in range(int(rec["num_total_features"]))]
    for j, d in zip(used, rec["mappers"]):
        full[j] = BinMapper.from_dict(d)
    return full


# -- state gather/apply (the GBDT-private inventory) -------------------------

def _rng_state(gen) -> Optional[dict]:
    """numpy Generator -> its bit_generator state dict (JSON-safe
    ints), or None for absent/stand-in generators."""
    if gen is None or not hasattr(gen, "bit_generator"):
        return None
    return gen.bit_generator.state


def _set_rng_state(gen, state) -> None:
    if gen is not None and state is not None \
            and hasattr(gen, "bit_generator"):
        gen.bit_generator.state = state


def _pack_mask(mask) -> Optional[dict]:
    """0/1 float mask -> {n, b64-packed-bits}; None passes through."""
    if mask is None:
        return None
    m = np.asarray(mask)
    return {"n": int(m.shape[0]),
            "bits": base64.b64encode(
                np.packbits(m > 0.5).tobytes()).decode()}


def _unpack_mask(rec) -> Optional[np.ndarray]:
    if rec is None:
        return None
    n = int(rec["n"])
    bits = np.frombuffer(base64.b64decode(rec["bits"]), np.uint8)
    return np.unpackbits(bits)[:n].astype(np.float32)


def gather_state(booster) -> dict:
    """Everything past the model text that a bit-identical resume
    needs (see module docstring for the inventory)."""
    state = {
        "rng": {
            "bagging": _rng_state(getattr(booster, "_bagging_rng",
                                          None)),
            "feature": _rng_state(getattr(booster, "_feature_rng",
                                          None)),
            "hook": _rng_state(getattr(booster, "_hook_rng", None)),
            "drop": _rng_state(getattr(booster, "_drop_rng", None)),
        },
        "bag_cache": _pack_mask(getattr(booster, "_bag_cache", None)),
        "shrinkage_rate": float(booster.shrinkage_rate),
        "boost_from_avg_done": list(
            getattr(booster, "_boost_from_avg_done", [])),
        "best_score": getattr(booster, "_best_score", None),
        "best_iter": getattr(booster, "_best_iter", None),
        "best_msg": getattr(booster, "_best_msg", None),
        "eval_history": list(getattr(booster, "_eval_history", [])),
    }
    if hasattr(booster, "_tree_weight"):        # DART
        state["dart"] = {
            "tree_weight": [float(w) for w in booster._tree_weight],
            "sum_weight": float(booster._sum_weight),
        }
    return state


def apply_state(booster, state: dict) -> None:
    rng = state.get("rng", {})
    _set_rng_state(getattr(booster, "_bagging_rng", None),
                   rng.get("bagging"))
    _set_rng_state(getattr(booster, "_feature_rng", None),
                   rng.get("feature"))
    _set_rng_state(getattr(booster, "_hook_rng", None), rng.get("hook"))
    _set_rng_state(getattr(booster, "_drop_rng", None), rng.get("drop"))
    mask = _unpack_mask(state.get("bag_cache"))
    if mask is not None:
        booster._bag_cache = mask
    booster.shrinkage_rate = float(state.get(
        "shrinkage_rate", booster.shrinkage_rate))
    done = state.get("boost_from_avg_done")
    if done is not None and hasattr(booster, "_boost_from_avg_done"):
        booster._boost_from_avg_done = [bool(x) for x in done]
    for attr in ("best_score", "best_iter", "best_msg"):
        if state.get(attr) is not None:
            setattr(booster, "_" + attr, state[attr])
    booster._eval_history = [tuple(x) for x in
                             state.get("eval_history", [])]
    dart = state.get("dart")
    if dart is not None and hasattr(booster, "_tree_weight"):
        booster._tree_weight = list(dart["tree_weight"])
        booster._sum_weight = float(dart["sum_weight"])


def _geometry_summary(booster) -> dict:
    """The step-cache geometry this booster trains under — diagnostics
    for 'why did my resumed run recompile' questions, not a resume
    precondition (a hit on resume is expected, not required)."""
    gcfg = getattr(booster, "_grower_cfg", None)
    return {
        "n_score": int(getattr(booster, "_n_score", 0)),
        "n_total": int(getattr(booster, "_n_total", 0)),
        "f_pad": int(getattr(booster, "_f_pad", 0)),
        "num_bins": int(gcfg.num_bins) if gcfg else None,
        "wave_size": int(gcfg.wave_size) if gcfg else None,
        "learner": booster.learner_mode,
        "devices": booster.num_devices,
        "cache_eligible": bool(getattr(booster, "_cache_eligible",
                                       False)),
    }


# -- bundle IO ---------------------------------------------------------------

def _commit_bundle(directory: str, path: str, arrays: dict,
                   bundle: dict, keep: int) -> str:
    """The host-local write phase: scores sidecar FIRST, bundle second
    (the bundle is the commit point), prune, count. Runs on the
    caller's thread for synchronous checkpoints and on the
    AsyncCheckpointWriter thread for background ones — commit-point
    ordering is identical either way."""
    with atomic_write(scores_path(path), mode="wb") as fh:
        np.savez_compressed(fh, **arrays)
    with atomic_write(path) as fh:
        json.dump(bundle, fh)
    prune_checkpoints(directory, keep)
    from ..obs import registry as obs
    obs.counter("checkpoint/writes").add(1)
    log.info("checkpoint written: %s (iteration %d, keep %d)",
             path, int(bundle["iteration"]), keep)
    return path


class AsyncCheckpointWriter:
    """Bounded-queue background writer for checkpoint bundles
    (tpu_ckpt_async): the COLLECTIVE score gather and the host-side
    bundle construction stay on the training thread (save_checkpoint);
    only the serialization + atomic file writes — the slow,
    filesystem-bound tail — run here, off the critical path.

    Semantics preserved from the synchronous path:

    - commit-point ordering: jobs run strictly in submission order on
      ONE thread, and each job writes sidecar-then-bundle via
      atomic_write, so a crash (even SIGKILL mid-write) never leaves a
      torn bundle and the newest complete bundle is always a valid
      restart point;
    - ``checkpoint/write_failures``: a failed background write warns
      and bumps the same counter the synchronous path does — training
      never stops for a full disk;
    - a full queue drops the OLDEST not-yet-started job (the newer
      checkpoint supersedes it — exactly what prune would do moments
      later) instead of blocking the training thread.

    ``drain()`` must run at train end and before any resume read
    (resolve_resume calls ``drain_writers()`` itself as a backstop).
    """

    def __init__(self, maxsize: int = 2):
        import collections
        import threading
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._jobs: "collections.deque" = \
            collections.deque()        # guarded-by: _lock
        self._maxsize = max(int(maxsize), 1)
        self._busy = False             # guarded-by: _lock
        self._closed = False           # guarded-by: _lock
        self._failures = 0             # guarded-by: _lock
        self._write_s = 0.0            # guarded-by: _lock
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True)
        self._thread.start()

    def submit(self, directory: str, path: str, arrays: dict,
               bundle: dict, keep: int) -> bool:
        """Enqueue one write job; never blocks on a slow disk."""
        from ..obs import registry as obs
        with self._lock:
            if self._closed:
                return False
            if len(self._jobs) >= self._maxsize:
                dropped = self._jobs.popleft()
                log.debug("checkpoint writer queue full: dropping "
                          "queued write %s (superseded by %s)",
                          dropped[1], path)
            self._jobs.append((directory, path, arrays, bundle, keep))
            obs.gauge("ckpt/queue_depth").set(len(self._jobs))
            self._wake.notify_all()
        return True

    def _run(self) -> None:
        from ..obs import registry as obs
        while True:
            with self._lock:
                while not self._jobs and not self._closed:
                    self._wake.wait()
                if not self._jobs and self._closed:
                    return
                job = self._jobs.popleft()
                self._busy = True
                obs.gauge("ckpt/queue_depth").set(len(self._jobs))
            t0 = time.monotonic()
            committed = False
            try:
                _commit_bundle(job[0], job[1], job[2], job[3], job[4])
                committed = True
            except Exception as e:       # same downgrade as the sync
                # path's caller: warn + count, never stop training
                obs.counter("checkpoint/write_failures").add(1)
                log.warning("background checkpoint write failed "
                            "(training continues): %s", e)
                with self._lock:
                    self._failures += 1
            finally:
                dt = time.monotonic() - t0
                obs.counter("ckpt/hidden_s").add(dt)
                if committed:
                    # instant on the trace timeline: the off-thread
                    # commit is visible WHERE it landed relative to
                    # the training iterations it hid behind
                    from ..obs import trace as obs_trace
                    obs_trace.instant(
                        "ckpt/async_commit", cat="ckpt",
                        args={"path": job[1],
                              "iteration": job[3].get("iteration"),
                              "write_s": round(dt, 6)})
                with self._lock:
                    self._busy = False
                    self._write_s += dt
                    self._wake.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job has committed (or failed).
        True = drained; False = timed out with work still pending."""
        import time as _time
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._lock:
            while self._jobs or self._busy:
                rem = None if deadline is None \
                    else deadline - _time.monotonic()
                if rem is not None and rem <= 0:
                    return False
                self._wake.wait(rem)
        return True

    def close(self, timeout: Optional[float] = None) -> bool:
        """Drain, then stop the thread. Safe to call twice."""
        ok = self.drain(timeout)
        with self._lock:
            self._closed = True
            self._wake.notify_all()
        self._thread.join(timeout)
        return ok and not self._thread.is_alive()

    @property
    def failures(self) -> int:
        with self._lock:
            return self._failures

    @property
    def write_seconds(self) -> float:
        """Total seconds of write work hidden from the training path."""
        with self._lock:
            return self._write_s


# every live writer, so resolve_resume can drain pending writes it
# did not create (a resume may read a directory another booster in
# this process is still writing to)
_writers: List[AsyncCheckpointWriter] = []   # guarded-by: _writers_lock
import threading as _threading
_writers_lock = _threading.Lock()


def new_writer(maxsize: int = 2) -> AsyncCheckpointWriter:
    w = AsyncCheckpointWriter(maxsize=maxsize)
    with _writers_lock:
        _writers.append(w)
    return w


def drain_writers(timeout: Optional[float] = None) -> None:
    """Drain every live background writer — called at train end and
    before any resume read, so a resume never races a pending write."""
    with _writers_lock:
        ws = list(_writers)
    for w in ws:
        w.drain(timeout)


def save_checkpoint(booster, directory: str, keep: int = 3,
                    writer: Optional[AsyncCheckpointWriter] = None,
                    ) -> Optional[str]:
    """Write ``ckpt_iter_<N>.scores.npz`` then ``ckpt_iter_<N>.json``
    (the bundle is the commit point) and prune to ``keep``; returns
    the bundle path. Raises on failure — the caller (the training
    loop) downgrades that to a warning so a full disk never takes
    training down, and the atomic writes guarantee the previous
    complete checkpoint survives. With ``writer`` the file writes are
    handed to the background writer thread (gather + bundle
    construction still happen here, on-path — the collective part and
    the snapshot-consistent view of the booster's mutable state)."""
    from ..parallel import cluster
    eff = booster._effective_num_models()
    if eff != len(booster.models):
        # trailing splitless trees: serialization would trim them while
        # the scores still carry their contributions — and training is
        # about to stop anyway (gbdt.cpp:393-409)
        log.info("checkpoint skipped at iteration %d: model has "
                 "trailing splitless trees (training is stopping)",
                 booster.current_iteration)
        return None
    it = booster.current_iteration
    path = checkpoint_path(directory, it)
    faults.check("checkpoint.write", context=f"iteration {it}")
    # the gather is COLLECTIVE under a multi-process mesh (sharded
    # score buffers all-gather to every host) — all ranks must reach
    # it; only rank 0 then serializes anything or touches the
    # filesystem (bundle construction below is host-local work the
    # other ranks would discard)
    arrays = {"scores": cluster.fetch(booster._scores)}
    for vi, vs in enumerate(booster._valid_scores):
        arrays[f"valid_{vi}"] = cluster.fetch(vs)
    if cluster.rank() != 0:
        return None
    bundle = {
        "schema": CHECKPOINT_SCHEMA,
        "version": CHECKPOINT_VERSION,
        "created_unix": round(time.time(), 3),
        "iteration": int(it),
        "config_hash": config_fingerprint(booster.config),
        "parameters": booster.config.to_string(),
        "geometry": _geometry_summary(booster),
        # world-size awareness (elastic resume): the score buffers
        # above are GLOBAL and in original row order regardless of how
        # many processes trained, so a different-size cluster can
        # re-shard them (restore's elastic path). n_real is the true
        # row count; columns past it are bucket/shard pad.
        "world": {
            "processes": cluster.world(),
            "devices": booster.num_devices,
            "n_real": int(getattr(booster, "_n", 0)),
            "n_score": int(getattr(booster, "_n_score", 0)),
            # per-valid-set true row counts: the elastic path needs
            # them to re-shard valid buffers whose widths (like the
            # train width) depend on the world size
            "valid_n_real": [int(v.num_data) for v in
                             getattr(booster, "valid_sets", [])],
        },
        "state": gather_state(booster),
        # the run's agreed bin mappers: an elastic resume constructs
        # its dataset with EXACTLY these (mappers_from_bundle), and
        # restore refuses a dataset binned differently (see
        # mapper_fingerprint)
        "mappers": {
            "used": [int(j) for j in
                     booster.train_data.used_feature_map],
            "num_total_features": int(
                booster.train_data.num_total_features),
            "mappers": [m.to_dict()
                        for m in booster.train_data.mappers],
            "hash": mapper_fingerprint(booster.train_data.mappers),
        },
        "scores_file": os.path.basename(scores_path(path)),
        "model": booster.model_to_string(),
    }
    # who wrote the bundle (obs/identity.py) — postmortem provenance,
    # NOT part of the resume fingerprint: config_fingerprint hashes the
    # config, never this bundle, so a rank-0 write restores anywhere
    from ..obs import identity
    bundle["identity"] = identity.identity()
    if writer is not None:
        writer.submit(directory, path, arrays, bundle, keep)
        return path
    return _commit_bundle(directory, path, arrays, bundle, keep)


def load_checkpoint(path: str) -> dict:
    """Parse + validate one checkpoint bundle. Every failure is a
    one-line ValueError naming the file, what is malformed, and the
    version this reader expects — never a deep parse traceback."""
    try:
        with open(path) as fh:
            bundle = json.load(fh)
    except OSError as e:
        raise ValueError(f"{path}: cannot read checkpoint ({e})") from e
    except json.JSONDecodeError as e:
        raise ValueError(
            f"{path}: corrupt checkpoint (truncated or not JSON: {e}); "
            f"expected schema {CHECKPOINT_SCHEMA} v{CHECKPOINT_VERSION}"
        ) from e
    if not isinstance(bundle, dict):
        raise ValueError(f"{path}: not a checkpoint bundle (top level "
                         f"is {type(bundle).__name__}, expected an "
                         f"object)")
    if bundle.get("schema") != CHECKPOINT_SCHEMA:
        raise ValueError(f"{path}: not a checkpoint bundle "
                         f"(schema={bundle.get('schema')!r}; expected "
                         f"{CHECKPOINT_SCHEMA})")
    if bundle.get("version") != CHECKPOINT_VERSION:
        raise ValueError(
            f"{path}: checkpoint version {bundle.get('version')!r}, "
            f"this reader wants {CHECKPOINT_VERSION} — refusing to "
            f"misread a different layout")
    for key in ("iteration", "model", "state", "config_hash",
                "scores_file"):
        if key not in bundle:
            raise ValueError(f"{path}: malformed checkpoint (missing "
                             f"{key!r}); expected schema "
                             f"{CHECKPOINT_SCHEMA} v{CHECKPOINT_VERSION}")
    sidecar = os.path.join(os.path.dirname(os.path.abspath(path)),
                           str(bundle["scores_file"]))
    if not os.path.isfile(sidecar):
        raise ValueError(f"{path}: score sidecar "
                         f"{bundle['scores_file']!r} is missing next to "
                         f"the bundle (partial copy? crash between "
                         f"writes?)")
    bundle["_scores_path"] = sidecar
    return bundle


def resolve_resume(path_or_dir: str) -> dict:
    """A checkpoint file loads directly; a directory resolves to its
    NEWEST valid checkpoint — corrupt/newer-layout bundles are skipped
    with a warning (a crash mid-write plus atomic_write means the
    newest complete one is the right restart point). Pending
    background writes are drained FIRST, so a resume in the same
    process never reads past a checkpoint still in a writer queue."""
    drain_writers()
    if os.path.isdir(path_or_dir):
        entries = list_checkpoints(path_or_dir)
        if not entries:
            raise ValueError(f"{path_or_dir}: no ckpt_iter_*.json "
                             f"checkpoints to resume from")
        errors = []
        for it, p in entries:
            try:
                return load_checkpoint(p)
            except ValueError as e:
                errors.append(str(e))
                log.warning("skipping unusable checkpoint: %s", e)
        raise ValueError(f"{path_or_dir}: no usable checkpoint "
                         f"({'; '.join(errors)})")
    return load_checkpoint(path_or_dir)


def restore(booster, bundle: dict) -> int:
    """Apply a loaded bundle to an ``init()``-ed booster: refuse a
    config mismatch, rebuild device TreeRecords from the model text,
    load the train/valid score buffers VERBATIM from the sidecar (the
    bit-identity guarantee — see module docstring), then restore the
    host-side state. Returns the iteration to continue from."""
    import jax.numpy as jnp

    from ..models.gbdt import GBDT
    from ..models.tree import record_arrays_from_tree
    from ..ops.grower import TreeRecord

    want = config_fingerprint(booster.config)
    have = bundle.get("config_hash")
    if have != want:
        raise ValueError(
            f"checkpoint was written under a different training config "
            f"(hash {have} vs this run's {want}); resume requires "
            f"identical training parameters — diff the checkpoint's "
            f"'parameters' block against your run, or point "
            f"tpu_checkpoint_dir at a fresh directory to start over")
    mrec = bundle.get("mappers")
    if mrec and mrec.get("hash"):
        have_h = mapper_fingerprint(booster.train_data.mappers)
        if have_h != mrec["hash"]:
            raise ValueError(
                f"checkpoint was binned with different bin mappers "
                f"(hash {mrec['hash']} vs this dataset's {have_h}) — "
                f"restored tree thresholds would shift; construct the "
                f"resuming dataset with the checkpoint's mappers "
                f"(utils/checkpoint.mappers_from_bundle — the elastic "
                f"driver parallel/elastic.py does this automatically)")
    scratch = GBDT()
    scratch.load_model_from_string(bundle["model"],
                                   source="checkpoint model text")
    loaded = scratch.models
    K = booster.num_tree_per_iteration
    if scratch.num_tree_per_iteration != K:
        raise ValueError(
            f"checkpoint num_tree_per_iteration="
            f"{scratch.num_tree_per_iteration} does not match this "
            f"run's {K} (num_class/objective changed?)")

    # score buffers: the live device state, not a replay
    from ..parallel import cluster
    spath = bundle.get("_scores_path") or bundle.get("scores_file")
    try:
        with np.load(spath) as z:
            scores = z["scores"]
            valids = [z[f"valid_{vi}"] for vi in
                      range(len(booster._valid_scores))]
    except (OSError, KeyError, ValueError) as e:
        raise ValueError(f"{spath}: unusable score sidecar "
                         f"({type(e).__name__}: {e})") from e
    want_shape = tuple(np.shape(booster._scores))
    if tuple(scores.shape) != want_shape:
        wrec = bundle.get("world") or {}
        old_world = wrec.get("processes")
        n_real = int(wrec.get("n_real", 0) or 0)
        new_world = cluster.world()
        if (n_real and n_real == int(getattr(booster, "_n", 0))
                and scores.shape[0] == want_shape[0]
                and scores.shape[1] >= n_real
                and want_shape[1] >= n_real):
            # ELASTIC RE-SHARD (ops/step_cache.py shard_align_unit):
            # same data, different world — the score width is just the
            # row bucket for the new shard geometry. Real rows copy
            # verbatim (bit-identity for everything the step reads);
            # the pad region keeps this run's fresh-init values — pad
            # scores are write-only (rvalid zeroes their gradients and
            # nothing downstream reads them).
            fresh = np.array(cluster.fetch(booster._scores))
            fresh[:, :n_real] = scores[:, :n_real]
            scores = fresh
            # an elastic re-shard starts a new INCARNATION of this
            # process's lifetime (obs/identity.py): every telemetry
            # record after this instant is distinguishable from the
            # pre-reshard stream it would otherwise blend into
            from ..obs import identity, trace as obs_trace
            inc = identity.bump_incarnation(
                f"elastic re-shard world {old_world} -> {new_world}")
            obs_trace.instant(
                "elastic/reshard", cat="cluster",
                args={"from_world": old_world, "to_world": new_world,
                      "incarnation": inc})
            log.info("elastic resume: re-sharded checkpoint scores "
                     "from world=%s (%s devices, width %d) onto "
                     "world=%d (%d devices, width %d) — %d real rows "
                     "carried verbatim", old_world,
                     wrec.get("devices", "?"),
                     int(wrec.get("n_score", 0) or 0) or -1,
                     new_world, booster.num_devices, want_shape[1],
                     n_real)
        elif old_world is not None and int(old_world) != new_world:
            raise ValueError(
                f"{spath}: checkpoint was written by a "
                f"{old_world}-process run (score width "
                f"{scores.shape[1]}) and this run has {new_world} "
                f"process(es) (width {want_shape[1]}) over a "
                f"different row count — elastic re-shard needs the "
                f"SAME training data (same rows in the same order); "
                f"re-point tpu_resume_from at a checkpoint of this "
                f"dataset or retrain from scratch")
        else:
            raise ValueError(
                f"{spath}: score buffer shape {tuple(scores.shape)} "
                f"does not match this run's {want_shape} — same data "
                f"and tpu_row_bucket policy are required to resume")
    vreal = [int(x) for x in
             (bundle.get("world") or {}).get("valid_n_real", [])]
    for vi, v in enumerate(valids):
        have_v = tuple(np.shape(booster._valid_scores[vi]))
        if tuple(v.shape) != have_v:
            nv = vreal[vi] if vi < len(vreal) else 0
            same_rows = (nv and vi < len(booster.valid_sets)
                         and nv == int(booster.valid_sets[vi].num_data)
                         and v.shape[0] == have_v[0]
                         and v.shape[1] >= nv and have_v[1] >= nv)
            if same_rows:
                # same elastic rule as the train buffer: real rows
                # verbatim, pad keeps this run's fresh-init values
                fresh_v = np.array(cluster.fetch(
                    booster._valid_scores[vi]))
                fresh_v[:, :nv] = v[:, :nv]
                valids[vi] = fresh_v
                continue
            raise ValueError(
                f"{spath}: valid_{vi} score shape {tuple(v.shape)} "
                f"does not match this run's {have_v} — add the same "
                f"valid sets before resuming")

    L = booster._grower_cfg.num_leaves
    td = booster.train_data
    booster.models = list(loaded)
    booster.records = []
    booster._tree_shrinkage = [m.shrinkage if m.shrinkage else 1.0
                               for m in loaded]
    for tree in loaded:
        arrs = record_arrays_from_tree(tree, td.real_to_inner,
                                       td.mappers, L)
        booster.records.append(TreeRecord(
            **{k: jnp.asarray(v) for k, v in arrs.items()}))
    booster._scores = booster._place_scores(scores)
    booster._valid_scores = [booster._place_scores(v) for v in valids]
    booster.iter_ = len(loaded) // K
    booster._clean_groups = booster.iter_
    booster._bump_model_gen()
    apply_state(booster, bundle.get("state", {}))
    log.info("resumed from checkpoint at iteration %d (%d trees, "
             "config hash %s)", booster.iter_, len(loaded), want)
    return booster.iter_
