"""Atomic file replacement — the one copy of the tmp+rename pattern.

Every on-disk artifact the engine writes concurrently-readably (run
reports, tuning cache, span traces, Prometheus textfiles) follows the
same discipline: write to ``<path>.tmp.<pid>``, ``os.replace`` into
place, never leave a torn file for a reader or a stale tmp on failure.
Standard library only — the obs modules import this at load time.
"""
from __future__ import annotations

import os
import threading
from contextlib import contextmanager


@contextmanager
def atomic_write(path: str, mode: str = "w"):
    """Yield a file handle whose contents replace ``path`` atomically
    on clean exit; on ANY failure the temp file is removed and ``path``
    is untouched. Parent directories are created. The temp name is
    pid+tid-unique: two threads writing the same path (the lrb loop's
    per-window trace flush vs a re-targeting configure) each publish a
    complete document instead of interleaving one shared tmp file."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, mode) as fh:
            yield fh
        os.replace(tmp, path)
    finally:
        try:                    # failed write: no stale tmp left behind
            os.unlink(tmp)      # (already renamed away on success)
        except OSError:
            pass
