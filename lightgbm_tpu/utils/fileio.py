"""Atomic file replacement — the one copy of the tmp+rename pattern.

Every on-disk artifact the engine writes concurrently-readably (run
reports, tuning cache, span traces, Prometheus textfiles) follows the
same discipline: write to ``<path>.tmp.<pid>``, ``os.replace`` into
place, never leave a torn file for a reader or a stale tmp on failure.
Standard library only — the obs modules import this at load time.
"""
from __future__ import annotations

import glob as _glob
import os
import re as _re
import threading
from contextlib import contextmanager
from typing import Callable, Optional


@contextmanager
def atomic_write(path: str, mode: str = "w"):
    """Yield a file handle whose contents replace ``path`` atomically
    on clean exit; on ANY failure the temp file is removed and ``path``
    is untouched. Parent directories are created. The temp name is
    pid+tid-unique: two threads writing the same path (the lrb loop's
    per-window trace flush vs a re-targeting configure) each publish a
    complete document instead of interleaving one shared tmp file."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, mode) as fh:
            yield fh
        os.replace(tmp, path)
    finally:
        try:                    # failed write: no stale tmp left behind
            os.unlink(tmp)      # (already renamed away on success)
        except OSError:
            pass


def prune_numbered(prefix: str, suffix_pattern: str, index_re: str,
                   keep: int,
                   companions: Optional[Callable] = None) -> None:
    """Best-effort keep-newest-K prune for numbered artifact families
    (model snapshots ``*.snapshot_iter_N``, checkpoint bundles
    ``ckpt_iter_N.json`` — utils/checkpoint.py): glob
    ``escape(prefix) + suffix_pattern`` (the prefix is caller data — a
    path with ``[``/``?`` in it must match literally, not as a glob
    class), rank by the ``index_re`` capture group (numeric, so
    r10 > r9), delete everything past the newest ``keep`` plus each
    victim's ``companions(path)`` sidecars. Deletion failures are
    ignored — pruning is hygiene, never a correctness step."""
    rx = _re.compile(index_re)
    found = []
    for p in _glob.glob(_glob.escape(prefix) + suffix_pattern):
        m = rx.search(p)
        if m:
            found.append((int(m.group(1)), p))
    for _, p in sorted(found, reverse=True)[max(int(keep), 1):]:
        extra = list(companions(p)) if companions is not None else []
        for victim in [p] + extra:
            try:
                os.unlink(victim)
            except OSError:
                pass
