"""Deterministic fault injection for the fault-tolerance drills.

Every recovery path in this repo is *proven*, not hoped for: tests (and
operators running game-day drills) arm named injection points and the
engine's retry / degrade / checkpoint machinery must absorb the blast.
The points are fixed, seed-keyed and counted, so a failing drill
reproduces exactly — the same occurrence of the same point fails on
every run with the same spec.

Injection points wired through the engine (grep ``faults.check``):

=====================  ====================================================
point                  where it fires
=====================  ====================================================
``ingest.prep``        host half of the ingest double buffer — the
                       prefetch worker's chunk slice/key step
                       (io/ingest.py ``DeviceBinner._prep_chunk``)
``ingest.device_put``  host->device chunk transfer (io/ingest.py
                       ``DeviceBinner._submit``; retried when transient)
``checkpoint.write``   resumable-checkpoint serialization
                       (utils/checkpoint.py ``save_checkpoint``)
``train.iter``         top of each boosting iteration in ``gbdt.train``
                       (the kill-and-resume drills aim here)
``lrb.window_train``   one sliding window's training in the lrb loop
                       (lrb.py — the degrade-don't-die path)
``export.write``       live metrics exporter snapshot (obs/export.py)
``fleet.predict``      the scoring daemon's per-tenant dispatch, just
                       before the device predict (serve/coalescer.py;
                       context = tenant id)
``fleet.predict.<t>``  same seam, but checked under a tenant-suffixed
                       point name so a drill can target ONE tenant
                       (the shed drill injects latency into a single
                       tenant's stream while its neighbors stay fast)
=====================  ====================================================

Spec grammar (``configure(spec)`` / the ``tpu_faults`` config knob /
the ``LGBM_TPU_FAULTS`` env var for subprocess drills)::

    point@N[,N...][:action] [; more points]

    train.iter@17:kill            SIGKILL self on the 17th iteration
    ingest.device_put@1:transient raise a RETRYABLE fault on call 1
    lrb.window_train@2            raise a persistent fault on call 2
    ingest.prep@p0.25             seeded coin-flip per call (p=0.25)

Occurrences are 1-based per point and counted process-wide; ``N+``
means "every call from the N-th on". Actions: ``raise`` (default — a
persistent ``InjectedFault``), ``transient`` (an
``InjectedFault(transient=True)``, which utils/retry.py classifies as
retryable), ``kill`` (``SIGKILL`` to self — the crash drills), and
``sleep<ms>`` (e.g. ``sleep50`` — stall the call for that many
milliseconds and then RETURN normally; a pure latency fault for the
SLO/admission drills, where the failure mode under test is slowness,
not an exception).

Stdlib + obs only; importing this module never touches jax.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from . import log

ENV_SPEC = "LGBM_TPU_FAULTS"
ENV_SEED = "LGBM_TPU_FAULTS_SEED"

KNOWN_ACTIONS = ("raise", "transient", "kill")


class InjectedFault(RuntimeError):
    """A deliberately injected failure. ``transient`` marks it
    retryable for utils/retry.py's classifier."""

    def __init__(self, msg: str, transient: bool = False):
        super().__init__(msg)
        self.transient = transient


class _Rule:
    """One point's firing rule: explicit occurrence set, an open-ended
    threshold (``N+``), or a seeded per-call probability. Each p-rule
    owns a PRIVATE RNG seeded from (seed, point): a shared stream
    consumed in cross-thread call-arrival order would make multi-point
    probability drills non-reproducible — the one property the seed
    exists to provide."""

    def __init__(self, at=(), at_from: Optional[int] = None,
                 p: Optional[float] = None, action: str = "raise",
                 seed: int = 0, point: str = "", sleep_ms: float = 0.0):
        self.at = frozenset(int(x) for x in at)
        self.at_from = at_from
        self.p = p
        self.action = action
        self.sleep_ms = float(sleep_ms)
        if p is not None:
            import random
            self.rng = random.Random(f"{seed}:{point}")

    def fires(self, count: int, coin: float) -> bool:
        if count in self.at:
            return True
        if self.at_from is not None and count >= self.at_from:
            return True
        if self.p is not None and coin < self.p:
            return True
        return False


_lock = threading.Lock()
_rules: Dict[str, _Rule] = {}
_counts: Dict[str, int] = {}
_env_loaded = False
_armed_spec = None              # (spec, seed) for idempotent re-arming


def _parse_spec(spec: str, seed: int) -> Dict[str, _Rule]:
    rules: Dict[str, _Rule] = {}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise ValueError(f"fault spec {part!r}: want point@N[:action]")
        point, rest = part.split("@", 1)
        action, sleep_ms = "raise", 0.0
        if ":" in rest:
            rest, action = rest.rsplit(":", 1)
            action = action.strip().lower()
            if action.startswith("sleep"):
                try:
                    sleep_ms = float(action[len("sleep"):] or "nan")
                except ValueError:
                    sleep_ms = float("nan")
                if not sleep_ms >= 0.0:       # catches NaN too
                    raise ValueError(
                        f"fault spec {part!r}: want sleep<ms> with a "
                        f"non-negative millisecond count (e.g. sleep50)")
                action = "sleep"
            elif action not in KNOWN_ACTIONS:
                raise ValueError(
                    f"fault spec {part!r}: unknown action {action!r} "
                    f"(want sleep<ms> or one of "
                    f"{'/'.join(KNOWN_ACTIONS)})")
        rest = rest.strip()
        at, at_from, p = [], None, None
        if rest.startswith("p"):
            p = float(rest[1:])
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"fault spec {part!r}: probability "
                                 f"{p} outside [0, 1]")
        else:
            for tok in rest.split(","):
                tok = tok.strip()
                if tok.endswith("+"):
                    at_from = int(tok[:-1])
                elif tok:
                    at.append(int(tok))
        name = point.strip()
        rules[name] = _Rule(at, at_from, p, action, seed=seed,
                            point=name, sleep_ms=sleep_ms)
    return rules


def configure(spec, seed: int = 0) -> None:
    """Arm injection points from a spec string (see module docstring)
    or a ``{point: rule-kwargs}`` dict. Replaces the current plan and
    resets occurrence counts — EXCEPT when re-arming the identical
    (spec, seed), which is a no-op so the several drivers that each
    arm from config (every windowed booster init) cannot reset a
    drill's occurrence counters mid-run. Empty/None disarms."""
    global _armed_spec
    if isinstance(spec, dict):
        rules = {str(k): _Rule(point=str(k), seed=seed, **v)
                 for k, v in spec.items()}
    elif spec:
        if _armed_spec == (spec, seed):
            return
        rules = _parse_spec(spec, seed)
    else:
        rules = {}
    _armed_spec = (spec, seed) if spec and not isinstance(spec, dict) \
        else None
    with _lock:
        _rules.clear()
        _rules.update(rules)
        _counts.clear()
    if rules:
        log.warning("fault injection ARMED: %s",
                    ", ".join(sorted(rules)))


def configure_from_config(config) -> None:
    """Arm from the ``tpu_faults`` config knob (idempotent no-op when
    the knob is empty — a plan armed by a test/env stays armed)."""
    spec = str(getattr(config, "tpu_faults", "") or "")
    if spec:
        configure(spec, int(getattr(config, "tpu_fault_seed", 0) or 0))


def clear() -> None:
    configure(None)


def _ensure_env_loaded() -> None:
    """Lazy one-shot env arm: subprocess drills export
    ``LGBM_TPU_FAULTS`` and the child needs no code changes."""
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(ENV_SPEC, "")
    if spec:
        configure(spec, int(os.environ.get(ENV_SEED, "0") or 0))


def active() -> bool:
    """True when any point is armed (hot paths gate on this)."""
    _ensure_env_loaded()
    return bool(_rules)


def check(point: str, context=None) -> None:
    """Count one call of ``point`` and inject its armed action if the
    rule fires. No-op (one dict lookup) when nothing is armed."""
    _ensure_env_loaded()
    if not _rules:
        return
    with _lock:
        rule = _rules.get(point)
        if rule is None:
            return
        _counts[point] = count = _counts.get(point, 0) + 1
        # per-point RNG: the coin for a point's Nth call is a pure
        # function of (seed, point, N) regardless of what other
        # points' threads are doing
        coin = rule.rng.random() if rule.p is not None else 1.0
        fire = rule.fires(count, coin)
    if not fire:
        return
    from ..obs import registry as obs
    obs.counter("faults/injected").add(1)
    ctx = f" ({context})" if context is not None else ""
    msg = (f"injected fault at {point} occurrence {count}{ctx} "
           f"[action={rule.action}]")
    log.warning("%s", msg)
    if rule.action == "sleep":
        # latency fault: stall, then let the call proceed — the caller
        # never sees an exception, only the wall-clock damage (the
        # admission-control drills assert on the p99 consequence)
        import time
        time.sleep(rule.sleep_ms / 1000.0)
        return
    # black box BEFORE the blast: a kill action SIGKILLs the process —
    # this dump is the only evidence that will ever exist for it
    # (forced: the moment cannot recur; obs/flight.py)
    from ..obs import flight
    flight.trigger("fault", {"point": point, "occurrence": count,
                             "action": rule.action,
                             **({"context": str(context)}
                                if context is not None else {})},
                   force=rule.action == "kill")
    if rule.action == "kill":
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    raise InjectedFault(msg, transient=rule.action == "transient")


def counts() -> Dict[str, int]:
    """Per-point call counts so far (tests)."""
    with _lock:
        return dict(_counts)
