"""Leveled logging for lightgbm_tpu.

TPU-native counterpart of the reference logger (reference:
include/LightGBM/utils/log.h:22-105): Debug/Info/Warning levels plus a
Fatal that raises instead of aborting the process.
"""
from __future__ import annotations

import sys
from enum import IntEnum


class LogLevel(IntEnum):
    FATAL = -1
    WARNING = 0
    INFO = 1
    DEBUG = 2


class LightGBMError(RuntimeError):
    """Raised where the reference calls Log::Fatal (utils/log.h:83)."""


_current_level = LogLevel.INFO
_callback = None


def set_level(level: LogLevel | int) -> None:
    global _current_level
    _current_level = LogLevel(int(level))


def get_level() -> LogLevel:
    return _current_level


def set_callback(cb) -> None:
    """Redirect log output (mirrors Log::ResetCallBack)."""
    global _callback
    _callback = cb


def _write(level: LogLevel, tag: str, msg: str) -> None:
    if level <= _current_level:
        line = f"[LightGBM-TPU] [{tag}] {msg}"
        if _callback is not None:
            _callback(line + "\n")
        else:
            print(line, file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    _write(LogLevel.DEBUG, "Debug", msg % args if args else msg)


def info(msg: str, *args) -> None:
    _write(LogLevel.INFO, "Info", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    _write(LogLevel.WARNING, "Warning", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    raise LightGBMError(msg % args if args else msg)


def check(condition: bool, msg: str = "Check failed") -> None:
    """CHECK macro equivalent (utils/log.h:22)."""
    if not condition:
        fatal(msg)
