"""Leveled logging for lightgbm_tpu.

TPU-native counterpart of the reference logger (reference:
include/LightGBM/utils/log.h:22-105): Debug/Info/Warning levels plus a
Fatal that raises instead of aborting the process.

Thread-safe: level, callback and run-context are read/written under a
module lock (the ingest prefetch worker logs from off-thread while the
main thread may be re-routing output via ``set_callback``). While a
RunRecorder is active (obs/recorder.py) it installs a *run context*
provider and every line gains a ``[t+12.3s it=140]`` prefix — run
elapsed seconds and current boosting iteration — so interleaved lines
from worker threads stay attributable to a point in the run.
"""
from __future__ import annotations

import sys
import threading
from enum import IntEnum


class LogLevel(IntEnum):
    FATAL = -1
    WARNING = 0
    INFO = 1
    DEBUG = 2


class LightGBMError(RuntimeError):
    """Raised where the reference calls Log::Fatal (utils/log.h:83)."""


_lock = threading.Lock()
_current_level = LogLevel.INFO
_callback = None
# zero-arg provider -> (run_elapsed_seconds, iteration-or-None) | None;
# installed by an active RunRecorder, cleared at finish
_run_context = None
# additive tee sinks: each receives every emitted line (after the
# level filter, with the run prefix) WITHOUT re-routing the normal
# output — the flight recorder's log ring (obs/flight.py). A sink must
# be cheap and never raise.
_sinks: list = []
# rank tag ("r1") set by the cluster layer under world>1 so interleaved
# multi-process stderr is attributable without grepping pids; empty
# single-process (the prefix stays byte-identical)
_rank_tag = ""


def set_level(level: LogLevel | int) -> None:
    global _current_level
    with _lock:
        _current_level = LogLevel(int(level))


def get_level() -> LogLevel:
    with _lock:
        return _current_level


def set_callback(cb) -> None:
    """Redirect log output (mirrors Log::ResetCallBack)."""
    global _callback
    with _lock:
        _callback = cb


def set_run_context(provider) -> None:
    """Install (or clear, with None) the run-prefix provider."""
    global _run_context
    with _lock:
        _run_context = provider


def set_rank_tag(tag: str) -> None:
    """Install (or clear, with "") the rank tag the prefix carries —
    parallel/cluster.py sets it at bootstrap/adoption under world>1;
    every line then reads ``[r1 t+12.3s it=140]`` (or ``[r1]`` outside
    a run context)."""
    global _rank_tag
    with _lock:
        _rank_tag = str(tag or "")


def rank_tag() -> str:
    with _lock:
        return _rank_tag


def add_sink(fn) -> None:
    """Register a tee sink fed every emitted line (idempotent)."""
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn) -> None:
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


def _write(level: LogLevel, tag: str, msg: str) -> None:
    with _lock:
        lvl, cb, ctx = _current_level, _callback, _run_context
        rtag = _rank_tag
    if level > lvl:
        return
    prefix = ""
    parts = [rtag] if rtag else []
    if ctx is not None:
        try:
            rc = ctx()
        except Exception:               # noqa: BLE001 — the prefix is
            rc = None                   # decoration, never a failure
        if rc is not None:
            elapsed, it = rc
            parts.append(f"t+{elapsed:.1f}s"
                         + (f" it={it}" if it is not None else ""))
    if parts:
        prefix = "[" + " ".join(parts) + "] "
    line = f"[LightGBM-TPU] [{tag}] {prefix}{msg}"
    for sink in tuple(_sinks):
        try:
            sink(line)
        except Exception:               # noqa: BLE001 — a sink must
            pass                        # never break the logged path
    if cb is not None:
        cb(line + "\n")
    else:
        print(line, file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    _write(LogLevel.DEBUG, "Debug", msg % args if args else msg)


def info(msg: str, *args) -> None:
    _write(LogLevel.INFO, "Info", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    _write(LogLevel.WARNING, "Warning", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    raise LightGBMError(msg % args if args else msg)


def check(condition: bool, msg: str = "Check failed") -> None:
    """CHECK macro equivalent (utils/log.h:22)."""
    if not condition:
        fatal(msg)
