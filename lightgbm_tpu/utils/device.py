"""Device/platform selection.

``jax.devices()`` returns the highest-priority backend (on this image the
axon TPU plugin registers itself even when ``JAX_PLATFORMS=cpu`` is set).
``LGBM_TPU_PLATFORM`` selects an explicit backend — tests set it to
``cpu`` together with ``jax_num_cpu_devices`` to get an 8-device virtual
mesh for in-process multi-worker coverage.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax

# platform requested by config (device_type=cpu); the operator's
# LGBM_TPU_PLATFORM env pin always outranks it
_config_platform: Optional[str] = None


def set_config_platform(platform: Optional[str]) -> None:
    """Install (or clear, with None) the config-level device routing
    (device_type). Never touches LGBM_TPU_PLATFORM — an operator pin
    stays authoritative."""
    global _config_platform
    _config_platform = platform


def get_devices(platform: Optional[str] = None) -> List:
    plat = (platform or os.environ.get("LGBM_TPU_PLATFORM")
            or _config_platform)
    if plat:
        return jax.local_devices(backend=plat)
    return jax.devices()


def get_global_devices(platform: Optional[str] = None) -> List:
    """EVERY process's devices of the selected backend — the device
    set a multi-process training mesh must span (a collective over a
    subset would leave peers waiting forever). Single-process this is
    exactly get_devices(); under jax.distributed the platform pin
    routes through jax.devices(backend), which is global."""
    plat = (platform or os.environ.get("LGBM_TPU_PLATFORM")
            or _config_platform)
    if jax.process_count() == 1:
        return get_devices(plat)
    return jax.devices(plat) if plat else jax.devices()


def on_tpu() -> bool:
    """True when framework computation actually runs on a TPU device —
    gates Pallas kernel dispatch (Pallas TPU kernels can't lower for the
    CPU backend). Honors LGBM_TPU_PLATFORM like get_devices()."""
    return get_devices()[0].platform == "tpu"


def on_gpu() -> bool:
    """True when framework computation runs on a GPU device — gates the
    Pallas-Triton kernel dispatch (ops/hist_wave.py /
    ops/stacked_predict.py GPU tiers). Honors LGBM_TPU_PLATFORM like
    get_devices(); jax reports both CUDA and ROCm as platform "gpu"."""
    return get_devices()[0].platform == "gpu"


def backend_kind() -> str:
    """The routing backend of the selected platform: "tpu", "gpu" or
    "cpu". ONE three-way seam for every kernel-route decision (tier
    selection, compile-cache policy, autotuner arms) instead of
    scattered on_tpu()/on_gpu() pairs that can disagree."""
    p = get_devices()[0].platform
    return p if p in ("tpu", "gpu") else "cpu"
