"""Device/platform selection.

``jax.devices()`` returns the highest-priority backend (on this image the
axon TPU plugin registers itself even when ``JAX_PLATFORMS=cpu`` is set).
``LGBM_TPU_PLATFORM`` selects an explicit backend — tests set it to
``cpu`` together with ``jax_num_cpu_devices`` to get an 8-device virtual
mesh for in-process multi-worker coverage.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax


def get_devices(platform: Optional[str] = None) -> List:
    plat = platform or os.environ.get("LGBM_TPU_PLATFORM")
    if plat:
        return jax.local_devices(backend=plat)
    return jax.devices()


def on_tpu() -> bool:
    """True when framework computation actually runs on a TPU device —
    gates Pallas kernel dispatch (Pallas TPU kernels can't lower for the
    CPU backend). Honors LGBM_TPU_PLATFORM like get_devices()."""
    return get_devices()[0].platform == "tpu"
