"""Plotting utilities.

TPU-native counterpart of the reference plotting module
(reference: python-package/lightgbm/plotting.py:24 plot_importance,
:133 plot_metric, :384 plot_tree). matplotlib-only; plot_tree renders
the tree structure directly with matplotlib instead of requiring
graphviz.
"""
from __future__ import annotations

from copy import deepcopy

import numpy as np

from .basic import Booster, LightGBMError


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        raise ImportError("You must install matplotlib for plotting")


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    grid=True, **kwargs):
    """Plot model's feature importances (plotting.py:24-130)."""
    plt = _check_matplotlib()
    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type)
        feature_name = booster.feature_name()
    elif hasattr(booster, "booster_"):
        importance = booster.booster_.feature_importance(importance_type)
        feature_name = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel")
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty")
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(int(x)) if importance_type == "split"
                else f"{x:.2f}", va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        ax.set_ylim(-1, len(values))
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None,
                grid=True):
    """Plot one metric's history from an evals_result dict or a Booster
    trained with record_evaluation (plotting.py:133-230)."""
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):     # LGBMModel
        eval_results = deepcopy(booster.evals_result_)
        if not eval_results:
            raise LightGBMError("Fit the estimator with eval_set to "
                                "record metrics")
    elif isinstance(booster, Booster):
        raise LightGBMError(
            "Pass the evals_result dict from train(..., evals_result=...)")
    else:
        raise TypeError("booster must be dict of eval results or a "
                        "fitted LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    if dataset_names is None:
        dataset_names = iter(eval_results.keys())
    name = None
    num_iteration, max_result, min_result = 0, -np.inf, np.inf
    for name_ds in dataset_names:
        metrics = eval_results[name_ds]
        if metric is None:
            metric_name, results = next(iter(metrics.items()))
        else:
            metric_name, results = metric, metrics[metric]
        name = metric_name
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
        num_iteration = max(len(results), num_iteration)
        ax.plot(range(len(results)), results, label=name_ds)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    else:
        ax.set_xlim(0, num_iteration)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        margin = 0.05 * (max_result - min_result + 1e-12)
        ax.set_ylim(min_result - margin, max_result + margin)
    if ylabel == "auto":
        ylabel = name
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _tree_model(booster, tree_index):
    """Shared renderer preamble: normalize Booster/LGBMModel, dump the
    model, bound-check the tree, return (tree_structure, names)."""
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError("tree_index is out of range")
    return (model["tree_info"][tree_index]["tree_structure"],
            model["feature_names"])


def _split_desc(node, names, precision):
    """Shared split-node text: feature-name fallback + threshold
    rounding used by both tree renderers."""
    feat = node["split_feature"]
    fname = names[feat] if feat < len(names) else f"f{feat}"
    op = node.get("decision_type", "<=")
    return f"{fname} {op} {round(node['threshold'], precision)}"


def _leaf_desc(node, precision):
    """Shared leaf text: (index, rounded value)."""
    return (node.get("leaf_index", 0),
            round(node.get("leaf_value", 0.0), precision))


def plot_tree(booster, ax=None, tree_index=0, figsize=None,
              show_info=None, precision=3):
    """Render one tree's structure with matplotlib (plotting.py:384-449
    renders via graphviz; this draws the same node content natively).
    ``show_info``: extra node fields to annotate, from
    {'internal_count', 'internal_value', 'leaf_count'}."""
    plt = _check_matplotlib()
    tree, names = _tree_model(booster, tree_index)

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize or (12, 8))

    # layout: assign x by in-order leaf position, y by depth
    positions = {}
    leaf_x = [0]

    def layout(node, depth):
        if "leaf_index" in node or "leaf_value" in node and \
                "split_index" not in node:
            x = leaf_x[0]
            leaf_x[0] += 1
            positions[id(node)] = (x, -depth)
            return x
        lx = layout(node["left_child"], depth + 1)
        rx = layout(node["right_child"], depth + 1)
        x = (lx + rx) / 2.0
        positions[id(node)] = (x, -depth)
        return x

    layout(tree, 0)

    def draw(node):
        x, y = positions[id(node)]
        info = show_info or []
        if "split_index" in node:
            label = (f"{_split_desc(node, names, precision)}\n"
                     f"gain={round(node.get('split_gain', 0.0), precision)}")
            for key in ("internal_count", "internal_value"):
                if key in info and key in node:
                    label += f"\n{key}={round(node[key], precision)}"
            box = dict(boxstyle="round", fc="lightblue", ec="black")
            for child in (node["left_child"], node["right_child"]):
                cx, cy = positions[id(child)]
                ax.plot([x, cx], [y, cy], "k-", lw=0.8, zorder=1)
                draw(child)
        else:
            li, lv = _leaf_desc(node, precision)
            label = f"leaf {li}:\n{lv}"
            if "leaf_count" in info and "leaf_count" in node:
                label += f"\ncount={node['leaf_count']}"
            box = dict(boxstyle="round", fc="lightgreen", ec="black")
        ax.text(x, y, label, ha="center", va="center", bbox=box,
                fontsize=8, zorder=2)

    draw(tree)
    ax.set_axis_off()
    ax.set_title(f"Tree {tree_index}")
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None,
                        precision=3, name=None, comment=None, **kwargs):
    """One tree as a graphviz Digraph (reference plotting.py:311-381
    create_tree_digraph; node content matches _to_graphviz:257-308).
    ``show_info`` from {'split_gain', 'internal_value', 'internal_count',
    'leaf_count'}."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")
    tree, names = _tree_model(booster, tree_index)
    info = show_info or []

    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            label = _split_desc(node, names, precision)
            if "split_gain" in info:
                label += f"\ngain: {round(node.get('split_gain', 0.0), precision)}"
            if "internal_value" in info and "internal_value" in node:
                label += f"\nvalue: {round(node['internal_value'], precision)}"
            if "internal_count" in info and "internal_count" in node:
                label += f"\ncount: {node['internal_count']}"
            graph.node(nid, label=label)
            add(node["left_child"], nid, "yes")
            add(node["right_child"], nid, "no")
        else:
            li, lv = _leaf_desc(node, precision)
            nid = f"leaf{li}"
            label = f"leaf {li}: {lv}"
            if "leaf_count" in info and "leaf_count" in node:
                label += f"\ncount: {node['leaf_count']}"
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)
        return nid

    add(tree)
    return graph
