from .metric import Metric, create_metric, create_metrics, metric_alias

__all__ = ["Metric", "create_metric", "create_metrics", "metric_alias"]
