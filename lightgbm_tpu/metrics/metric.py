"""Evaluation metrics.

TPU-native counterparts of the reference metrics
(reference: src/metric/metric.cpp:11-55 factory; regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp, map_metric.hpp,
xentropy_metric.hpp, dcg_calculator.cpp). Numpy-vectorized host
implementations — metric evaluation is once-per-iteration O(N) work on
scores already pulled from device.

Scores arrive class-major ``[K, N]`` like the reference's score buffer;
``objective.convert_output`` supplies the raw->output transform exactly as
Metric::Eval receives the objective pointer (include/LightGBM/metric.h:40).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..utils import log


class Metric:
    name = "base"
    bigger_is_better = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data):
        self.label = (np.asarray(metadata.label, np.float64)
                      if metadata.label is not None else np.zeros(num_data))
        self.weights = (np.asarray(metadata.weights, np.float64)
                        if metadata.weights is not None else None)
        self.query_boundaries = metadata.query_boundaries
        self.num_data = num_data
        self.sum_weights = (float(num_data) if self.weights is None
                            else float(np.sum(self.weights)))

    def eval(self, score: np.ndarray, objective) -> List[tuple]:
        raise NotImplementedError

    def device_eval_builder(self, objective):
        """Return a traceable fn(scores [K, N_padded]) -> jnp scalar, or
        None when this metric has no device implementation.

        Device metrics keep per-iteration evaluation (early stopping,
        metric_freq=1) down to ONE scalar download instead of pulling
        the full [K, N] score tensor to the host every iteration
        (gbdt.cpp:432-534 evaluates on the host because its scores
        already live there; ours don't). f32 reductions: values agree
        with the f64 host path to ~1e-6 relative.
        """
        return None

    def _dev_arrays(self):
        import jax.numpy as jnp
        if not hasattr(self, "_dev_label"):
            self._dev_label = jnp.asarray(self.label, jnp.float32)
            self._dev_weights = (
                jnp.asarray(self.weights, jnp.float32)
                if self.weights is not None else None)
        return self._dev_label, self._dev_weights

    def _dev_avg(self, losses, w):
        import jax.numpy as jnp
        if w is None:
            return jnp.mean(losses)
        return jnp.sum(losses * w) / self.sum_weights

    def _avg(self, losses: np.ndarray) -> float:
        if self.weights is None:
            return float(np.mean(losses))
        return float(np.sum(losses * self.weights) / self.sum_weights)

    def _convert(self, score: np.ndarray, objective) -> np.ndarray:
        if objective is not None:
            import jax.numpy as jnp
            # upcast: convert_output computes in f32, but host metrics
            # clip against f64 epsilons (1 - 1e-15 rounds to 1.0 in
            # f32, which would turn log(1-p) into -inf on saturated
            # sigmoid/softmax outputs; the reference evaluates in
            # double throughout, binary_metric.hpp)
            return np.asarray(objective.convert_output(jnp.asarray(score)),
                              np.float64)
        return score


# --- regression family (src/metric/regression_metric.hpp) -----------------

class _PointwiseMetric(Metric):
    # jnp mirror of .loss for the device path; None = host-only
    loss_dev = None

    def eval(self, score, objective):
        s = self._convert(score[0] if score.ndim > 1 else score, objective)
        return [(self.name, self._avg(self.loss(self.label, s)))]

    def device_eval_builder(self, objective):
        if self.loss_dev is None:
            return None
        lab, w = self._dev_arrays()
        n = self.num_data

        def fn(scores):
            s = scores[0, :n]
            if objective is not None:
                s = objective.convert_output(s)
            return self._dev_avg(self.loss_dev(lab, s), w)
        return fn


class L2Metric(_PointwiseMetric):
    name = "l2"

    @staticmethod
    def loss(y, s):
        return (y - s) ** 2

    loss_dev = loss


class RMSEMetric(_PointwiseMetric):
    name = "rmse"

    def eval(self, score, objective):
        s = self._convert(score[0] if score.ndim > 1 else score, objective)
        return [(self.name, math.sqrt(self._avg((self.label - s) ** 2)))]

    def device_eval_builder(self, objective):
        import jax.numpy as jnp
        lab, w = self._dev_arrays()
        n = self.num_data

        def fn(scores):
            s = scores[0, :n]
            if objective is not None:
                s = objective.convert_output(s)
            return jnp.sqrt(self._dev_avg((lab - s) ** 2, w))
        return fn


class L1Metric(_PointwiseMetric):
    name = "l1"

    @staticmethod
    def loss(y, s):
        return np.abs(y - s)

    @staticmethod
    def loss_dev(y, s):
        import jax.numpy as jnp
        return jnp.abs(y - s)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def loss(self, y, s):
        a = self.config.alpha
        d = y - s
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberLossMetric(_PointwiseMetric):
    name = "huber"

    def loss(self, y, s):
        a = self.config.alpha
        d = np.abs(s - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairLossMetric(_PointwiseMetric):
    name = "fair"

    def loss(self, y, s):
        c = self.config.fair_c
        x = np.abs(s - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    @staticmethod
    def loss(y, s):
        eps = 1e-10
        s = np.maximum(s, eps)
        return s - y * np.log(s)


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    @staticmethod
    def loss(y, s):
        return np.abs((y - s) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseMetric):
    name = "gamma"

    @staticmethod
    def loss(y, s):
        eps = 1e-10
        psi = 1.0
        theta = -1.0 / np.maximum(s, eps)
        a = psi
        b = -np.log(-theta)
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - math.lgamma(1.0 / psi)
        return -((y * theta - b) / a + c)


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def eval(self, score, objective):
        s = self._convert(score[0] if score.ndim > 1 else score, objective)
        eps = 1e-10
        frac = self.label / np.maximum(s, eps)
        loss = -np.log(np.maximum(frac, eps)) + frac - 1.0
        return [(self.name, float(2.0 * np.sum(loss)))]


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def loss(self, y, s):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(s, eps)
        a = y * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return -a + b


# --- binary (src/metric/binary_metric.hpp) --------------------------------

class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score, objective):
        s = score[0] if score.ndim > 1 else score
        y = (self.label > 0).astype(np.float64)
        if (objective is not None
                and getattr(objective, "name", "") == "binary"):
            # from RAW scores in f64 (reference semantics,
            # binary_metric.hpp computes the sigmoid in double): the
            # f32 convert_output saturates beyond |s·sigmoid| ~ 17
            sa = float(objective.sigmoid) * np.asarray(s, np.float64)
            loss = (y * np.logaddexp(0.0, -sa)
                    + (1.0 - y) * np.logaddexp(0.0, sa))
            return [(self.name, self._avg(loss))]
        p = self._convert(s, objective)
        eps = 1e-15
        p = np.clip(p, eps, 1.0 - eps)
        loss = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        return [(self.name, self._avg(loss))]

    def device_eval_builder(self, objective):
        import jax.numpy as jnp
        lab, w = self._dev_arrays()
        n = self.num_data
        y = (lab > 0).astype(jnp.float32)

        import jax
        # sigmoid objectives: compute the loss from RAW scores via
        # softplus — exact in f32, no probability clipping. The old
        # clip-at-1e-7 capped per-row loss at ~16.1 vs the host path's
        # ~34.5 and could shift early stopping on overfit runs.
        sig = (getattr(objective, "sigmoid", None)
               if objective is not None
               and getattr(objective, "name", "") in ("binary",)
               else None)

        def fn(scores):
            s = scores[0, :n]
            if sig is not None:
                sa = jnp.float32(sig) * s
                # -log sigma(sa) = softplus(-sa); -log(1-sigma) = softplus(sa)
                loss = (y * jax.nn.softplus(-sa)
                        + (1.0 - y) * jax.nn.softplus(sa))
                return self._dev_avg(loss, w)
            if objective is not None:
                s = objective.convert_output(s)
            p = jnp.clip(s, 1e-7, 1.0 - 1e-7)   # f32-resolvable eps
            loss = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
            return self._dev_avg(loss, w)
        return fn


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective):
        p = self._convert(score[0] if score.ndim > 1 else score, objective)
        y = (self.label > 0)
        pred = p > 0.5
        return [(self.name, self._avg((pred != y).astype(np.float64)))]

    def device_eval_builder(self, objective):
        import jax.numpy as jnp
        lab, w = self._dev_arrays()
        n = self.num_data
        y = lab > 0

        def fn(scores):
            s = scores[0, :n]
            if objective is not None:
                s = objective.convert_output(s)
            return self._dev_avg(((s > 0.5) != y).astype(jnp.float32), w)
        return fn


class AUCMetric(Metric):
    """AUC (binary_metric.hpp:266-400): weighted rank statistic."""
    name = "auc"
    bigger_is_better = True

    def eval(self, score, objective):
        s = np.asarray(score[0] if score.ndim > 1 else score, np.float64)
        y = (self.label > 0)
        w = (self.weights if self.weights is not None
             else np.ones_like(s))
        order = np.argsort(s, kind="mergesort")
        s_s, y_s, w_s = s[order], y[order], w[order]
        # handle ties: average rank within equal-score groups
        pos_w = np.where(y_s, w_s, 0.0)
        neg_w = np.where(~y_s, w_s, 0.0)
        cum_neg = np.cumsum(neg_w)
        # group by unique scores
        uniq, inv = np.unique(s_s, return_inverse=True)
        grp_pos = np.bincount(inv, weights=pos_w)
        grp_neg = np.bincount(inv, weights=neg_w)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
        auc_sum = np.sum(grp_pos * (cum_neg_before + 0.5 * grp_neg))
        total_pos = pos_w.sum()
        total_neg = neg_w.sum()
        if total_pos == 0 or total_neg == 0:
            return [(self.name, 1.0)]
        return [(self.name, float(auc_sum / (total_pos * total_neg)))]

    def device_eval_builder(self, objective):
        """Device AUC: one sort + sorted segment sums — the rank
        statistic with tie groups, entirely on device."""
        import jax
        import jax.numpy as jnp
        lab, w = self._dev_arrays()
        n = self.num_data
        ypos = lab > 0

        def fn(scores):
            s = scores[0, :n]
            order = jnp.argsort(s)
            y_s = ypos[order]
            w_s = w[order] if w is not None else jnp.ones(n, jnp.float32)
            s_s = s[order]
            pos_w = jnp.where(y_s, w_s, 0.0)
            neg_w = jnp.where(y_s, 0.0, w_s)
            # tie groups: average rank within equal-score runs
            first = jnp.concatenate(
                [jnp.ones(1, bool), s_s[1:] != s_s[:-1]])
            gid = jnp.cumsum(first.astype(jnp.int32)) - 1
            grp_pos = jax.ops.segment_sum(pos_w, gid, num_segments=n,
                                          indices_are_sorted=True)
            grp_neg = jax.ops.segment_sum(neg_w, gid, num_segments=n,
                                          indices_are_sorted=True)
            cum_before = jnp.concatenate(
                [jnp.zeros(1), jnp.cumsum(grp_neg)[:-1]])
            auc_sum = jnp.sum(grp_pos * (cum_before + 0.5 * grp_neg))
            tp, tn = jnp.sum(pos_w), jnp.sum(neg_w)
            return jnp.where((tp == 0.0) | (tn == 0.0), 1.0,
                             auc_sum / (tp * tn))
        return fn


# --- multiclass (src/metric/multiclass_metric.hpp) ------------------------

class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective):
        y = self.label.astype(np.int64)
        if (objective is not None
                and getattr(objective, "name", "") == "multiclass"):
            # raw-score f64 path: -log p_y = logsumexp(s) - s_y
            s64 = np.asarray(score, np.float64)
            mx = s64.max(axis=0)
            lse = mx + np.log(np.exp(s64 - mx).sum(axis=0))
            loss = lse - s64[y, np.arange(s64.shape[1])]
            return [(self.name, self._avg(loss))]
        p = self._convert(score, objective)      # [K, N]
        eps = 1e-15
        py = np.clip(p[y, np.arange(p.shape[1])], eps, None)
        return [(self.name, self._avg(-np.log(py)))]

    def device_eval_builder(self, objective):
        import jax.numpy as jnp
        lab, w = self._dev_arrays()
        n = self.num_data
        y = lab.astype(jnp.int32)

        import jax
        # softmax objectives: -log p_y = logsumexp(s) - s_y on the RAW
        # scores — exact in f32, no clipping (see binary logloss above)
        softmax = (objective is not None
                   and getattr(objective, "name", "") == "multiclass")

        def fn(scores):
            s = scores[:, :n]
            if softmax:
                loss = (jax.scipy.special.logsumexp(s, axis=0)
                        - s[y, jnp.arange(n)])
                return self._dev_avg(loss, w)
            if objective is not None:
                s = objective.convert_output(s)
            py = jnp.clip(s[y, jnp.arange(n)], 1e-7, None)
            return self._dev_avg(-jnp.log(py), w)
        return fn


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective):
        p = self._convert(score, objective)
        pred = np.argmax(p, axis=0)
        y = self.label.astype(np.int64)
        return [(self.name, self._avg((pred != y).astype(np.float64)))]

    def device_eval_builder(self, objective):
        import jax.numpy as jnp
        lab, w = self._dev_arrays()
        n = self.num_data
        y = lab.astype(jnp.int32)

        def fn(scores):
            s = scores[:, :n]
            if objective is not None:
                s = objective.convert_output(s)
            pred = jnp.argmax(s, axis=0).astype(jnp.int32)
            return self._dev_avg((pred != y).astype(jnp.float32), w)
        return fn


class MultiSoftmaxLoglossMetric(MultiLoglossMetric):
    name = "multi_logloss"


# --- xentropy family (src/metric/xentropy_metric.hpp) ---------------------

class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, score, objective):
        p = self._convert(score[0] if score.ndim > 1 else score, objective)
        eps = 1e-15
        p = np.clip(p, eps, 1.0 - eps)
        y = self.label
        loss = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        return [(self.name, self._avg(loss))]


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective):
        s = np.asarray(score[0] if score.ndim > 1 else score, np.float64)
        # hhat = log(1 + exp(s)); loss per xentropy_metric.hpp
        hhat = np.log1p(np.exp(s))
        y = self.label
        w = self.weights if self.weights is not None else 1.0
        p = 1.0 - np.exp(-w * hhat)
        eps = 1e-15
        p = np.clip(p, eps, 1.0 - eps)
        loss = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        return [(self.name, float(np.mean(loss)))]


class KLDivergenceMetric(Metric):
    name = "kldiv"

    def eval(self, score, objective):
        s = np.asarray(score[0] if score.ndim > 1 else score, np.float64)
        p = 1.0 / (1.0 + np.exp(-s))
        eps = 1e-15
        p = np.clip(p, eps, 1.0 - eps)
        y = np.clip(self.label, eps, 1.0 - eps)
        kl = (y * np.log(y / p) + (1.0 - y) * np.log((1.0 - y) / (1.0 - p)))
        return [(self.name, self._avg(kl))]


# --- ranking (src/metric/rank_metric.hpp, map_metric.hpp) -----------------

class NDCGMetric(Metric):
    name = "ndcg"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        label_gain = self.config.label_gain
        if not label_gain:
            label_gain = [float(2 ** i - 1) for i in range(31)]
        self.label_gain = np.asarray(label_gain, np.float64)
        self.eval_at = list(self.config.eval_at) or [1, 2, 3, 4, 5]

    def eval(self, score, objective):
        s = np.asarray(score[0] if score.ndim > 1 else score, np.float64)
        qb = self.query_boundaries
        results = {k: [] for k in self.eval_at}
        qweights = []
        for q in range(len(qb) - 1):
            lo, hi = qb[q], qb[q + 1]
            ls = self.label[lo:hi].astype(np.int64)
            ss = s[lo:hi]
            qweights.append(1.0)
            order = np.argsort(-ss, kind="mergesort")
            gains = self.label_gain[ls]
            ideal = np.sort(gains)[::-1]
            disc = 1.0 / np.log2(np.arange(len(ls)) + 2.0)
            for k in self.eval_at:
                kk = min(k, len(ls))
                dcg = np.sum(gains[order[:kk]] * disc[:kk])
                maxdcg = np.sum(ideal[:kk] * disc[:kk])
                results[k].append(1.0 if maxdcg <= 0 else dcg / maxdcg)
        out = []
        for k in self.eval_at:
            out.append((f"ndcg@{k}", float(np.mean(results[k]))))
        return out


class MapMetric(Metric):
    name = "map"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        self.eval_at = list(self.config.eval_at) or [1, 2, 3, 4, 5]

    def eval(self, score, objective):
        s = np.asarray(score[0] if score.ndim > 1 else score, np.float64)
        qb = self.query_boundaries
        results = {k: [] for k in self.eval_at}
        for q in range(len(qb) - 1):
            lo, hi = qb[q], qb[q + 1]
            rel = self.label[lo:hi] > 0
            order = np.argsort(-s[lo:hi], kind="mergesort")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            prec = hits / (np.arange(len(rel_sorted)) + 1.0)
            for k in self.eval_at:
                kk = min(k, len(rel_sorted))
                num_rel = rel_sorted[:kk].sum()
                ap = (np.sum(prec[:kk] * rel_sorted[:kk]) / num_rel
                      if num_rel > 0 else 0.0)
                results[k].append(ap)
        return [(f"map@{k}", float(np.mean(results[k])))
                for k in self.eval_at]


# --- factory (src/metric/metric.cpp:11-55) --------------------------------

_METRICS = {
    "l2": L2Metric, "mean_squared_error": L2Metric, "mse": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "l2_root": RMSEMetric, "root_mean_squared_error": RMSEMetric,
    "rmse": RMSEMetric,
    "l1": L1Metric, "mean_absolute_error": L1Metric, "mae": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberLossMetric,
    "fair": FairLossMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multiclass_ova": MultiLoglossMetric, "ova": MultiLoglossMetric,
    "ovr": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivergenceMetric, "kldiv": KLDivergenceMetric,
}


def metric_alias(name: str) -> str:
    n = name.strip().lower()
    return _METRICS[n].name if n in _METRICS else n


def create_metric(name: str, config) -> Optional[Metric]:
    n = name.strip().lower()
    if n in ("", "none", "null", "na", "custom"):
        return None
    if n.startswith("ndcg@") or n.startswith("map@"):
        base, at = n.split("@", 1)
        config.eval_at = [int(x) for x in at.split(",")]
        n = base
    if n not in _METRICS:
        log.warning("Unknown metric %s", name)
        return None
    return _METRICS[n](config)


def create_metrics(names: Sequence[str], config, metadata,
                   num_data: int) -> List[Metric]:
    out = []
    seen = set()
    for name in names:
        m = create_metric(name, config)
        if m is not None and m.name not in seen:
            m.init(metadata, num_data)
            seen.add(m.name)
            out.append(m)
    return out


def default_metric_for_objective(objective_name: str) -> str:
    """Config::GetMetricType fallback: metric defaults to objective."""
    return objective_name
