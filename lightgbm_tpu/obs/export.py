"""Live metrics export: a periodic snapshot daemon for the registry.

The run report (obs/recorder.py) is batch-shaped — one artifact AFTER
train() returns. A serving-shaped run (the lrb.py retrain-while-serve
loop, a long bench) needs its telemetry **while it runs**: this module
snapshots the default registry (obs/registry.py) on a fixed interval
from a daemon thread and publishes it three ways:

- ``<base>.prom`` — Prometheus text-exposition format, atomically
  replaced every interval (a node_exporter-style textfile, scrapeable
  by pointing a textfile collector at it);
- ``<base>.jsonl`` — an append-only time series, one snapshot per
  line (``{"ts": ..., "counters": ..., "gauges": ..., "phases": ...,
  "histograms": ...}``) — tail/grep-able during the run, plottable
  after it;
- an optional stdlib ``http.server`` endpoint (``tpu_metrics_port``)
  serving ``GET /metrics`` (Prometheus text), ``GET /metrics.json``
  (the raw snapshot), ``GET /healthz`` (liveness + last-snapshot age +
  SLO budget state — the fleet health-check body, JSON, answers 200
  even before the first snapshot completes) and ``GET /slo`` (the SLO
  engine's full budget report, obs/slo.py) for scraping a live run
  without touching disk.

The exporter thread is also the SLO engine's clock: every interval it
evaluates the armed specs (obs/slo.py) BEFORE snapshotting, so the
``slo/*`` budget gauges ride the same Prometheus text and JSONL time
series as everything else, and it feeds each snapshot's counters/
gauges to the flight recorder's recent-metrics ring (obs/flight.py).
An ``exporter/last_snapshot_age_s`` gauge makes the exporter's OWN
staleness observable — a wedged writer thread shows up in the very
artifacts it stopped writing (and on a live ``/metrics`` scrape).

Config knobs: ``tpu_metrics_export`` (the base path; a ``.prom`` /
``.jsonl`` suffix is stripped), ``tpu_metrics_interval_s``,
``tpu_metrics_port`` (0 = no HTTP). Drivers call
``ensure_from_config`` — the exporter is process-global and idempotent,
so the sliding-window loop starts it once and every later booster
joins it.

Standard library only, like the registry and tracer — the exporter
thread must be importable (and startable) before jax ever loads.
"""
from __future__ import annotations

import atexit
import json
import re
import threading
import time
from typing import Optional

from ..utils.fileio import atomic_write
from . import identity
from .registry import MetricsRegistry, default_registry
from .trace import config_get

__all__ = [
    "MetricsExporter", "prometheus_text", "ensure_from_config",
    "global_exporter", "shutdown",
]

DEFAULT_INTERVAL_S = 5.0

# Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*; our registry names
# use "/" domains ("ingest/h2d_bytes") — sanitize + namespace prefix
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "lgbm_tpu_"


def _prom_name(name: str) -> str:
    san = _NAME_RE.sub("_", name)
    if not san or not (san[0].isalpha() or san[0] in "_:"):
        san = "_" + san
    return _PREFIX + san


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(snapshot: dict) -> str:
    """Render a registry snapshot (MetricsRegistry.snapshot()) to the
    Prometheus text-exposition format: counters and gauges one sample
    each, timers as ``_seconds_total``/``_calls_total`` counters plus a
    ``_max_seconds`` gauge, histograms in the native histogram format
    (cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``)."""
    lines = []

    def emit(name, mtype, value, labels=""):
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name}{labels} {value}")

    ident = snapshot.get("identity")
    if isinstance(ident, dict):
        # rank identity as an info-style gauge: constant 1, the record
        # in the labels — the Prometheus idiom for build/identity facts
        labels = ",".join(f'{k}="{ident[k]}"' for k in
                          ("machine_rank", "world", "incarnation")
                          if k in ident)
        emit(_PREFIX + "identity_info", "gauge", "1", "{" + labels + "}")
    for name, v in snapshot.get("counters", {}).items():
        emit(_prom_name(name) + "_total", "counter", _fmt(v))
    for name, v in snapshot.get("gauges", {}).items():
        emit(_prom_name(name), "gauge", _fmt(v))
    for name, rec in snapshot.get("phases", {}).items():
        base = _prom_name(name)
        emit(base + "_seconds_total", "counter", _fmt(rec["total_s"]))
        emit(base + "_calls_total", "counter", _fmt(rec["calls"]))
        emit(base + "_max_seconds", "gauge", _fmt(rec["max_s"]))
    for name, h in snapshot.get("histograms", {}).items():
        base = _prom_name(name)
        lines.append(f"# TYPE {base} histogram")
        cum = 0
        for b in sorted(h.get("buckets", {}), key=float):
            cum += h["buckets"][b]
            lines.append(f'{base}_bucket{{le="{float(b):g}"}} {cum}')
        lines.append(f'{base}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{base}_sum {_fmt(h.get('sum', 0.0))}")
        lines.append(f"{base}_count {h['count']}")
        # pre-computed p99.9 gauge: fleet-scale tail latency lives
        # past p99, and histogram_quantile() at p99.9 needs bucket
        # resolution a scraper cannot assume — export the registry's
        # own interpolated estimate alongside the native buckets
        if h.get("p999") is not None:
            emit(base + "_p999", "gauge", _fmt(h["p999"]))
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """Periodic registry snapshotter: files + optional HTTP endpoint.

    ``start()`` writes one snapshot immediately (a run that dies
    before the first interval still leaves evidence) and launches the
    daemon thread; ``stop()`` writes a final snapshot and joins. The
    thread is a daemon either way — a forgotten exporter can never
    hold the process open.
    """

    def __init__(self, base_path: str = "",
                 interval_s: float = DEFAULT_INTERVAL_S,
                 port: int = -1,
                 registry: Optional[MetricsRegistry] = None):
        base = str(base_path or "")
        for suffix in (".prom", ".jsonl", ".json"):
            if base.endswith(suffix):
                base = base[: -len(suffix)]
        self.base_path = base
        self.interval_s = max(float(interval_s or DEFAULT_INTERVAL_S),
                              0.01)
        self.port = int(port)
        self._reg = registry or default_registry()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._server_thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()
        self.snapshots_written = 0
        self._write_warned = False
        self._last_snapshot_t: Optional[float] = None   # monotonic

    # -- paths ---------------------------------------------------------------

    @property
    def prom_path(self) -> str:
        return f"{self.base_path}.prom" if self.base_path else ""

    @property
    def jsonl_path(self) -> str:
        return f"{self.base_path}.jsonl" if self.base_path else ""

    @property
    def http_port(self) -> Optional[int]:
        """The bound port (resolves port=0 ephemeral binds); None when
        no server is running."""
        if self._server is None:
            return None
        return self._server.server_address[1]

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MetricsExporter":
        if self.port >= 0:
            try:
                self._start_server()
            except (OSError, OverflowError, ValueError) as e:
                # export is an observability aid: a taken/invalid port
                # (two runs sharing tpu_metrics_port, a bad extra_params
                # value) must not take training down — files still flow
                from ..utils import log
                log.warning("metrics HTTP endpoint on port %d failed "
                            "(%s); continuing without it", self.port, e)
                self._server = None
                self._server_thread = None
        self._write_once()
        self._thread = threading.Thread(
            target=self._run, name="metrics-exporter", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_snapshot: bool = True) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
            if self._server_thread is not None:
                self._server_thread.join(timeout=5.0)
            self._server = None
            self._server_thread = None
        if final_snapshot:
            self._write_once()

    def _run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            self._write_once()

    # -- snapshot writers ----------------------------------------------------

    def _snapshot(self) -> dict:
        # exporter self-staleness: the age of the last COMPLETED
        # snapshot, refreshed on every snapshot read (a live /metrics
        # scrape of a wedged writer thread sees the age growing)
        if self._last_snapshot_t is not None:
            self._reg.gauge("exporter/last_snapshot_age_s").set(
                round(time.monotonic() - self._last_snapshot_t, 3))
        snap = self._reg.snapshot()
        snap["ts"] = round(time.time(), 3)
        snap["uptime_s"] = round(time.monotonic() - self._t0, 3)
        snap["identity"] = identity.identity()
        # rank-0 cluster rollups (obs/clusterobs.py) fold into the
        # same snapshot the .prom/.jsonl//metrics surfaces publish —
        # only for the default-registry exporter (a private test
        # registry must not inherit global cluster state)
        if self._reg is default_registry():
            from . import clusterobs
            cs = clusterobs.cluster_snapshot()
            if cs is not None:
                for domain in ("counters", "gauges", "histograms"):
                    snap.setdefault(domain, {}).update(
                        cs.get(domain) or {})
        return snap

    def last_snapshot_age_s(self) -> Optional[float]:
        """Seconds since the last completed snapshot; None before the
        first one (the /healthz first-scrape race answers null, not a
        crash)."""
        if self._last_snapshot_t is None:
            return None
        return round(time.monotonic() - self._last_snapshot_t, 3)

    def _evaluate_slo(self) -> None:
        """The exporter thread IS the SLO engine's clock: evaluate the
        armed specs so the slo/* budget gauges land in the snapshot
        written right after (evaluate never raises)."""
        from . import slo as _slo
        eng = _slo.global_engine()
        if eng is not None:
            eng.evaluate()

    def _write_once(self) -> None:
        # the exporter interval is ALSO the rollup clock: rank 0 pulls
        # every rank's newest digest from the coordination KV before
        # evaluating SLOs, so cluster/* instruments are fresh for both
        # the SLO engine and the snapshot below (no-op off rank 0 or
        # single-process)
        if self._reg is default_registry():
            from . import clusterobs
            try:
                clusterobs.maybe_refresh_from_kv()
            except Exception:       # noqa: BLE001 — telemetry aid
                pass
        self._evaluate_slo()
        if not self.base_path:
            # HTTP-only mode: no files, but the tick still snapshots —
            # the flight recorder's recent-metrics ring must fill
            # whether or not anything lands on disk
            from . import flight as _flight
            fr = _flight.get()
            if fr is not None:
                fr.note_metrics(self._snapshot())
            self.snapshots_written += 1
            self._last_snapshot_t = time.monotonic()
            return
        try:
            from ..utils import faults
            if faults.active():
                faults.check("export.write")
            snap = self._snapshot()
            # .prom: atomic replace (scrapers must never read a torn
            # file); .jsonl: append-only time series
            with atomic_write(self.prom_path) as fh:
                fh.write(prometheus_text(snap))
            with open(self.jsonl_path, "a") as fh:
                fh.write(json.dumps(snap) + "\n")
            self.snapshots_written += 1
            self._last_snapshot_t = time.monotonic()
            # black-box feed: the flight recorder keeps the last few
            # interval snapshots' counters/gauges (obs/flight.py)
            from . import flight as _flight
            fr = _flight.get()
            if fr is not None:
                fr.note_metrics(snap)
        except Exception as e:          # noqa: BLE001 — export is an
            # observability aid; a full disk (or an injected
            # export.write fault) must not take training down — but an
            # operator watching for files that never appear deserves
            # ONE diagnostic
            if not self._write_warned:
                self._write_warned = True
                from ..utils import log
                log.warning("metrics export to %s failing (%s); will "
                            "keep retrying silently", self.base_path, e)

    # -- operational bodies --------------------------------------------------

    def health(self) -> dict:
        """The ``GET /healthz`` body: liveness, last-snapshot age and
        the compact SLO budget state. Total by construction — it must
        answer 200 on the very first scrape, before any snapshot has
        completed (``last_snapshot_age_s`` is null then) and with no
        SLO engine armed (``slo`` is null)."""
        from . import flight as _flight
        from . import slo as _slo
        eng = _slo.global_engine()
        slo_state = None
        budget_ok = True
        if eng is not None:
            try:
                slo_state = eng.summary()
                budget_ok = not slo_state.get("exhausted")
            except Exception:           # noqa: BLE001 — health must
                slo_state = {"error": "slo summary failed"}
        alive = not self._stop_ev.is_set()
        return {
            "ok": bool(alive and budget_ok),
            "alive": bool(alive),
            "budget_ok": bool(budget_ok),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "interval_s": self.interval_s,
            "snapshots_written": self.snapshots_written,
            "last_snapshot_age_s": self.last_snapshot_age_s(),
            "slo": slo_state,
            "flight_dumps": len(_flight.dump_paths()),
        }

    def slo_report(self) -> dict:
        """The ``GET /slo`` body: the engine's full budget report, or
        an explicit not-armed shape (still 200 — a scraper probing a
        fleet must distinguish 'no SLOs configured' from 'down').

        Non-mutating: the EXPORTER interval is the engine's clock —
        a scrape returns the last evaluation (evaluating once only if
        none has happened yet), so an aggressive external scraper
        cannot shrink the burn-rate windows or inflate the gauge-tick
        budgets."""
        from . import slo as _slo
        eng = _slo.global_engine()
        if eng is None:
            return {"enabled": False, "specs": []}
        rep = dict(eng.report(fresh=False))
        rep["enabled"] = True
        return rep

    # -- HTTP ----------------------------------------------------------------

    def _start_server(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):              # noqa: N802 — stdlib API
                route = self.path.split("?")[0]
                if route == "/metrics":
                    body = prometheus_text(exporter._snapshot())
                    ctype = "text/plain; version=0.0.4"
                elif route == "/metrics.json":
                    body = json.dumps(exporter._snapshot())
                    ctype = "application/json"
                elif route in ("/healthz", "/health"):
                    body = json.dumps(exporter.health())
                    ctype = "application/json"
                elif route == "/slo":
                    body = json.dumps(exporter.slo_report())
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):      # silence per-request stderr
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", max(self.port, 0)),
                                           Handler)
        self._server.daemon_threads = True
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, name="metrics-http",
            daemon=True)
        self._server_thread.start()


# ---------------------------------------------------------------------------
# process-global exporter (drivers join it; tests build private ones)
# ---------------------------------------------------------------------------

_global: Optional[MetricsExporter] = None
_global_lock = threading.Lock()
_atexit_installed = False


def _atexit_flush() -> None:
    """Final snapshot at interpreter exit (the tracer's safety-net
    pattern): without it, everything recorded in the last interval
    window — the final lrb windows, finish-time counters — would be
    missing from the on-disk artifacts."""
    ex = _global
    if ex is not None:
        try:
            ex.stop(final_snapshot=True)
        except Exception:               # noqa: BLE001 — teardown
            pass


def ensure_from_config(config) -> Optional[MetricsExporter]:
    """Start the process-global exporter when ``tpu_metrics_export``
    (or ``tpu_metrics_port`` > 0) is configured; later callers with the
    same base path join the running daemon. Accepts a Config or a raw
    params dict."""
    global _global
    base = str(config_get(config, "tpu_metrics_export", "") or "")
    port = int(config_get(config, "tpu_metrics_port", 0) or 0)
    if not base and port <= 0:
        return None
    # cluster policy (obs/identity.py): every rank gets its own file
    # target (no more atomic-replace races on one .prom), and only
    # rank 0 serves HTTP — by policy, not by bind-failure accident
    base = identity.rank_suffixed(base)
    if port > 0 and identity.is_multiprocess() and identity.rank() != 0:
        from ..utils import log
        log.info("metrics HTTP endpoint is rank-0-only; rank %d "
                 "exports to files/ring only", identity.rank())
        port = 0
    interval = float(config_get(config, "tpu_metrics_interval_s",
                                DEFAULT_INTERVAL_S)
                     or DEFAULT_INTERVAL_S)
    global _atexit_installed
    with _global_lock:
        if _global is not None:
            if base and _global.base_path != base:
                from ..utils import log
                log.warning(
                    "metrics exporter already running to %s; "
                    "tpu_metrics_export=%s ignored for this process "
                    "(one exporter per process)",
                    _global.base_path or "<http only>", base)
            return _global
        _global = MetricsExporter(
            base_path=base, interval_s=interval,
            port=port if port > 0 else -1).start()
        if not _atexit_installed:
            atexit.register(_atexit_flush)
            _atexit_installed = True
        from ..utils import log
        where = []
        if base:
            where.append(f"{base}.prom/.jsonl every {interval:g}s")
        if _global.http_port is not None:
            where.append(f"http://127.0.0.1:{_global.http_port}/metrics")
        log.info("metrics exporter started (%s)", ", ".join(where))
        return _global


def global_exporter() -> Optional[MetricsExporter]:
    return _global


def shutdown() -> None:
    """Stop the process-global exporter (tests / clean teardown)."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.stop()
            _global = None
