"""Per-iteration run recording + the versioned run-report artifact.

The RunRecorder is the training drivers' telemetry seam
(models/gbdt.py train, engine.train, bench.py): it times every boosting
iteration, samples device HBM in use and host->device transfer-byte
deltas, collects the per-iteration eval metric values, watches for
pathologically slow iterations, and at the end serializes the whole run
— iteration records plus the registry's phase table / counters /
histograms — to a versioned JSON (or JSONL) *run report* whose path
comes from the ``tpu_run_report`` config knob. Perf PRs diff these
artifacts instead of log tails.

Versioning follows the repo's binary-token discipline (io/dataset.py
BINARY_TOKEN, ops/autotune.py TUNING_CACHE_VERSION): readers check
``schema``/``version`` and refuse to misparse a future layout.

The recorder also owns two run-scoped behaviors:

- the structured log prefix: while a run is active every log line
  carries ``[t+<elapsed>s it=<iteration>]`` (utils/log.py
  set_run_context), so interleaved worker-thread logs are attributable;
- the slow-iteration watchdog: an iteration slower than
  ``tpu_watchdog_factor`` x the trailing median (last 64 iterations,
  armed after 8) logs a warning with the current phase table — the
  in-flight diagnosis for "training suddenly crawls" (retracing, queue
  stalls, host fallback).

Distributed runs ride the same schema (no version bump): the free-form
``meta`` section carries ``mesh_devices`` (the resolved mesh size) and
each iteration record gains ``comm_bytes`` — the logical psum payload
of that iteration's wave-histogram reductions, filled by the driver
from the end-of-run wave counts (models/gbdt.py
_comm_bytes_per_iteration) — alongside the cumulative
``comm/psum_bytes`` / ``comm/psum_passes`` counters.
"""
from __future__ import annotations

import json
import statistics
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..analysis import lockorder
from ..utils import log, timing
from . import identity
from . import trace
from .registry import MetricsRegistry, default_registry

RUN_REPORT_SCHEMA = "lightgbm-tpu/run-report"
RUN_REPORT_VERSION = 1

# watchdog shape: median over this many trailing iterations, armed only
# once this many samples exist (the compile-heavy first iterations must
# not be judged against an empty history)
WATCHDOG_WINDOW = 64
WATCHDOG_MIN_HISTORY = 8


def _hbm_bytes_in_use() -> Optional[int]:
    """Device HBM in use via memory_stats(); None where the backend
    doesn't report (CPU jax) — callers skip the field."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            v = stats.get("bytes_in_use")
            if v is not None:
                return int(v)
    except Exception:                   # noqa: BLE001 — absence == None
        pass
    return None


class RunRecorder:
    """Collects one training run; serializes it to the run report."""

    def __init__(self, path: str = "", watchdog_factor: float = 0.0,
                 meta: Optional[dict] = None,
                 registry: Optional[MetricsRegistry] = None):
        # one report per rank under world>1 (obs/identity.py) — N
        # ranks handed the same tpu_run_report must never clobber
        self.path = identity.rank_suffixed(path or "")
        self.watchdog_factor = float(watchdog_factor or 0.0)
        self.meta = dict(meta or {})
        self._reg = registry or default_registry()
        self._lock = lockorder.named_lock("obs.recorder._lock")
        self._by_it: Dict[int, dict] = {}
        # per-kind trailing windows ("iter" vs "sync" spans must not
        # be judged against each other's medians)
        self._recent: Dict[str, deque] = {}
        self._t0: Optional[float] = None
        self._started_unix: Optional[float] = None
        self._cur_it: Optional[int] = None
        self._span_t0: Optional[float] = None
        self._last_h2d = 0
        self._hbm_ok = True
        self._finished = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RunRecorder":
        self._t0 = time.monotonic()
        self._started_unix = time.time()
        self._last_h2d = self._h2d_total()
        log.set_run_context(self._log_context)
        return self

    def _log_context(self):
        if self._t0 is None:
            return None
        return (time.monotonic() - self._t0, self._cur_it)

    # -- per-iteration spans -------------------------------------------------

    @contextmanager
    def iteration(self, it: int):
        self.begin_iteration(it)
        try:
            yield
        finally:
            self.end_iteration(it)

    def begin_iteration(self, it: int) -> None:
        self._cur_it = it
        self._span_t0 = time.monotonic()

    def end_iteration(self, it: int, kind: str = "iter") -> None:
        t0 = self._span_t0
        self._span_t0 = None
        if t0 is None:
            return
        self.observe_iteration(it, time.monotonic() - t0, kind)

    def tick(self, it: int, evals=None) -> None:
        """Callback-driven span accounting (engine.train): called once
        after each iteration; the span is the time since the previous
        tick (or start). ``evals``: the iteration's
        evaluation_result_list ((dataset, metric, value, bigger)
        tuples)."""
        now = time.monotonic()
        t0 = self._span_t0 if self._span_t0 is not None else self._t0
        self._cur_it = it
        self._span_t0 = now
        if t0 is not None:
            self.observe_iteration(it, now - t0)
        if evals:
            for tup in evals:
                self.record_eval(it, str(tup[0]), str(tup[1]),
                                 float(tup[2]))

    def observe_iteration(self, it: int, wall_s: float,
                          kind: str = "iter") -> None:
        """Record one iteration's wall time + device samples and run
        the watchdog. Public so the drivers (and tests) can feed spans
        they timed themselves. ``kind`` partitions the watchdog's
        trailing medians: jax dispatch is async, so an iteration that
        the driver KNOWS performed a blocking drain (periodic stop
        check / queue drain, models/gbdt.py) legitimately absorbs many
        iterations of queued device time — judging it against
        issue-only spans would false-positive every drain interval.
        Such spans are tagged kind="sync" and compared only against
        each other."""
        rec = self._rec(it)
        h2d = self._h2d_total()
        with self._lock:
            rec["wall_s"] = round(float(wall_s), 6)
            if kind != "iter":
                rec["sync"] = True
            if h2d > self._last_h2d:
                rec["h2d_bytes"] = h2d - self._last_h2d
            self._last_h2d = h2d
        if self._hbm_ok:
            hbm = _hbm_bytes_in_use()
            if hbm is None:
                self._hbm_ok = False    # backend doesn't report; stop asking
            else:
                with self._lock:
                    rec["hbm_bytes_in_use"] = hbm
                self._reg.gauge("device/hbm_bytes_in_use").set(hbm)
        self._reg.histogram("train/iteration_s").observe(wall_s)
        self._watchdog(it, wall_s, kind)

    def _watchdog(self, it: int, wall_s: float, kind: str) -> None:
        recent = self._recent.get(kind)
        if recent is None:
            recent = self._recent[kind] = deque(maxlen=WATCHDOG_WINDOW)
        armed = (self.watchdog_factor > 0
                 and len(recent) >= WATCHDOG_MIN_HISTORY)
        if armed:
            med = statistics.median(recent)
            if med > 0 and wall_s > self.watchdog_factor * med:
                self._reg.counter("watchdog/slow_iterations").add(1)
                # instant marker on the trace timeline: a slow
                # iteration is visible in Perfetto exactly where it
                # happened, not only as a log line
                trace.instant("watchdog/slow_iteration", cat="event",
                              args={"it": int(it),
                                    "wall_s": round(float(wall_s), 6),
                                    "median_s": round(float(med), 6)})
                log.warning(
                    "slow iteration %d: %.3f s vs trailing median "
                    "%.3f s (%.1fx, threshold %.1fx); phase table:\n%s",
                    it, wall_s, med, wall_s / med, self.watchdog_factor,
                    timing.report() or "  (no phases recorded)")
                # black box: the watchdog firing is a postmortem
                # moment — dump the flight bundle with the state AT
                # the stall, not whatever survives to run end
                # (rate-limited there; obs/flight.py)
                from . import flight
                flight.trigger("watchdog",
                               {"it": int(it),
                                "wall_s": round(float(wall_s), 6),
                                "median_s": round(float(med), 6),
                                "factor": self.watchdog_factor})
        recent.append(float(wall_s))

    # -- per-iteration fields ------------------------------------------------

    def _rec(self, it: int) -> dict:
        with self._lock:
            rec = self._by_it.get(it)
            if rec is None:
                rec = self._by_it[it] = {"it": int(it)}
            return rec

    def record_eval(self, it: int, dataset: str, metric: str,
                    value: float) -> None:
        rec = self._rec(it)
        with self._lock:
            rec.setdefault("evals", {}).setdefault(dataset, {})[metric] \
                = float(value)

    def set_field(self, it: int, key: str, value) -> None:
        rec = self._rec(it)
        with self._lock:
            rec[key] = value

    def _h2d_total(self) -> int:
        """Total host->device bytes across every transfer counter (the
        ingest pipeline's chunked device_puts + the bulk bin uploads)."""
        return sum(v for k, v in self._reg.counter_items().items()
                   if "h2d" in k and k.endswith("bytes"))

    # -- report --------------------------------------------------------------

    def finish(self, leaves_per_iteration: Optional[List[List[int]]] = None,
               waves_per_iteration: Optional[List[int]] = None,
               extra: Optional[dict] = None) -> dict:
        """Assemble the run report (and write it when a path is set).
        ``leaves_per_iteration``: [iteration][class-tree] leaf counts,
        filled by the driver from ONE stacked device download at the
        end of the run. Idempotent: the first call wins."""
        if self._finished:
            return {}
        self._finished = True
        log.set_run_context(None)
        # cross-link report <-> trace: flush the tracer's ring so the
        # trace on disk covers this run, and record where it went
        if trace.enabled():
            trace_path = trace.write()
            if trace_path:
                self.meta.setdefault("trace_path", trace_path)
        # cross-link report <-> flight dumps: any postmortem bundle
        # the black box wrote this process (watchdog, faults, degraded
        # windows, SLO exhaustion — obs/flight.py) is findable FROM
        # the run report
        from . import flight
        dumps = flight.dump_paths()
        if dumps:
            self.meta.setdefault("flight_dumps", dumps)
        # who produced this report: rank/world/incarnation — the key
        # a cross-rank investigation joins artifacts on
        self.meta.setdefault("identity", identity.identity())
        if leaves_per_iteration is not None:
            for i, grp in enumerate(leaves_per_iteration):
                self._rec(i + 1)["leaves"] = [int(x) for x in grp]
        if waves_per_iteration is not None:
            for i, w in enumerate(waves_per_iteration):
                self._rec(i + 1)["waves"] = int(w)
        snap = self._reg.snapshot()
        phases = dict(sorted(snap["phases"].items(),
                             key=lambda kv: -kv[1]["total_s"]))
        with self._lock:
            iterations = [self._by_it[k] for k in sorted(self._by_it)]
        report = {
            "schema": RUN_REPORT_SCHEMA,
            "version": RUN_REPORT_VERSION,
            "created_unix": (round(self._started_unix, 3)
                             if self._started_unix else None),
            "wall_s": (round(time.monotonic() - self._t0, 6)
                       if self._t0 is not None else None),
            "meta": self.meta,
            "phases": phases,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap["histograms"],
            "iterations": iterations,
        }
        if extra:
            report["extra"] = dict(extra)
        if self.path:
            try:
                self._write(report)
                log.info("run report written to %s (%d iterations)",
                         self.path, len(iterations))
            except OSError as e:
                log.warning("could not write run report %s: %s",
                            self.path, e)
        return report

    def _write(self, report: dict) -> None:
        """Atomic write (utils/fileio.py, the tuning-cache discipline).
        ``*.jsonl`` paths stream one record per line — header,
        iterations, summary — so megarun reports stay grep/tail-able;
        anything else is one JSON document."""
        from ..utils.fileio import atomic_write
        with atomic_write(self.path) as fh:
            if self.path.endswith(".jsonl"):
                head = {k: report[k] for k in
                        ("schema", "version", "created_unix", "meta")}
                head["kind"] = "header"
                fh.write(json.dumps(head) + "\n")
                for rec in report["iterations"]:
                    fh.write(json.dumps({"kind": "iteration", **rec})
                             + "\n")
                summary = {"kind": "summary"}
                for k in ("wall_s", "phases", "counters", "gauges",
                          "histograms", "extra"):
                    if k in report:
                        summary[k] = report[k]
                fh.write(json.dumps(summary) + "\n")
            else:
                json.dump(report, fh, indent=1)


def load_run_report(path: str) -> dict:
    """Parse a run report (either format) back into the ``finish()``
    dict shape; raises ValueError on schema/version mismatch — a
    future layout is refused, never misread."""
    with open(path) as fh:
        if path.endswith(".jsonl"):
            report: dict = {"iterations": []}
            for ln in fh:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                kind = rec.pop("kind", None)
                if kind == "iteration":
                    report["iterations"].append(rec)
                else:                   # header / summary merge flat
                    report.update(rec)
        else:
            report = json.load(fh)
    if report.get("schema") != RUN_REPORT_SCHEMA:
        raise ValueError(f"{path}: not a run report "
                         f"(schema={report.get('schema')!r})")
    if report.get("version") != RUN_REPORT_VERSION:
        raise ValueError(f"{path}: run report version "
                         f"{report.get('version')!r}, reader wants "
                         f"{RUN_REPORT_VERSION}")
    return report
