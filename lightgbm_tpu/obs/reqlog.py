"""Request-scoped wide events: the per-request identity of the
serving path.

The telemetry built so far is either aggregate (the registry's
counters/histograms, obs/registry.py) or span-shaped (the Chrome trace
ring, obs/trace.py). Neither answers the operator's first question
about a slow or wrong answer: *which request*, against *which model*,
in *which window*? This module adds that identity:

- **Request ids** are issued monotonically process-wide
  (``next_request_id``) by the serving entry points — the lrb loop's
  evaluation micro-batches and ``predict_live`` path,
  ``bench.py --serve`` — and carried through the predict stack in a
  thread-local *request context* (``request(...)``), so the layers in
  between can tag what they see: trace spans carry ``req_id``/
  ``window`` in their args, and the serve-bucket seam
  (ops/predict_cache.py ``serve_bucket_rows``) notes the padded batch
  width the request actually rode (``note_bucket``).
- **Wide events** — ONE structured record per request batch and per
  lrb window, carrying everything an investigation needs in one line
  (latency, rows, the serving model's window/generation, the serve
  bucket, degraded/staleness state) — land in a bounded in-memory ring
  ALWAYS (the flight recorder's feed, obs/flight.py) and, when
  ``tpu_reqlog`` names a path, in an append-only JSONL file.
- **Sampling** (``tpu_reqlog_sample``) applies to the FILE only, and
  is a deterministic pure function of the request id (the repo's
  lowbias32 hash idiom, shard-invariant by construction): the same id
  is sampled on every run at the same rate, so two runs' logs cover
  the same requests and a reported id can be checked against the
  knob. Window/degraded records are never sampled out — there are few
  and they are the ones postmortems start from.

Standard library only, like the registry and tracer; the ring is on
whether or not a file path is configured (``record`` is a dict build
plus a deque append), so the flight recorder always has recent
request evidence to dump.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

from ..analysis import lockorder
from . import identity
from .trace import config_get

__all__ = [
    "RequestLog", "next_request_id", "request", "current",
    "note_bucket", "record", "get", "ensure_from_config", "shutdown",
    "REQLOG_SCHEMA", "REQLOG_VERSION",
]

REQLOG_SCHEMA = "lightgbm-tpu/reqlog"
REQLOG_VERSION = 1

DEFAULT_RING_RECORDS = 1024

# record kinds that are never sampled out of the file: windows and
# degraded windows are few, and they anchor every postmortem
ALWAYS_LOGGED_KINDS = ("window", "degraded_window")

# -- request ids -------------------------------------------------------------

_id_lock = threading.Lock()
_next_id = 0


def next_request_id() -> int:
    """Monotonically-issued process-wide request/batch id (1-based)."""
    global _next_id
    with _id_lock:
        _next_id += 1
        return _next_id


def _mix32(x: int) -> int:
    """lowbias32 (the PR-4 shard-invariant sampling hash): a cheap
    high-quality avalanche so consecutive ids sample independently."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


# -- the thread-local request context ---------------------------------------


class RequestContext:
    """What the layers below the serving entry can see of the current
    request: its id, the window it belongs to, and (filled by the
    serve-bucket seam) the padded batch width it rode."""
    __slots__ = ("req_id", "window", "bucket")

    def __init__(self, req_id: int, window: Optional[int] = None):
        self.req_id = int(req_id)
        self.window = window
        self.bucket: Optional[int] = None


_tls = threading.local()


@contextmanager
def request(req_id: Optional[int] = None, window: Optional[int] = None):
    """Install a request context for the calling thread's predict
    path; nests (the previous context is restored on exit)."""
    rid = next_request_id() if req_id is None else int(req_id)
    prev = getattr(_tls, "ctx", None)
    ctx = RequestContext(rid, window)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def current() -> Optional[RequestContext]:
    """The calling thread's active request context, or None."""
    return getattr(_tls, "ctx", None)


def note_bucket(bucket: int) -> None:
    """Called from the serve-bucket seam (ops/predict_cache.py
    serve_bucket_rows): record the padded width the current request's
    batch dispatched at. Free no-op without an active context."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.bucket = int(bucket)


# -- the wide-event log ------------------------------------------------------


class RequestLog:
    """Bounded ring of wide events + optional sampled JSONL file."""

    def __init__(self, path: str = "", sample: float = 1.0,
                 ring_records: int = DEFAULT_RING_RECORDS,
                 registry=None):
        self.path = str(path or "")
        self.sample = min(max(float(sample), 0.0), 1.0)
        self._threshold = int(self.sample * 4294967296.0)
        self._ring: deque = deque(maxlen=max(int(ring_records), 16))
        self._lock = lockorder.named_lock("obs.reqlog._lock")
        self._fh = None
        self._write_warned = False
        if registry is None:
            from . import registry as _reg
            registry = _reg.default_registry()
        self._reg = registry
        self.records_written = 0

    # -- sampling ------------------------------------------------------------

    def sampled(self, req_id) -> bool:
        """Deterministic per-id file-sampling decision: a pure
        function of (id, rate) — every instance at the same rate
        samples the same ids."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0 or req_id is None:
            return False
        return _mix32(int(req_id)) < self._threshold

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, req_id=None, **fields) -> dict:
        """One wide event: always into the ring (the flight recorder's
        evidence), into the file when configured and (for request
        records) the id samples in. Returns the record."""
        rec = {"ts": round(time.time(), 6), "kind": str(kind)}
        if req_id is not None:
            rec["req_id"] = int(req_id)
        if identity.is_multiprocess():
            # every wide event carries its rank under world>1, so N
            # ranks' files interleave attributably (obs/identity.py);
            # single-process records stay byte-identical
            rec["rank"] = identity.rank()
            if identity.incarnation():
                rec["inc"] = identity.incarnation()
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        self._ring.append(rec)
        self._reg.counter("reqlog/records").add(1)
        if self.path and (kind in ALWAYS_LOGGED_KINDS
                          or self.sampled(req_id)):
            self._write(rec)
        return rec

    def _write(self, rec: dict) -> None:
        try:
            with self._lock:
                if self._fh is None:
                    # append-only JSONL, the exporter's time-series
                    # discipline (obs/export.py): a header line makes
                    # the file self-describing for readers
                    # (tools/trace_summary.py)
                    self._fh = open(self.path, "a")
                    self._fh.write(json.dumps({
                        "kind": "header", "schema": REQLOG_SCHEMA,
                        "version": REQLOG_VERSION,
                        "sample": self.sample,
                        "identity": identity.identity(),
                        "started_unix": round(time.time(), 3)}) + "\n")
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
                self.records_written += 1
        except Exception as e:          # noqa: BLE001 — observability
            # aid: a full disk must not take serving down, but the
            # operator deserves ONE diagnostic (export.py discipline)
            self._reg.counter("reqlog/write_failures").add(1)
            if not self._write_warned:
                self._write_warned = True
                from ..utils import log
                log.warning("request log %s failing (%s); in-memory "
                            "ring keeps recording", self.path, e)

    def recent(self, n: Optional[int] = None) -> list:
        """The newest ``n`` (default: all ringed) wide events — the
        flight recorder pulls these into its postmortem bundle."""
        out = list(self._ring)
        return out if n is None else out[-int(n):]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# -- module-global instance (drivers join it; tests build private ones) ------

_global: Optional[RequestLog] = None
_global_lock = threading.Lock()


def get(create: bool = True) -> Optional[RequestLog]:
    """The process-global request log; created ring-only on first use
    (the ring is the always-on half — a file needs ``tpu_reqlog``)."""
    global _global
    if _global is None and create:
        with _global_lock:
            if _global is None:
                _global = RequestLog()
    return _global


def record(kind: str, req_id=None, **fields) -> dict:
    """Record a wide event on the global log (see RequestLog.record)."""
    return get().record(kind, req_id=req_id, **fields)


def ensure_from_config(config) -> Optional[RequestLog]:
    """Configure the global log from ``tpu_reqlog`` (file path) and
    ``tpu_reqlog_sample`` (deterministic per-id file sampling rate).
    Idempotent; a later caller naming a DIFFERENT path warns and keeps
    the running log (one request log per process, like the exporter)."""
    global _global
    path = str(config_get(config, "tpu_reqlog", "") or "")
    # one wide-event file per rank under world>1 (obs/identity.py) —
    # append-mode interleave across processes would tear records
    path = identity.rank_suffixed(path)
    sample = float(config_get(config, "tpu_reqlog_sample", 1.0))
    with _global_lock:
        if _global is None:
            _global = RequestLog(path, sample)
            if path:
                from ..utils import log
                log.info("request log -> %s (sample %g)", path, sample)
            return _global
        if path and not _global.path:
            # a ring-only default upgraded to a file by the first
            # driver that names one: adopt path AND rate together
            _global.path = path
            _global.sample = min(max(sample, 0.0), 1.0)
            _global._threshold = int(_global.sample * 4294967296.0)
            from ..utils import log
            log.info("request log -> %s (sample %g)", path, sample)
        elif path and _global.path != path:
            from ..utils import log
            log.warning("request log already writing to %s; "
                        "tpu_reqlog=%s ignored for this process "
                        "(one request log per process)",
                        _global.path, path)
        return _global


def shutdown() -> None:
    """Close and drop the global log (tests / clean teardown)."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.close()
            _global = None
