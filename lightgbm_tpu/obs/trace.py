"""Cross-thread span tracing: a ring-buffered Chrome trace-event
recorder for the whole pipeline.

The run report (obs/recorder.py) answers "how long did iteration 140
take"; this module answers "what was every thread DOING while it ran".
One trace file shows the ingest prefetch worker slicing chunk k+1 while
the main thread dispatches chunk k's bin kernel, the step-cache
compiling (or hitting) the fused step, each boosting iteration, and —
in the sliding-window driver (lrb.py) — the derive/train/evaluate
phases of every window, all on a shared clock.

Output is the Chrome trace-event JSON format (the ``traceEvents``
array form), loadable in Perfetto (ui.perfetto.dev) and chrome://
tracing:

- spans are complete events (``ph == "X"``: ``ts``/``dur`` in
  microseconds, ``pid``/``tid`` integers);
- point-in-time markers (watchdog firings, step-cache hits/misses) are
  instant events (``ph == "i"``, thread scope);
- thread names are emitted as ``ph == "M"`` metadata records so
  Perfetto labels the ingest worker row "ingest-prefetch" instead of a
  bare thread id.

Design constraints (the registry's rules, obs/registry.py):

- **Thread-safe.** Spans are recorded from the ingest worker, the
  pipelined-eval path and the exporter thread concurrently; every
  mutation takes one lock. Events are appended at span EXIT (complete
  events carry their duration), so a span records with a single locked
  append — no cross-thread begin/end pairing.
- **Bounded.** The buffer is a ring (``tpu_trace_buffer`` events,
  config.py): a million-iteration serving loop keeps the LAST N events
  instead of growing without bound; ``dropped_events`` counts what the
  ring evicted (surfaced in the written file's metadata).
- **Dependency-free.** Standard library only — utils/timing.py imports
  this module at load time, exactly like the registry.
- **Off is free.** ``enabled()`` is a module-attribute read; every
  record call no-ops without taking the lock when no tracer is
  installed.

The module-global tracer is installed by ``configure`` (drivers call
``ensure_from_config`` with any Config/dict carrying ``tpu_trace``) and
the buffer is flushed to disk by ``write()`` — called by
RunRecorder.finish (which also cross-links ``meta.trace_path``), by
lrb.py after every window (so a live loop always has a current trace on
disk), and at interpreter exit as a safety net.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

from ..analysis import lockorder
from ..utils.fileio import atomic_write
from . import identity

__all__ = [
    "Tracer", "configure", "ensure_from_config", "stop", "active",
    "enabled", "span", "instant", "write", "config_get",
    "add_sink", "remove_sink",
]


def config_get(config, key: str, default=None):
    """Read a knob off a Config object (attribute) or a raw params
    dict (key) — the one accessor behind the telemetry daemons'
    ``ensure_from_config`` seams (this module and obs/export.py), so
    the two cannot drift. Returns ``default`` for missing OR
    explicitly-None values."""
    if isinstance(config, dict):
        v = config.get(key, default)
    else:
        v = getattr(config, key, default)
    return default if v is None else v

DEFAULT_BUFFER_EVENTS = 65536
MIN_BUFFER_EVENTS = 1024

# event sinks: callables fed EVERY recorded event dict, tracer or not
# (the flight recorder's always-on span ring, obs/flight.py). Fed
# outside the tracer's lock; a sink must be cheap and never raise.
_sinks: list = []
# fallback clock for sink-only events (no tracer installed): same
# perf_counter µs convention as Tracer.now_us, epoch at module import
_sink_t0_ns = time.perf_counter_ns()


def add_sink(fn) -> None:
    """Register an event sink (idempotent — re-registration of the
    same callable is a no-op)."""
    if fn not in _sinks:
        _sinks.append(fn)


def remove_sink(fn) -> None:
    if fn in _sinks:
        _sinks.remove(fn)


def _feed_sinks(ev: dict) -> None:
    for s in tuple(_sinks):
        try:
            s(ev)
        except Exception:               # noqa: BLE001 — a sink must
            pass                        # never break the traced path


def _sink_only_event(name: str, cat: str, ph: str, ts_us: float,
                     dur_us: Optional[float] = None,
                     args: Optional[dict] = None) -> None:
    """Record an event for the sinks when NO tracer is installed (the
    flight ring keeps span evidence even with tpu_trace off)."""
    ev = {"name": name, "cat": cat, "ph": ph, "ts": round(ts_us, 3),
          "pid": os.getpid(), "tid": _native_tid()}
    if ph == "X":
        ev["dur"] = round(max(dur_us or 0.0, 0.0), 3)
    elif ph == "i":
        ev["s"] = "t"
    if args:
        ev["args"] = args
    _stamp_rank(ev)
    _feed_sinks(ev)


def _stamp_rank(ev: dict) -> None:
    """Rank (and, once past the first re-shard, incarnation) into the
    event args under a multi-process world — per-event identity so a
    merged timeline (tools/trace_summary.py --merge) attributes every
    span without filename context. Free single-process."""
    if not identity.is_multiprocess():
        return
    args = ev.setdefault("args", {})
    args.setdefault("rank", identity.rank())
    inc = identity.incarnation()
    if inc:
        args.setdefault("inc", inc)


def _sink_now_us() -> float:
    return (time.perf_counter_ns() - _sink_t0_ns) / 1000.0


def _native_tid() -> int:
    try:
        return threading.get_native_id()
    except Exception:                   # noqa: BLE001 — pre-3.8 fallback
        return threading.get_ident() & 0x7FFFFFFF


class Tracer:
    """Ring-buffered trace-event recorder; one per process normally
    (the module global), private instances for tests."""

    def __init__(self, path: str, capacity: int = DEFAULT_BUFFER_EVENTS):
        self.path = str(path)
        self.capacity = max(int(capacity), MIN_BUFFER_EVENTS)
        self._lock = lockorder.named_lock("obs.trace._lock")
        self._events: deque = deque(maxlen=self.capacity)
        self._threads: dict = {}        # tid -> thread name
        self._dropped = 0
        self._pid = os.getpid()
        self._t0_ns = time.perf_counter_ns()
        self._started_unix = time.time()

    def resize(self, capacity: int) -> None:
        """Change the ring capacity in place, keeping the newest
        events (a later config naming the same trace path but a larger
        tpu_trace_buffer must not be silently ignored)."""
        capacity = max(int(capacity), MIN_BUFFER_EVENTS)
        with self._lock:
            if capacity == self.capacity:
                return
            self.capacity = capacity
            self._events = deque(self._events, maxlen=capacity)

    # -- clock ---------------------------------------------------------------

    def now_us(self) -> float:
        """Microseconds since tracer start — the shared ``ts`` clock
        (perf_counter is monotonic and thread-consistent)."""
        return (time.perf_counter_ns() - self._t0_ns) / 1000.0

    # -- recording -----------------------------------------------------------

    def _append(self, ev: dict) -> None:
        _stamp_rank(ev)
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(ev)
        _feed_sinks(ev)                 # outside the ring lock

    def _register_thread(self, tid: int) -> None:
        if tid not in self._threads:
            name = threading.current_thread().name
            with self._lock:
                self._threads.setdefault(tid, name)

    def complete(self, name: str, cat: str, start_us: float,
                 args: Optional[dict] = None) -> None:
        """Record a finished span [start_us, now] on the CALLING
        thread (complete events pair begin/end in one record, so
        cross-thread spans can never mis-nest)."""
        tid = _native_tid()
        self._register_thread(tid)
        end = self.now_us()
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(start_us, 3),
              "dur": round(max(end - start_us, 0.0), 3),
              "pid": self._pid, "tid": tid}
        if args:
            ev["args"] = args
        self._append(ev)

    def instant(self, name: str, cat: str = "event",
                args: Optional[dict] = None) -> None:
        """Record a point-in-time marker on the calling thread."""
        tid = _native_tid()
        self._register_thread(tid)
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": round(self.now_us(), 3),
              "pid": self._pid, "tid": tid}
        if args:
            ev["args"] = args
        self._append(ev)

    @contextmanager
    def span(self, name: str, cat: str = "phase",
             args: Optional[dict] = None):
        t0 = self.now_us()
        try:
            yield
        finally:
            self.complete(name, cat, t0, args)

    # -- stats / serialization ----------------------------------------------

    @property
    def dropped_events(self) -> int:
        with self._lock:
            return self._dropped

    def event_count(self) -> int:
        with self._lock:
            return len(self._events)

    def trace_document(self) -> dict:
        """The Perfetto-loadable JSON document for the current buffer:
        thread-name metadata records first, then the ring's events."""
        with self._lock:
            events = list(self._events)
            threads = dict(self._threads)
            dropped = self._dropped
        ident = identity.identity()
        pname = "lightgbm_tpu"
        if ident["world"] > 1:
            pname = f"lightgbm_tpu r{ident['machine_rank']}"
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": pname}},
                # the full identity record as process metadata, so a
                # merged multi-rank file keeps each process labeled
                {"name": "process_labels", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"labels": (
                     f"rank {ident['machine_rank']}/{ident['world']} "
                     f"inc {ident['incarnation']}")}}]
        for tid, tname in sorted(threads.items()):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": tname}})
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "schema": "lightgbm-tpu/trace",
                "version": 1,
                "started_unix": round(self._started_unix, 3),
                "dropped_events": dropped,
                "identity": ident,
            },
        }

    def write(self) -> str:
        """Dump the current buffer to ``path`` (atomic tmp+rename, the
        run-report discipline — utils/fileio.py). Idempotent —
        callable after every window of a live loop; each write
        replaces the file with the ring's current contents."""
        doc = self.trace_document()
        with atomic_write(self.path) as fh:
            json.dump(doc, fh)
        return self.path


# ---------------------------------------------------------------------------
# module-global tracer (the engine's default; tests build private ones)
# ---------------------------------------------------------------------------

_tracer: Optional[Tracer] = None
_atexit_installed = False


def configure(path: str, capacity: int = DEFAULT_BUFFER_EVENTS) -> Tracer:
    """Install (or re-target) the process-global tracer. Idempotent for
    the same path — the running buffer is kept so early spans (dataset
    ingest before the booster exists) survive. Re-targeting to a NEW
    path flushes the old tracer's buffer to its own file first, so
    spans recorded after its last write are not silently dropped."""
    global _tracer, _atexit_installed
    if _tracer is not None and _tracer.path == str(path):
        # honor a LARGER buffer knob on same-path reconfigure; never
        # shrink mid-run (a later caller with the default capacity —
        # e.g. a params dict without tpu_trace_buffer — must not drop
        # the events an earlier explicit knob sized the ring for)
        if capacity > _tracer.capacity:
            _tracer.resize(capacity)
        return _tracer
    if _tracer is not None:
        write()                 # never-raises flush of the old buffer
    _tracer = Tracer(path, capacity)
    if not _atexit_installed:
        # safety net: a crashed/interrupted run still leaves a trace
        atexit.register(write)
        _atexit_installed = True
    return _tracer


def ensure_from_config(config) -> Optional[Tracer]:
    """Install the global tracer when ``tpu_trace`` is set on a Config
    (attribute) or params dict (key); called from dataset construction
    and the training drivers — whichever runs first wins the buffer."""
    path = str(config_get(config, "tpu_trace", "") or "")
    if not path:
        return None
    # one trace file per rank (obs/identity.py): world>1 must never
    # atomic-replace a peer's buffer with its own
    path = identity.rank_suffixed(path)
    cap = int(config_get(config, "tpu_trace_buffer",
                         DEFAULT_BUFFER_EVENTS) or DEFAULT_BUFFER_EVENTS)
    return configure(path, cap)


def stop() -> None:
    """Uninstall the global tracer (tests) without writing."""
    global _tracer
    _tracer = None


def active() -> Optional[Tracer]:
    return _tracer


def enabled() -> bool:
    return _tracer is not None


@contextmanager
def span(name: str, cat: str = "phase", args: Optional[dict] = None):
    """Record a span on the global tracer; free no-op when tracing is
    off (the hot-path callers — timing.phase, the ingest worker —
    guard on ``enabled()`` first, but this is safe bare too). With no
    tracer but registered sinks (the always-on flight ring), the event
    still reaches the sinks — the black box keeps span evidence even
    when ``tpu_trace`` is off."""
    tr = _tracer
    if tr is None:
        if not _sinks:
            yield
            return
        t0 = _sink_now_us()
        try:
            yield
        finally:
            _sink_only_event(name, cat, "X", t0,
                             dur_us=_sink_now_us() - t0, args=args)
        return
    t0 = tr.now_us()
    try:
        yield
    finally:
        tr.complete(name, cat, t0, args)


def instant(name: str, cat: str = "event",
            args: Optional[dict] = None) -> None:
    tr = _tracer
    if tr is not None:
        tr.instant(name, cat, args)
    elif _sinks:
        _sink_only_event(name, cat, "i", _sink_now_us(), args=args)


_write_warned = False


def write() -> Optional[str]:
    """Flush the global tracer's buffer to its path; None when off.
    Never raises — tracing is an observability aid, not a failure
    mode (the atexit hook runs this) — but the FIRST failure logs a
    warning so an unwritable tpu_trace path is not a silent no-trace
    run (the run-report 'could not write' pattern)."""
    global _write_warned
    tr = _tracer
    if tr is None:
        return None
    try:
        return tr.write()
    except OSError as e:
        if not _write_warned:
            _write_warned = True
            try:
                from ..utils import log
                log.warning("could not write trace %s: %s", tr.path, e)
            except Exception:       # noqa: BLE001 — atexit teardown
                pass
        return None
