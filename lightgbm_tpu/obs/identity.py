"""Process-wide observability identity: (machine_rank, world, incarnation).

Every telemetry surface in obs/ — metrics snapshots, trace events,
reqlog wide events, flight bundles, run-report meta — stamps the SAME
identity record, so artifacts from N ranks of one cluster correlate
without filename archaeology:

- ``machine_rank`` / ``world``: this process's rank in the cluster
  (parallel/cluster.py pushes them here at bootstrap/adoption time —
  this module never imports the cluster layer, it is a stdlib-only
  leaf like the rest of obs/).
- ``incarnation``: bumped on every elastic re-shard this process
  lives through (utils/checkpoint.py restore's elastic path — the
  authoritative seam every re-shard funnels through, whether driven
  by the autoscale controller or an elastic resume onto a new mesh).
  Telemetry emitted before and after a re-shard carries different
  incarnations, so a merged timeline can attribute a metric to the
  world size that produced it.

Path policy: ``rank_suffixed(path)`` inserts ``.r<rank>`` before the
final extension when world > 1 (``metrics.prom`` -> ``metrics.r1.prom``)
and leaves single-process paths byte-identical — the fix for the PR-6
export collision where two same-host ranks raced one atomic-replace
target. obs/export.py, obs/trace.py, obs/reqlog.py and obs/flight.py
all route their artifact paths through it.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

__all__ = [
    "identity", "rank", "world", "incarnation", "is_multiprocess",
    "set_topology", "bump_incarnation", "rank_suffixed", "log_tag",
]

_lock = threading.Lock()
_state: Dict[str, int] = {      # guarded-by: _lock
    "machine_rank": 0,
    "world": 1,
    "incarnation": 0,
}


def identity() -> Dict[str, int]:
    """The current identity record, ready to embed in an artifact."""
    with _lock:
        return dict(_state)


def rank() -> int:
    return _state["machine_rank"]


def world() -> int:
    return _state["world"]


def incarnation() -> int:
    return _state["incarnation"]


def is_multiprocess() -> bool:
    return _state["world"] > 1


def set_topology(machine_rank: int, world_n: int) -> None:
    """Record this process's place in the cluster — called by
    parallel/cluster.py at bootstrap/adoption (the one writer besides
    the re-shard bump). Idempotent for a repeated identical call."""
    with _lock:
        _state["machine_rank"] = int(machine_rank)
        _state["world"] = max(int(world_n), 1)


def bump_incarnation(reason: str = "") -> int:
    """Advance the incarnation counter (one elastic re-shard lived
    through) and return the new value. The caller is the checkpoint
    restore's elastic re-shard branch (utils/checkpoint.py)."""
    with _lock:
        _state["incarnation"] += 1
        new = _state["incarnation"]
    # log lazily: utils/log is a leaf too, but keep import out of the
    # hot module-load path
    from ..utils import log
    log.info("obs identity: incarnation -> %d%s", new,
             f" ({reason})" if reason else "")
    return new


def rank_suffixed(path: str, rank_n: Optional[int] = None) -> str:
    """``path`` with ``.r<rank>`` inserted before the final extension
    when world > 1 (or when an explicit ``rank_n`` is given); returned
    unchanged single-process so existing single-rank artifact paths
    stay byte-identical."""
    if not path:
        return path
    r = rank_n if rank_n is not None else rank()
    if rank_n is None and not is_multiprocess():
        return path
    base, ext = os.path.splitext(path)
    return f"{base}.r{int(r)}{ext}" if ext else f"{path}.r{int(r)}"


def log_tag() -> str:
    """The rank tag the log prefix carries (``r1``) — empty
    single-process so single-rank stderr stays byte-identical."""
    return f"r{rank()}" if is_multiprocess() else ""
