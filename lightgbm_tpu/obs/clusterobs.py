"""Cluster-scope metrics: per-rank digests through the coordination
KV, merged into ``cluster/*`` rollups on rank 0.

The per-process registry (obs/registry.py) stays the accumulation
point; this module makes the CLUSTER visible from one place:

**Digest** (every rank): ``build_digest()`` compacts the registry into
a JSON wire record — every counter and gauge by value, every histogram
as its bucket-count vector plus sum/min/max (schema
``lightgbm-tpu/obs-digest`` v1). The cluster heartbeat thread
(parallel/cluster.py) publishes it under ``lgbm_tpu/obs/<rank>/<seq>``
alongside its liveness key, same cadence discipline: seq in the key,
previous seq deleted, so the directory holds one digest per rank and a
reader never blocks on an absent key.

**Rollup** (rank 0): the exporter thread (obs/export.py) calls
``maybe_refresh_from_kv()`` each interval; digests merge into a fresh
private ``MetricsRegistry`` holding first-class ``cluster/*``
instruments —

- ``cluster/<name>`` counter = sum over ranks;
- ``cluster/<name>`` histogram = elementwise bucket-count sum (ranks
  share the preset bounds, so quantiles interpolate over the TRUE
  cluster distribution, not an average of per-rank quantiles);
- per-rank gauge families ``cluster/iter_wall_mean_s/r<k>`` and
  ``cluster/psum_stall_s/r<k>`` (cardinality bounded by world size);
- straggler attribution: ``cluster/psum_stall_max_rank`` and
  ``cluster/slowest_iter_rank`` name the rank to go look at;
- ``cluster/ranks_reporting`` / ``cluster/world`` so a missing digest
  is visible as a number, not an absence.

The merged registry is published through the existing surfaces — the
exporter folds its snapshot into the ``.prom``/``.jsonl``/``/metrics``
payloads — and the SLO engine (obs/slo.py) resolves ``cluster/...``
instrument names against it, so budgets burn on cluster truth instead
of rank-0's slice.

Stdlib-only like the rest of obs/; the cluster client is always passed
in or imported lazily.
"""
from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Tuple

from ..analysis import lockorder
from . import identity
from . import registry as _registry

DIGEST_SCHEMA = "lightgbm-tpu/obs-digest"
DIGEST_VERSION = 1

# KV namespace for per-rank metric digests (next to lgbm_tpu/hb/)
OBS_PREFIX = "lgbm_tpu/obs/"
# publish every N heartbeats: digests are ~kilobytes against the
# heartbeat's bytes, so they ride a slower multiple of the same clock
DIGEST_EVERY_BEATS = 4

_DIGEST_KEY_RE = re.compile(r"obs/(\d+)/(\d+)$")

_lock = lockorder.named_lock("obs.clusterobs._lock")
_agg: Optional[_registry.MetricsRegistry] = None   # guarded-by: _lock
_last_digests: Dict[int, dict] = {}                # guarded-by: _lock
_pub_seq = 0                                       # guarded-by: _lock
_enabled = -1   # tpu_cluster_obs: -1 auto / 0 off / 1 on  guarded-by: _lock


def configure_from_config(config) -> None:
    """Latch the ``tpu_cluster_obs`` enablement (the cluster bootstrap
    calls this with the driving config — the heartbeat thread that
    publishes has no config in scope)."""
    from .trace import config_get
    global _enabled
    v = int(config_get(config, "tpu_cluster_obs", -1))
    with _lock:
        _enabled = v if v in (-1, 0, 1) else -1


def enabled() -> bool:
    """Whether digests publish at all: only ``tpu_cluster_obs=0`` says
    no. Auto and force both publish under world>1 — digests cost
    kilobytes, and the rollup half only runs where an exporter thread
    exists to consume them (obs/export.py)."""
    with _lock:
        return _enabled != 0


# -- digest build/parse ------------------------------------------------------


def build_digest(reg: Optional[_registry.MetricsRegistry] = None
                 ) -> dict:
    """This process's registry compacted to the digest wire shape."""
    reg = reg or _registry.default_registry()
    snap_hists = {}
    with reg._lock:
        counters = {n: c._value for n, c in reg._counters.items()}
        gauges = {n: g._value for n, g in reg._gauges.items()
                  if g._value is not None}
        hists = list(reg._histograms.items())
    for name, h in hists:
        with h._lock:
            if not h._count:
                continue
            snap_hists[name] = {
                "b": list(h.buckets),
                "c": list(h._counts),
                "sum": h._sum,
                "min": h._min,
                "max": h._max,
            }
    return {
        "schema": DIGEST_SCHEMA,
        "version": DIGEST_VERSION,
        "identity": identity.identity(),
        "counters": counters,
        "gauges": gauges,
        "hists": snap_hists,
    }


def digest_to_wire(digest: dict) -> str:
    return json.dumps(digest, separators=(",", ":"))


def digest_from_wire(raw: str) -> Optional[dict]:
    """Parse one digest value; None for anything malformed (a reader
    must never die on a truncated KV write)."""
    try:
        d = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(d, dict) or d.get("schema") != DIGEST_SCHEMA \
            or d.get("version") != DIGEST_VERSION:
        return None
    return d


# -- KV publish / read -------------------------------------------------------


def publish_digest(client, rank_n: int) -> bool:
    """Push this rank's current digest under ``lgbm_tpu/obs/<rank>/
    <seq>``, deleting the previous seq — the heartbeat key discipline.
    False when the client refused (coordinator gone)."""
    global _pub_seq
    with _lock:
        seq = _pub_seq
        _pub_seq += 1
    wire = digest_to_wire(build_digest())
    try:
        client.key_value_set(f"{OBS_PREFIX}{rank_n}/{seq}", wire)
        if seq:
            client.key_value_delete(f"{OBS_PREFIX}{rank_n}/{seq - 1}")
    except Exception:
        return False
    return True


def publish_now() -> bool:
    """Synchronous digest push over the live cluster client (the
    end-of-run flush in parallel/elastic.py — the periodic heartbeat
    ride-along may not have fired since the last iteration)."""
    if not enabled():
        return False
    from ..parallel import cluster
    client = cluster._client()
    if client is None:
        return False
    return publish_digest(client, cluster.rank())


def read_digests(client) -> Dict[int, dict]:
    """rank -> newest parseable digest from the KV directory."""
    try:
        entries = client.key_value_dir_get(OBS_PREFIX)
    except Exception:
        return {}
    newest: Dict[int, Tuple[int, str]] = {}
    for key, value in entries:
        m = _DIGEST_KEY_RE.search(key)
        if not m:
            continue
        r, seq = int(m.group(1)), int(m.group(2))
        if r not in newest or seq > newest[r][0]:
            newest[r] = (seq, value)
    out: Dict[int, dict] = {}
    for r, (_seq, value) in newest.items():
        d = digest_from_wire(value)
        if d is not None:
            out[r] = d
    return out


# -- rollup merge ------------------------------------------------------------


def merge_digests(digests: Dict[int, dict],
                  world_n: Optional[int] = None
                  ) -> _registry.MetricsRegistry:
    """Build a fresh registry of first-class ``cluster/*`` instruments
    from per-rank digests. Pure function of its inputs — the unit
    tests drive it without any KV."""
    agg = _registry.MetricsRegistry()
    world_n = int(world_n if world_n is not None
                  else (max(digests) + 1 if digests else 0))
    agg.gauge("cluster/world").set(world_n)
    agg.gauge("cluster/ranks_reporting").set(len(digests))
    # summed counters: cluster/<name> accumulates every rank's value
    for r in sorted(digests):
        for name, v in (digests[r].get("counters") or {}).items():
            # bounded-cardinality: one series per per-process counter
            # name — the per-rank dimension is summed away here
            agg.counter(f"cluster/{name}").add(v)
    # merged histograms: same preset bounds -> elementwise sum; a rank
    # whose bounds differ (version skew mid-rollout) is skipped for
    # that instrument rather than poisoning the quantiles
    bounds_by_name: Dict[str, List[float]] = {}
    for r in sorted(digests):
        for name, h in (digests[r].get("hists") or {}).items():
            b = [float(x) for x in h.get("b") or []]
            if not b:
                continue
            bounds_by_name.setdefault(name, b)
            if b != bounds_by_name[name]:
                continue
            # bounded-cardinality: one series per per-process
            # histogram name — ranks merge into it
            agg.histogram(f"cluster/{name}", tuple(b)).merge_counts(
                h.get("c") or [0] * (len(b) + 1),
                h.get("sum") or 0.0, h.get("min"), h.get("max"))
    # per-rank gauge families + straggler attribution. Two families is
    # deliberate: stall and iteration wall are the straggler evidence;
    # everything else stays summed or per-process.
    stall_by_rank: Dict[int, float] = {}
    iter_by_rank: Dict[int, float] = {}
    for r in sorted(digests):
        d = digests[r]
        stall = (d.get("counters") or {}).get("comm/psum_stall_s")
        if stall is not None:
            stall_by_rank[r] = float(stall)
            # bounded-cardinality: one series per rank, world-sized
            agg.gauge(f"cluster/psum_stall_s/r{r}").set(float(stall))
        h = (d.get("hists") or {}).get("train/iteration_s")
        if h and h.get("c"):
            cnt = sum(int(c) for c in h["c"])
            if cnt:
                mean = float(h.get("sum") or 0.0) / cnt
                iter_by_rank[r] = mean
                # bounded-cardinality: one series per rank, world-sized
                agg.gauge(f"cluster/iter_wall_mean_s/r{r}").set(mean)
    if stall_by_rank and any(stall_by_rank.values()):
        agg.gauge("cluster/psum_stall_max_rank").set(
            max(stall_by_rank, key=stall_by_rank.get))
    if iter_by_rank:
        agg.gauge("cluster/slowest_iter_rank").set(
            max(iter_by_rank, key=iter_by_rank.get))
    return agg


def missing_ranks(digests: Dict[int, dict], world_n: int) -> List[int]:
    return [r for r in range(int(world_n)) if r not in digests]


# -- rank-0 refresh + published views ---------------------------------------


def refresh_from_kv() -> bool:
    """Read every rank's newest digest and rebuild the aggregated
    registry. True when at least one digest merged. Call sites gate on
    rank 0 (``maybe_refresh_from_kv``); calling this elsewhere is
    harmless, just wasted reads."""
    from ..parallel import cluster
    client = cluster._client()
    if client is None:
        return False
    digests = read_digests(client)
    if not digests:
        return False
    agg = merge_digests(digests, world_n=cluster.world())
    global _agg
    with _lock:
        _agg = agg
        _last_digests.clear()
        _last_digests.update(digests)
    return True


def maybe_refresh_from_kv() -> bool:
    """The exporter-thread entry: refresh only on rank 0 of a live
    multi-process cluster (other ranks publish, they never merge)."""
    from ..parallel import cluster
    if not cluster.is_multiprocess() or cluster.rank() != 0:
        return False
    return refresh_from_kv()


def aggregated_registry() -> Optional[_registry.MetricsRegistry]:
    """The current ``cluster/*`` rollup registry (rank 0 after at
    least one merge), or None. The SLO engine resolves ``cluster/...``
    instrument names against this."""
    with _lock:
        return _agg


def last_digests() -> Dict[int, dict]:
    """The digest set behind the current rollup — the incident bundle
    embeds this as the cluster's final state (obs/incident.py)."""
    with _lock:
        return dict(_last_digests)


def cluster_snapshot() -> Optional[dict]:
    """Snapshot of the aggregated registry for the exporter to fold
    into its per-interval snapshot; None before the first merge."""
    with _lock:
        agg = _agg
    return agg.snapshot() if agg is not None else None


def reset() -> None:
    """Drop merge state (tests)."""
    global _agg, _pub_seq
    with _lock:
        _agg = None
        _last_digests.clear()
        _pub_seq = 0
