"""Distributed incidents: N per-rank flight bundles + the cluster's
final digest state, assembled into ONE document.

A multi-process failure leaves its evidence scattered: the victim's
flight recorder dumped a bundle just before SIGKILL (the pre-kill
fault hook, utils/faults.py), each survivor dumped its own on
PeerLostError / DeadlineGuard, and the coordination KV still holds the
last metrics digest every rank published (obs/clusterobs.py). Each
artifact names one process; the operator's question spans all of them.
This module answers it with a single **incident bundle** (schema
``lightgbm-tpu/incident`` v1, atomic write):

- ``dead_ranks`` — who died, as the survivor's liveness scan named
  them (parallel/cluster.py dead_ranks);
- ``ranks`` — every reachable rank's flight bundles, EMBEDDED (the
  per-rank files stay on disk, but the incident document is
  self-contained — one file to attach to a report);
- ``digests`` — the final per-rank metrics digest snapshot out of the
  KV, the cluster's last agreed-upon state;
- the assembling survivor's own identity, so "who wrote this" is
  never a guess.

Assembly happens where the shared filesystem is: the elastic driver
(parallel/elastic.py) points every rank's flight recorder at ONE
directory (``tpu_flight_dir``), the survivor exit path sweeps it, and
``run_drill`` re-sweeps after the processes exit so late dumps (the
victim's pre-kill bundle flushes during teardown) still land in the
final document. ``tools/trace_summary.py --merge`` renders the
embedded bundles' spans on one aligned timeline.

Standard library only, like the rest of obs/.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional

from ..utils.fileio import atomic_write
from . import identity

__all__ = [
    "INCIDENT_SCHEMA", "INCIDENT_VERSION",
    "sweep_flight_dumps", "build_incident", "write_incident",
    "load_incident",
]

INCIDENT_SCHEMA = "lightgbm-tpu/incident"
INCIDENT_VERSION = 1

_RANK_IN_NAME_RE = re.compile(r"flight_r(\d+)_")


def _rank_of(path: str, doc: dict) -> int:
    """The rank a flight bundle belongs to: the embedded identity
    stamp, else the ``flight_r<k>_`` filename segment, else 0 (a
    single-process dump pre-dating the rank tag)."""
    ident = doc.get("identity")
    if isinstance(ident, dict) and "machine_rank" in ident:
        try:
            return int(ident["machine_rank"])
        except (TypeError, ValueError):
            pass
    m = _RANK_IN_NAME_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else 0


def sweep_flight_dumps(directory: str) -> Dict[int, List[dict]]:
    """rank -> [{"path", "bundle"}, ...] for every parseable
    ``flight_*.json`` in ``directory``, oldest first per rank.
    Unparseable files are skipped (a process killed mid-write must not
    sink the sweep)."""
    by_rank: Dict[int, List[tuple]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return {}
    for name in names:
        if not (name.startswith("flight_") and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        r = _rank_of(path, doc)
        by_rank.setdefault(r, []).append(
            (doc.get("created_unix") or 0, path, doc))
    out: Dict[int, List[dict]] = {}
    for r, entries in by_rank.items():
        entries.sort(key=lambda e: e[0])
        out[r] = [{"path": p, "bundle": d} for _t, p, d in entries]
    return out


def build_incident(reason: str, directory: str,
                   dead_ranks: Optional[List[int]] = None,
                   context: Optional[dict] = None) -> dict:
    """Assemble the incident document from every reachable per-rank
    flight bundle in ``directory`` plus the last KV digest snapshot.
    Pure best-effort on every input: a partial incident beats none."""
    from . import clusterobs
    try:
        # the survivor may still have a live coordinator (it IS the
        # coordinator when rank 0 survives): pull the freshest digests
        clusterobs.refresh_from_kv()
    except Exception:                   # noqa: BLE001 — best effort
        pass
    per_rank = sweep_flight_dumps(directory)
    ident = identity.identity()
    return {
        "schema": INCIDENT_SCHEMA,
        "version": INCIDENT_VERSION,
        "created_unix": round(time.time(), 3),
        "reason": str(reason),
        "context": context or {},
        "identity": ident,              # who assembled this document
        "world": ident.get("world"),
        "dead_ranks": sorted(int(r) for r in (dead_ranks or [])),
        "ranks_with_dumps": sorted(per_rank),
        # JSON object keys are strings; the reader casts back
        "ranks": {str(r): per_rank[r] for r in sorted(per_rank)},
        "digests": clusterobs.last_digests(),
    }


def write_incident(reason: str, directory: str,
                   dead_ranks: Optional[List[int]] = None,
                   context: Optional[dict] = None,
                   out_path: str = "") -> Optional[str]:
    """Build + atomically write the incident bundle (default:
    ``incident_<reason>.json`` in the swept directory). Never raises —
    incident assembly runs on a dying process's exit path."""
    try:
        doc = build_incident(reason, directory, dead_ranks, context)
        if not out_path:
            safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(reason))[:40]
            out_path = os.path.join(directory, f"incident_{safe}.json")
        with atomic_write(out_path) as fh:
            json.dump(doc, fh)
        from ..utils import log
        log.warning("incident bundle (%s): %d rank(s)' flight dumps, "
                    "dead ranks %s -> %s", reason,
                    len(doc["ranks"]), doc["dead_ranks"] or "none",
                    out_path)
        return out_path
    except Exception:                   # noqa: BLE001 — see docstring
        return None


def resweep(path: str, directory: str) -> Optional[dict]:
    """Refresh an existing incident bundle's flight-dump sweep: a
    victim's pre-kill bundle can flush to disk AFTER the survivor
    assembled the incident (teardown races the sweep), so the drill
    driver (parallel/elastic.py run_drill) re-sweeps once every
    process has exited. Digests and provenance are kept from the
    original — the parent has no KV to re-read. Returns the updated
    document (also rewritten in place), or None when ``path`` is not a
    readable incident bundle."""
    try:
        doc = load_incident(path)
    except (OSError, ValueError):
        return None
    per_rank = sweep_flight_dumps(directory)
    doc["ranks_with_dumps"] = sorted(per_rank)
    doc["ranks"] = {str(r): per_rank[r] for r in sorted(per_rank)}
    try:
        with atomic_write(path) as fh:
            json.dump(doc, fh)
    except OSError:
        pass
    return doc


def load_incident(path: str) -> dict:
    """Parse + validate an incident bundle; ValueError on any other
    schema/version (the repo's versioned-artifact discipline)."""
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != INCIDENT_SCHEMA:
        raise ValueError(f"{path}: not an incident bundle "
                         f"(schema={doc.get('schema')!r})")
    if doc.get("version") != INCIDENT_VERSION:
        raise ValueError(f"{path}: incident version "
                         f"{doc.get('version')!r}, reader wants "
                         f"{INCIDENT_VERSION}")
    return doc
