"""Observability subsystem: metrics registry, run reports, profiling.

- ``obs.registry`` — thread-safe counters/gauges/histograms/timers; the
  phase accounting in utils/timing.py stores here, the ingest pipeline
  counts transfer bytes here (io/ingest.py), and everything lands in
  the run report.
- ``obs.recorder`` — per-iteration RunRecorder + the versioned
  JSON/JSONL run-report artifact (config ``tpu_run_report``), the
  slow-iteration watchdog (``tpu_watchdog_factor``), and the
  ``[t+12.3s it=140]`` log prefix.
- ``obs.profiler`` — jax profiler integration: TraceAnnotation wrapping
  for timing phases and the ``tpu_profile_dir``/``tpu_profile_iters``
  iteration-window trace bracket.
- ``obs.trace`` — cross-thread span tracer (config ``tpu_trace``/
  ``tpu_trace_buffer``): ring-buffered Chrome trace-event JSON showing
  the ingest worker, the training iterations, step-cache compiles and
  the lrb window phases on one Perfetto timeline.
- ``obs.export`` — live metrics exporter (``tpu_metrics_export``/
  ``tpu_metrics_interval_s``/``tpu_metrics_port``): a daemon that
  snapshots the default registry to Prometheus text + JSONL on an
  interval and serves ``/metrics`` + the operational ``/healthz`` and
  ``/slo`` endpoints over HTTP during a run.
- ``obs.reqlog`` — request-scoped wide events (``tpu_reqlog``/
  ``tpu_reqlog_sample``): monotonically-issued request ids carried
  through the predict stack in a thread-local context, one structured
  JSONL record per request batch and per lrb window, deterministic
  per-id file sampling, and an always-on ring the flight recorder
  dumps.
- ``obs.slo`` — SLO / error-budget engine (``tpu_slo``): declarative
  objective specs evaluated by the exporter thread every interval;
  compliance, remaining error budget and burn rate become first-class
  gauges and the ``/healthz``/``/slo`` bodies.
- ``obs.flight`` — flight recorder (``tpu_flight_buffer``): always-on
  bounded rings of recent spans, log lines, reqlog records and metric
  snapshots, dumped as ONE self-contained postmortem bundle on
  watchdog firings, faults, degraded lrb windows, SLO budget
  exhaustion, SIGTERM and uncaught exceptions; run reports cross-link
  the dumps as ``meta.flight_dumps``.

Only the stdlib-dependency modules (registry, trace, export, reqlog,
slo, flight) are imported eagerly (utils/timing.py depends on registry
and trace at module load); recorder/profiler import jax-adjacent
modules and load on first use.
"""
from . import export, flight, registry, reqlog, slo, trace
from .registry import (MetricsRegistry, counter, default_registry, gauge,
                       histogram, latency_histogram, timer)

__all__ = [
    "registry", "trace", "export", "reqlog", "slo", "flight",
    "MetricsRegistry", "default_registry", "counter", "gauge",
    "histogram", "latency_histogram", "timer",
]
