"""Observability subsystem: metrics registry, run reports, profiling.

- ``obs.registry`` — thread-safe counters/gauges/histograms/timers; the
  phase accounting in utils/timing.py stores here, the ingest pipeline
  counts transfer bytes here (io/ingest.py), and everything lands in
  the run report.
- ``obs.recorder`` — per-iteration RunRecorder + the versioned
  JSON/JSONL run-report artifact (config ``tpu_run_report``), the
  slow-iteration watchdog (``tpu_watchdog_factor``), and the
  ``[t+12.3s it=140]`` log prefix.
- ``obs.profiler`` — jax profiler integration: TraceAnnotation wrapping
  for timing phases and the ``tpu_profile_dir``/``tpu_profile_iters``
  iteration-window trace bracket.

Only the registry is imported eagerly (utils/timing.py depends on it at
module load); recorder/profiler import jax-adjacent modules and load on
first use.
"""
from . import registry
from .registry import (MetricsRegistry, counter, default_registry, gauge,
                       histogram, timer)

__all__ = [
    "registry", "MetricsRegistry", "default_registry",
    "counter", "gauge", "histogram", "timer",
]
