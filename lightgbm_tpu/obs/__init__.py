"""Observability subsystem: metrics registry, run reports, profiling.

- ``obs.registry`` — thread-safe counters/gauges/histograms/timers; the
  phase accounting in utils/timing.py stores here, the ingest pipeline
  counts transfer bytes here (io/ingest.py), and everything lands in
  the run report.
- ``obs.recorder`` — per-iteration RunRecorder + the versioned
  JSON/JSONL run-report artifact (config ``tpu_run_report``), the
  slow-iteration watchdog (``tpu_watchdog_factor``), and the
  ``[t+12.3s it=140]`` log prefix.
- ``obs.profiler`` — jax profiler integration: TraceAnnotation wrapping
  for timing phases and the ``tpu_profile_dir``/``tpu_profile_iters``
  iteration-window trace bracket.
- ``obs.trace`` — cross-thread span tracer (config ``tpu_trace``/
  ``tpu_trace_buffer``): ring-buffered Chrome trace-event JSON showing
  the ingest worker, the training iterations, step-cache compiles and
  the lrb window phases on one Perfetto timeline.
- ``obs.export`` — live metrics exporter (``tpu_metrics_export``/
  ``tpu_metrics_interval_s``/``tpu_metrics_port``): a daemon that
  snapshots the default registry to Prometheus text + JSONL on an
  interval and optionally serves ``/metrics`` over HTTP during a run.

Only the stdlib-dependency modules (registry, trace, export) are
imported eagerly (utils/timing.py depends on registry and trace at
module load); recorder/profiler import jax-adjacent modules and load on
first use.
"""
from . import export, registry, trace
from .registry import (MetricsRegistry, counter, default_registry, gauge,
                       histogram, latency_histogram, timer)

__all__ = [
    "registry", "trace", "export", "MetricsRegistry",
    "default_registry", "counter", "gauge", "histogram",
    "latency_histogram", "timer",
]
