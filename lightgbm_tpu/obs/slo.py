"""SLO / error-budget engine: declarative objectives evaluated live
against the metrics registry.

The exporter (obs/export.py) can show an operator every number the
engine records; what it could not say until now is whether the service
is *okay*. This module closes that gap with the SRE vocabulary: an
**SLO spec** names an indicator and a threshold, the engine evaluates
the specs continuously (the exporter thread calls ``evaluate`` every
snapshot interval), and the results are first-class gauges — current
value, compliance, **remaining error budget** and **burn rate** — so
they ride the existing Prometheus text, the JSONL time series, and the
two operational endpoints ``GET /healthz`` / ``GET /slo``.

Spec grammar (``tpu_slo``; ``;``-separated, ops ``<``/``<=``/``>``/
``>=``)::

    predict_p99_ms < 50            # 99% of predict batches under 50 ms
    serve_p999_ms < 20             # lrb serving tail at p99.9
    window_wall_p95_s < 30         # lrb window walls
    staleness_windows <= 2         # gauge lrb/model_staleness_windows
    degraded_window_rate < 0.05    # degraded / total windows
    hist:predict/latency_s:p99 < 0.05      # any histogram, seconds
    gauge:device/hbm_bytes_in_use < 2e9    # any gauge
    ratio:lrb/windows_failed|lrb/windows_total < 0.01  # any counters

Budget math (each spec carries an implied *objective* — the compliant
event fraction):

- **quantile specs** (``*_pNN_*``, ``hist:``): every histogram
  observation is an event; a bad event exceeds the threshold (bucket
  counts via ``Histogram.count_le`` — no per-sample storage). The
  objective is the quantile itself (``p99`` -> 0.99), so the error
  budget is the ``1 - q`` fraction of events: ``budget_remaining = 1 -
  bad / ((1 - q) * total)`` and the burn rate over the last evaluation
  interval is ``(bad_delta / total_delta) / (1 - q)`` — burn 1.0 means
  "exactly spending the budget", >1 means an alert-worthy burn.
- **ratio specs**: numerator counts bad events, denominator total; the
  threshold IS the budget fraction (``degraded_window_rate < 0.05``
  budgets 5% of windows): ``budget_remaining = 1 - num / (thr * den)``,
  ``burn = (num_delta / den_delta) / thr``.
- **gauge specs**: each evaluation tick is an event; a bad tick fails
  the comparison. Ticks are budgeted at the default objective
  ``GAUGE_OBJECTIVE`` (99% of ticks must comply).

Budget exhaustion (remaining <= 0) latches once per spec and triggers
the flight recorder (obs/flight.py) — the postmortem bundle lands at
the moment the budget ran out, not when a human notices the graph.

Standard library only; evaluation never raises (the exporter thread
must survive any spec/registry state).
"""
from __future__ import annotations

import re
import threading
import time
from typing import List, Optional

from ..analysis import lockorder
from .registry import MetricsRegistry, default_registry
from .trace import config_get

__all__ = [
    "SloSpec", "SloEngine", "parse_specs", "configure",
    "ensure_from_config", "global_engine", "shutdown",
    "GAUGE_OBJECTIVE",
]

# gauge specs budget evaluation ticks, not request events: allow 1% of
# ticks out of compliance before the budget burns dry
GAUGE_OBJECTIVE = 0.99

_OPS = {
    "<=": lambda a, b: a <= b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    ">": lambda a, b: a > b,
}

# named indicators -> (histogram name, value scale seconds->unit)
_NAMED_HISTS = {
    "predict": ("predict/latency_s", "ms"),
    "serve": ("lrb/serve_latency_s", "ms"),
    "window_wall": ("lrb/window_wall_s", "s"),
}
_NAMED_GAUGES = {
    "staleness_windows": "lrb/model_staleness_windows",
}
_NAMED_RATIOS = {
    "degraded_window_rate": ("lrb/windows_degraded", "lrb/windows_total"),
}

_QUANT_RE = re.compile(
    r"^(?P<base>[a-z_]+)_p(?P<q>\d{2,4})_(?P<unit>ms|s)$")
_OP_RE = re.compile(r"(<=|>=|<|>)")


def _q_from_digits(digits: str) -> float:
    """'50' -> 0.50, '95' -> 0.95, '99' -> 0.99, '999' -> 0.999.
    Tokens longer than two digits with a trailing zero ('100', '500')
    are ambiguous aliases of shorter tokens — 'p100' would silently
    mean p10 — so they map out of range and the callers' 0 < q < 1
    check rejects the spec with a 'not a quantile' error."""
    if len(digits) > 2 and digits.endswith("0"):
        return -1.0
    return int(digits) / float(10 ** len(digits))


class SloSpec:
    """One parsed objective: an indicator read, a comparison, and the
    budget parameters the engine's math runs on."""

    __slots__ = ("text", "name", "kind", "source", "source_den", "op",
                 "op_fn", "threshold", "threshold_s", "objective",
                 "unit", "quantile")

    def __init__(self, text: str, name: str, kind: str, source: str,
                 op: str, threshold: float, objective: float,
                 unit: str = "", quantile: Optional[float] = None,
                 source_den: str = "", threshold_s: Optional[float] = None):
        self.text = text
        self.name = name            # gauge-safe label, e.g. predict_p99_ms
        self.kind = kind            # "quantile" | "gauge" | "ratio"
        self.source = source        # registry instrument name
        self.source_den = source_den
        self.op = op
        self.op_fn = _OPS[op]
        self.threshold = float(threshold)   # in the spec's display unit
        self.threshold_s = (self.threshold if threshold_s is None
                            else float(threshold_s))  # seconds (hists)
        self.objective = float(objective)   # compliant event fraction
        self.unit = unit
        self.quantile = quantile


def _parse_one(part: str) -> SloSpec:
    m = _OP_RE.search(part)
    if not m:
        raise ValueError(f"SLO spec {part!r}: no comparison operator "
                         f"(want one of {'/'.join(_OPS)})")
    indicator = part[: m.start()].strip()
    op = m.group(1)
    try:
        threshold = float(part[m.end():].strip())
    except ValueError:
        raise ValueError(f"SLO spec {part!r}: threshold "
                         f"{part[m.end():].strip()!r} is not a number")
    label = re.sub(r"[^A-Za-z0-9_]", "_", indicator)

    # named quantile indicators: predict_p99_ms, serve_p999_ms, ...
    qm = _QUANT_RE.match(indicator)
    if qm and qm.group("base") in _NAMED_HISTS:
        hist, unit = _NAMED_HISTS[qm.group("base")]
        if qm.group("unit") != unit:
            raise ValueError(
                f"SLO spec {part!r}: {qm.group('base')} quantiles are "
                f"expressed in {unit}, not {qm.group('unit')}")
        q = _q_from_digits(qm.group("q"))
        if not 0.0 < q < 1.0:
            raise ValueError(f"SLO spec {part!r}: p{qm.group('q')} is "
                             f"not a quantile")
        scale = 1e-3 if unit == "ms" else 1.0
        return SloSpec(part, label, "quantile", hist, op, threshold,
                       objective=q, unit=unit, quantile=q,
                       threshold_s=threshold * scale)
    if indicator in _NAMED_GAUGES:
        return SloSpec(part, label, "gauge", _NAMED_GAUGES[indicator],
                       op, threshold, objective=GAUGE_OBJECTIVE)
    if indicator in _NAMED_RATIOS:
        num, den = _NAMED_RATIOS[indicator]
        if op not in ("<", "<="):
            raise ValueError(f"SLO spec {part!r}: rate objectives are "
                             f"upper bounds (< or <=)")
        if not 0.0 < threshold <= 1.0:
            raise ValueError(f"SLO spec {part!r}: rate threshold "
                             f"{threshold} outside (0, 1]")
        return SloSpec(part, label, "ratio", num, op, threshold,
                       objective=1.0 - threshold, source_den=den)
    # generic escape hatches
    if indicator.startswith("hist:"):
        rest = indicator[len("hist:"):]
        src, sep, qtok = rest.rpartition(":")
        if not sep or not qtok.startswith("p"):
            raise ValueError(f"SLO spec {part!r}: want "
                             f"hist:<name>:p<NN> {op} <seconds>")
        q = _q_from_digits(qtok[1:])
        if not 0.0 < q < 1.0:
            raise ValueError(f"SLO spec {part!r}: {qtok} is not a "
                             f"quantile")
        return SloSpec(part, re.sub(r"[^A-Za-z0-9_]", "_", rest),
                       "quantile", src, op, threshold, objective=q,
                       unit="s", quantile=q)
    if indicator.startswith("gauge:"):
        src = indicator[len("gauge:"):]
        return SloSpec(part, re.sub(r"[^A-Za-z0-9_]", "_", src),
                       "gauge", src, op, threshold,
                       objective=GAUGE_OBJECTIVE)
    if indicator.startswith("ratio:"):
        rest = indicator[len("ratio:"):]
        num, sep, den = rest.partition("|")
        if not sep:
            raise ValueError(f"SLO spec {part!r}: want "
                             f"ratio:<num>|<den> {op} <fraction>")
        if not 0.0 < threshold <= 1.0:
            raise ValueError(f"SLO spec {part!r}: rate threshold "
                             f"{threshold} outside (0, 1]")
        return SloSpec(part, re.sub(r"[^A-Za-z0-9_]", "_", rest),
                       "ratio", num, op, threshold,
                       objective=1.0 - threshold, source_den=den)
    raise ValueError(
        f"SLO spec {part!r}: unknown indicator {indicator!r} (named: "
        f"{', '.join(sorted(list(_NAMED_GAUGES) + list(_NAMED_RATIOS)))}"
        f", <base>_pNN_<unit> for {'/'.join(sorted(_NAMED_HISTS))}, or "
        f"hist:/gauge:/ratio: forms)")


def parse_specs(text: str) -> List[SloSpec]:
    """Parse a ``tpu_slo`` spec string into SloSpec objects; raises
    ValueError with the offending fragment on any malformed spec."""
    specs = []
    for part in str(text or "").split(";"):
        part = part.strip()
        if part:
            specs.append(_parse_one(part))
    return specs


class SloEngine:
    """Evaluates parsed specs against a registry; maintains per-spec
    budget/burn state and publishes it as gauges."""

    def __init__(self, specs: List[SloSpec],
                 registry: Optional[MetricsRegistry] = None,
                 min_events: int = 0):
        self.specs = list(specs)
        self._reg = registry or default_registry()
        # event floor for budget judgment: below this population a
        # tail objective is statistically meaningless (ONE outlier
        # "exhausts" a p99 budget over 10 events) — specs stay
        # vacuously compliant, budget untouched, until the floor is
        # met. 0 keeps the historical judge-from-event-1 behavior;
        # the fleet admission controller (serve/daemon.py) sets ~100
        # so a cold-start outlier cannot latch exhaustion.
        self._min_events = max(int(min_events), 0)
        self._lock = lockorder.named_lock("obs.slo._lock")
        # per-spec accounting: cumulative (total, bad) at the last
        # evaluation (burn deltas), tick counts for gauge specs, and
        # the exhaustion latch (one flight trigger per spec)
        self._last = [(0, 0)] * len(self.specs)
        self._ticks = [0] * len(self.specs)
        self._bad_ticks = [0] * len(self.specs)
        self._exhausted = [False] * len(self.specs)
        self._evaluations = 0
        self._last_report: Optional[dict] = None

    @classmethod
    def from_spec(cls, text: str,
                  registry: Optional[MetricsRegistry] = None
                  ) -> "SloEngine":
        return cls(parse_specs(text), registry=registry)

    # -- per-spec reads ------------------------------------------------------

    def _registry_for(self, name: str) -> MetricsRegistry:
        """``cluster/...`` instruments resolve against the rank-0
        rollup registry (obs/clusterobs.py) when one exists — budgets
        on cluster objectives burn on cluster truth, not rank-0's
        slice. Everything else (and any rank before the first merge)
        reads this engine's own registry."""
        if name.startswith("cluster/"):
            from . import clusterobs
            agg = clusterobs.aggregated_registry()
            if agg is not None:
                return agg
        return self._reg

    # bounded-cardinality: every dynamic metric name in this method
    # is a source from the parsed tpu_slo spec list (validated at
    # config time) — one series per configured objective
    def _events(self, spec: SloSpec):
        """-> (current, total_events, bad_events) for one spec; current
        is in the spec's display unit."""
        if spec.kind == "quantile":
            h = self._registry_for(spec.source).histogram(spec.source)
            # ONE consistent read: total and the <=-threshold count
            # must come from the same instant or concurrent observes
            # make bad negative (and corrupt the next burn delta)
            total, good = h.count_and_le(spec.threshold_s)
            if not total:
                return None, 0, 0
            cur = h.percentile(spec.quantile)
            if cur is not None and spec.unit == "ms":
                cur *= 1e3
            bad = (total - good if spec.op in ("<", "<=") else good)
            return cur, total, bad
        if spec.kind == "ratio":
            # read NUM before DEN: producers count the denominator
            # first (lrb._apply_train_outcome), so with this order a
            # concurrent window can only make the ratio smaller —
            # never show a bad event without its denominator (which
            # would overshoot the rate and falsely latch exhaustion)
            src_reg = self._registry_for(spec.source)
            num = src_reg.counter(spec.source).value
            den = src_reg.counter(spec.source_den).value
            cur = (num / den) if den else None
            return cur, den, num
        # gauge: ticks are counted by evaluate()
        cur = self._registry_for(spec.source).gauge(spec.source).value
        return cur, None, None

    # -- evaluation ----------------------------------------------------------

    def evaluate(self) -> dict:
        """One evaluation pass: per-spec compliance, budget and burn,
        published as ``slo/*`` gauges; returns (and stores) the full
        report. Never raises — the exporter thread calls this every
        interval."""
        try:
            return self._evaluate()
        except Exception as e:          # noqa: BLE001 — the exporter
            # thread must survive any registry/spec state
            from ..utils import log
            log.warning("SLO evaluation failed (%s); keeping last "
                        "report", e)
            return self._last_report or {"specs": [], "ok": None}

    # bounded-cardinality: the slo/<name>/* gauge family is one
    # series-set per configured objective (tpu_slo is a validated,
    # finite spec list)
    def _evaluate(self) -> dict:
        with self._lock:
            self._evaluations += 1
            rows = []
            exhausted_now = []
            for i, spec in enumerate(self.specs):
                cur, total, bad = self._events(spec)
                if spec.kind == "gauge":
                    # a never-written gauge is vacuously compliant
                    # (no data is not a violation — the first-scrape
                    # rule of /healthz applies here too)
                    ok = (cur is None
                          or bool(spec.op_fn(cur, spec.threshold)))
                    self._ticks[i] += 1
                    if not ok:
                        self._bad_ticks[i] += 1
                    total, bad = self._ticks[i], self._bad_ticks[i]
                else:
                    ok = (cur is None
                          or bool(spec.op_fn(cur, spec.threshold)))
                warming = (spec.kind != "gauge" and self._min_events > 0
                           and (total or 0) < self._min_events)
                if warming:
                    ok = True   # too few events to judge a tail
                budget_events = (1.0 - spec.objective) * (total or 0)
                if total and not warming:
                    remaining = (1.0 - bad / budget_events
                                 if budget_events > 0
                                 else (1.0 if not bad else 0.0))
                else:
                    remaining = 1.0
                lt, lb = self._last[i]
                dt, db = total - lt, bad - lb
                self._last[i] = (total, bad)
                allowed = 1.0 - spec.objective
                burn = ((db / dt) / allowed
                        if dt > 0 and allowed > 0 else 0.0)
                row = {
                    "spec": spec.text, "name": spec.name,
                    "kind": spec.kind, "ok": ok,
                    "current": (None if cur is None
                                else round(float(cur), 6)),
                    "threshold": spec.threshold,
                    "objective": spec.objective,
                    "events": total, "bad_events": bad,
                    "budget_remaining": round(remaining, 6),
                    "burn_rate": round(burn, 6),
                    "exhausted": bool(self._exhausted[i]
                                      or remaining <= 0.0),
                }
                if self._min_events:
                    row["warming"] = warming
                if remaining <= 0.0 and not self._exhausted[i]:
                    self._exhausted[i] = True
                    exhausted_now.append(row)
                rows.append(row)
            report = {
                "ts": round(time.time(), 3),
                "evaluations": self._evaluations,
                "specs": rows,
                "ok": all(r["ok"] for r in rows) if rows else True,
                "violating": sum(1 for r in rows if not r["ok"]),
                "budget_remaining_min": (
                    min(r["budget_remaining"] for r in rows)
                    if rows else None),
                "burn_rate_max": (max(r["burn_rate"] for r in rows)
                                  if rows else None),
                "exhausted": [r["name"] for r in rows if r["exhausted"]],
            }
            self._last_report = report
        # gauges OUTSIDE the engine lock (registry has its own): the
        # budget state rides every Prometheus scrape / JSONL snapshot
        for r in rows:
            base = f"slo/{r['name']}"
            self._reg.gauge(base + "/ok").set(1.0 if r["ok"] else 0.0)
            if r["current"] is not None:
                self._reg.gauge(base + "/current").set(r["current"])
            self._reg.gauge(base + "/budget_remaining").set(
                r["budget_remaining"])
            self._reg.gauge(base + "/burn_rate").set(r["burn_rate"])
        if rows:
            self._reg.gauge("slo/violating").set(
                float(report["violating"]))
            self._reg.gauge("slo/budget_remaining_min").set(
                report["budget_remaining_min"])
        self._reg.counter("slo/evaluations").add(1)
        # budget exhaustion is a postmortem moment: dump the black box
        # NOW (latched per spec so a burned budget does not re-dump
        # every interval)
        for row in exhausted_now:
            from ..utils import log
            log.warning("SLO budget EXHAUSTED: %s (current=%s, "
                        "threshold=%s, bad %d of %d events)",
                        row["spec"], row["current"], row["threshold"],
                        row["bad_events"], row["events"])
            from . import flight
            flight.trigger("slo_budget_exhausted",
                           {"slo": row["name"], "spec": row["spec"],
                            "current": row["current"],
                            "bad_events": row["bad_events"],
                            "events": row["events"]}, force=True)
        return report

    def report(self, fresh: bool = True) -> dict:
        """The budget report (the ``GET /slo`` body). ``fresh=False``
        returns the last evaluation without re-evaluating (the flight
        recorder's non-reentrant read)."""
        if fresh or self._last_report is None:
            return self.evaluate()
        return self._last_report

    def summary(self) -> dict:
        """The compact budget state for ``GET /healthz``."""
        rep = self._last_report or self.evaluate()
        return {
            "specs": len(rep.get("specs", [])),
            "ok": rep.get("ok"),
            "violating": rep.get("violating", 0),
            "budget_remaining_min": rep.get("budget_remaining_min"),
            "exhausted": rep.get("exhausted", []),
        }


# -- module-global engine ----------------------------------------------------

_global: Optional[SloEngine] = None
_global_lock = threading.Lock()


def configure(text: str,
              registry: Optional[MetricsRegistry] = None
              ) -> Optional[SloEngine]:
    """Install (or replace) the process-global engine from a spec
    string; empty disarms."""
    global _global
    with _global_lock:
        _global = SloEngine.from_spec(text, registry) if text else None
        return _global


def ensure_from_config(config) -> Optional[SloEngine]:
    """Install the global engine when ``tpu_slo`` is set; idempotent
    for the same spec text (every windowed booster re-inits)."""
    global _global
    text = str(config_get(config, "tpu_slo", "") or "")
    if not text:
        return _global
    with _global_lock:
        if (_global is not None
                and [s.text for s in _global.specs]
                == [s.strip() for s in text.split(";") if s.strip()]):
            return _global
        _global = SloEngine.from_spec(text)
        from ..utils import log
        log.info("SLO engine armed: %s",
                 "; ".join(s.text for s in _global.specs))
        return _global


def global_engine() -> Optional[SloEngine]:
    return _global


def shutdown() -> None:
    """Drop the global engine (tests)."""
    global _global
    with _global_lock:
        _global = None
