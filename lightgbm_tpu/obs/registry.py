"""Thread-safe metrics registry: counters, gauges, histograms, timers.

The single accumulation point for the engine's telemetry. The timing
module (utils/timing.py) feeds its per-phase wall clocks into the timer
domain instead of private dicts, the ingest pipeline (io/ingest.py)
counts host->device transfer bytes from its worker thread, and the
RunRecorder (obs/recorder.py) snapshots everything into the run report.

Design constraints:

- **Thread-safe.** The ingest prefetch worker records transfer counters
  and phase times from off-thread while the main thread accumulates
  training phases; every instrument mutation and every get-or-create
  takes the owning registry's lock. The lock is per-registry, not
  per-instrument: contention is negligible at telemetry rates and one
  lock keeps snapshot() atomic across domains.
- **Dependency-free.** This module imports only the standard library —
  utils/timing.py imports it at module load, so it must not import jax,
  numpy, or anything else from this package.
- **Plain monotonic time.** Durations are recorded by callers from
  ``time.monotonic()`` deltas; the registry itself never reads clocks.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..analysis import lockorder

__all__ = [
    "Counter", "Gauge", "Histogram", "Timer", "MetricsRegistry",
    "default_registry", "counter", "gauge", "histogram", "timer",
    "log_buckets", "latency_histogram", "LATENCY_BUCKETS_S",
    "quantile_label",
]


class Counter:
    """Monotonically increasing count (events, bytes, rows)."""
    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    inc = add

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value (HBM in use, queue depth)."""
    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


# default histogram buckets: exponential, sized for seconds-grade
# durations (1 ms .. 60 s) but serviceable for any positive magnitude
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0)


def log_buckets(lo: float, hi: float,
                per_decade: int = 12) -> Tuple[float, ...]:
    """Geometric bucket bounds from ``lo`` to (at least) ``hi`` with
    ``per_decade`` buckets per factor of 10. At 12/decade adjacent
    bounds differ by ~21%, so an interpolated quantile (see
    ``Histogram.percentile``) lands within a fifth of the true value
    across seven decades with under a hundred buckets — the
    latency-quantile resolution/size trade."""
    import math
    lo = float(lo)
    per_decade = max(int(per_decade), 1)
    n = int(math.ceil(math.log10(float(hi) / lo) * per_decade))
    return tuple(lo * 10.0 ** (k / per_decade) for k in range(n + 1))


# latency preset: 1 µs .. 60 s — wide enough for a single predict
# dispatch at the bottom and a cold-compile window wall at the top
LATENCY_BUCKETS_S: Tuple[float, ...] = log_buckets(1e-6, 60.0, 12)


def quantile_label(q: float) -> str:
    """0.5 -> "p50", 0.95 -> "p95", 0.999 -> "p999" — the one naming
    rule for quantile keys in snapshots/result tables."""
    return "p" + f"{q * 100:g}".replace(".", "")


class Histogram:
    """Fixed-bucket histogram with percentile readout.

    Buckets are upper bounds (cumulative style); one implicit overflow
    bucket catches everything above the last bound. ``percentile``
    returns the upper bound of the bucket containing the requested
    rank (the observed max for the overflow bucket) — coarse by
    construction, stable under concurrency, no per-sample storage.
    """
    __slots__ = ("_lock", "buckets", "_counts", "_count", "_sum",
                 "_min", "_max")

    def __init__(self, lock: threading.RLock,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self._lock = lock
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        self.observe_n(v, 1)

    def observe_n(self, v: float, n: int) -> None:
        """Record ``n`` observations of the same value in one bucket
        walk — the per-request normalization of a batched call: every
        request in a ``n``-row micro-batch experienced the batch's
        wall, so the batch contributes ``n`` request latencies, not
        one (lrb.py serve path). Quantiles then rank REQUESTS."""
        v = float(v)
        n = int(n)
        if n <= 0:
            return
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):       # noqa: B007
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += n
            self._count += n
            self._sum += v * n
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[int]:
        """The raw per-bucket counts (overflow bucket last) — the
        mergeable wire form of this histogram: two histograms over the
        SAME bounds merge by elementwise sum (obs/clusterobs.py builds
        cluster-wide quantiles this way)."""
        with self._lock:
            return list(self._counts)

    def merge_counts(self, counts, sum_: float,
                     min_: Optional[float],
                     max_: Optional[float]) -> None:
        """Fold another histogram's bucket counts into this one —
        ``counts`` must cover this instrument's bounds plus the
        overflow bucket. min/max fold exactly, so percentile()'s
        range clamping stays correct on the merged instrument."""
        counts = [int(c) for c in counts]
        if len(counts) != len(self._counts):
            raise ValueError(
                f"cannot merge {len(counts)} bucket counts into a "
                f"{len(self._counts)}-bucket histogram — bounds differ")
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += sum(counts)
            self._sum += float(sum_)
            if min_ is not None:
                self._min = (float(min_) if self._min is None
                             else min(self._min, float(min_)))
            if max_ is not None:
                self._max = (float(max_) if self._max is None
                             else max(self._max, float(max_)))

    def percentile(self, q: float) -> Optional[float]:
        """q-quantile (0 < q <= 1) with linear interpolation INSIDE the
        bucket holding the quantile rank: the rank's fractional position
        among the bucket's samples maps onto the bucket's [lower, upper)
        bound span — the Prometheus ``histogram_quantile`` estimator.
        Bounds are clamped to the observed min/max (the first bucket's
        lower edge is the observed min, the overflow bucket's upper edge
        the observed max), so a bucket holding one sample still reports
        a value inside the data range. None when empty."""
        with self._lock:
            if not self._count:
                return None
            rank = max(1, int(q * self._count + 0.999999))
            cum = 0
            for i, c in enumerate(self._counts):
                if not c:
                    continue
                cum += c
                if cum < rank:
                    continue
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self._max)
                # clamp to observed range (min/max are exact)
                lo = max(lo, self._min)
                hi = max(min(hi, self._max), lo)
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * frac
            return self._max

    def count_le(self, v: float) -> int:
        """Estimated number of observations <= ``v``: whole buckets
        below it plus a linear share of the bucket straddling it
        (the percentile() interpolation run in reverse, same min/max
        clamping) — the event count the SLO engine's error-budget
        math stands on (obs/slo.py). 0 when empty."""
        with self._lock:
            if not self._count:
                return 0
            v = float(v)
            if self._max is not None and v >= self._max:
                return self._count
            if self._min is not None and v < self._min:
                return 0
            cum = 0
            for i, c in enumerate(self._counts):
                if not c:
                    continue
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self._max)
                lo = max(lo, self._min)
                hi = max(min(hi, self._max), lo)
                if v >= hi:
                    cum += c
                    continue
                if v >= lo:
                    frac = 1.0 if hi <= lo else (v - lo) / (hi - lo)
                    cum += int(c * frac)
                break
            return cum

    def count_and_le(self, v: float) -> Tuple[int, int]:
        """Consistent ``(count, count_le(v))`` under ONE lock hold
        (the lock is reentrant): the SLO engine's bad-event math
        (``bad = count - count_le``) must not straddle concurrent
        observes — a racing pair of reads can make it negative."""
        with self._lock:
            return self._count, self.count_le(v)

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            out = {"count": self._count, "sum": round(self._sum, 9),
                   "min": self._min, "max": self._max,
                   "buckets": {str(b): c for b, c in
                               zip(self.buckets, counts) if c},
                   "overflow": counts[-1]}
        for q, name in ((0.5, "p50"), (0.9, "p90"), (0.95, "p95"),
                        (0.99, "p99"), (0.999, "p999")):
            out[name] = self.percentile(q)
        return out

    def quantiles(self, qs=(0.5, 0.95, 0.99, 0.999)) -> dict:
        """{"p50": v, ..., "p999": v} readout for result tables
        (bench.py predict latency, lrb.py window wall); p99.9 rides
        along by default — tail latency at fleet scale lives past p99.
        Values None when empty."""
        return {quantile_label(q): self.percentile(q) for q in qs}


class Timer:
    """Accumulated duration: total seconds, call count, max call —
    the phase-table instrument (utils/timing.py feeds these)."""
    __slots__ = ("_lock", "_total", "_count", "_max")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._total = 0.0
        self._count = 0
        self._max = 0.0

    def add(self, seconds: float) -> None:
        seconds = float(seconds)
        with self._lock:
            self._total += seconds
            self._count += 1
            if seconds > self._max:
                self._max = seconds

    @property
    def total(self) -> float:
        with self._lock:
            return self._total

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def max(self) -> float:
        with self._lock:
            return self._max


class MetricsRegistry:
    """Named instruments in four domains, one lock, atomic snapshot."""

    def __init__(self):
        self._lock = lockorder.named_rlock("obs.registry._lock")
        self._counters: "OrderedDict[str, Counter]" = OrderedDict()   # guarded-by: _lock
        self._gauges: "OrderedDict[str, Gauge]" = OrderedDict()       # guarded-by: _lock
        self._histograms: "OrderedDict[str, Histogram]" = OrderedDict()  # guarded-by: _lock
        self._timers: "OrderedDict[str, Timer]" = OrderedDict()       # guarded-by: _lock

    # -- get-or-create accessors --------------------------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(self._lock)
            return g

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(self._lock,
                                                       buckets)
            return h

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer(self._lock)
            return t

    # -- reads ---------------------------------------------------------------

    def timer_items(self) -> List[Tuple[str, float, int, float]]:
        """[(name, total_s, calls, max_s)] — one consistent read."""
        with self._lock:
            return [(n, t._total, t._count, t._max)
                    for n, t in self._timers.items()]

    def counter_items(self) -> Dict[str, int]:
        with self._lock:
            return {n: c._value for n, c in self._counters.items()}

    def snapshot(self) -> dict:
        """JSON-able state of every instrument (the run-report body)."""
        with self._lock:
            counters = {n: c._value for n, c in self._counters.items()}
            gauges = {n: g._value for n, g in self._gauges.items()
                      if g._value is not None}
            hists = list(self._histograms.items())
            phases = {n: {"total_s": round(t._total, 6),
                          "calls": t._count,
                          "max_s": round(t._max, 6)}
                      for n, t in self._timers.items()}
        return {"counters": counters, "gauges": gauges,
                "histograms": {n: h.snapshot() for n, h in hists},
                "phases": phases}

    # -- resets --------------------------------------------------------------

    def reset_timers(self) -> None:
        """Clear the phase/timer domain only (timing.reset: each phase
        report covers one run's deltas; counters keep accumulating)."""
        with self._lock:
            self._timers.clear()

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._timers.clear()


# process-global default registry: the engine's instruments all live
# here unless a caller (tests) builds a private MetricsRegistry
_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def counter(name: str) -> Counter:
    return _default.counter(name)


def gauge(name: str) -> Gauge:
    return _default.gauge(name)


def histogram(name: str,
              buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    return _default.histogram(name, buckets)


def timer(name: str) -> Timer:
    return _default.timer(name)


def latency_histogram(name: str,
                      registry: Optional[MetricsRegistry] = None
                      ) -> Histogram:
    """Get-or-create a log-bucketed latency instrument (1 µs – 60 s,
    12 buckets/decade) — the quantile-grade preset behind
    ``predict/latency_s`` (bench.py) and ``lrb/window_wall_s``
    (lrb.py); serving PRs report p50/p95/p99 from these."""
    return (registry or _default).histogram(name, LATENCY_BUCKETS_S)
