"""Flight recorder: an always-on black box that dumps a postmortem
bundle at the moment something goes wrong.

When a window degrades, the watchdog fires, a fault injects, an SLO
budget burns dry, or the process is torn down, the evidence an
operator needs is normally scattered across the trace ring (if tracing
was on), the exporter's snapshots (if exporting was on), the log tail,
and the request log — with nothing tying them to the failure instant.
This module keeps a bounded in-memory ring of ALL of them, all the
time (capacity ``tpu_flight_buffer``; 0 disables), and on a trigger
writes ONE self-contained JSON bundle (schema
``lightgbm-tpu/flight`` v1, atomic write):

- the newest span/instant events (fed by a trace sink — recorded even
  when no ``tpu_trace`` tracer is installed);
- the newest log lines (a tee sink on utils/log.py);
- the newest request-log wide events (obs/reqlog.py ring);
- the exporter's recent metric snapshots plus a fresh full registry
  snapshot at dump time;
- the SLO engine's last budget report (obs/slo.py);
- the trigger history (every trigger is recorded even when its dump
  was rate-limited).

Triggers wired through the engine: watchdog firings
(obs/recorder.py), fault injection (utils/faults.py — the dump lands
BEFORE a ``kill`` action SIGKILLs the process), degraded lrb windows
(lrb.py), SLO budget exhaustion (obs/slo.py), SIGTERM, uncaught
exceptions (sys.excepthook chain), and an atexit sweep that persists a
pending rate-limited trigger. Dumps are rate-limited
(``MIN_DUMP_INTERVAL_S`` apart, ``MAX_DUMPS`` per process; ``force``
bypasses the interval for the moments that cannot recur — SIGTERM,
kill-action faults, budget exhaustion) and cross-linked from run
reports as ``meta.flight_dumps`` (obs/recorder.py).

Dump directory: the first configured artifact path's directory
(``tpu_run_report`` / ``tpu_reqlog`` / ``tpu_metrics_export`` /
``tpu_trace``), else the system temp dir — a bare run never litters
the working directory. Standard library only.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import List, Optional

from ..analysis import lockorder
from ..utils import log
from ..utils.fileio import atomic_write
from . import identity
from . import trace as _trace
from .registry import MetricsRegistry, default_registry
from .trace import config_get

__all__ = [
    "FlightRecorder", "configure", "ensure_from_config", "get",
    "active", "trigger", "dump_paths", "shutdown",
    "FLIGHT_SCHEMA", "FLIGHT_VERSION",
]

FLIGHT_SCHEMA = "lightgbm-tpu/flight"
FLIGHT_VERSION = 1

DEFAULT_BUFFER = 256          # spans / log lines / reqlog records kept
METRIC_SNAPS_KEPT = 6         # exporter-interval snapshots kept
MIN_DUMP_INTERVAL_S = 2.0     # non-forced triggers this close coalesce
MAX_DUMPS = 16                # per-process dump cap (runaway guard)
_TRIGGERS_KEPT = 64

_REASON_RE = re.compile(r"[^A-Za-z0-9_.-]")


class FlightRecorder:
    """The bounded black box + its dump machinery. One per process
    normally (the module global); private instances for tests."""

    def __init__(self, capacity: int = DEFAULT_BUFFER,
                 directory: str = "",
                 registry: Optional[MetricsRegistry] = None,
                 min_dump_interval_s: float = MIN_DUMP_INTERVAL_S,
                 max_dumps: int = MAX_DUMPS):
        self.capacity = max(int(capacity), 16)
        self.directory = directory or tempfile.gettempdir()
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.max_dumps = int(max_dumps)
        self._reg = registry or default_registry()
        # REENTRANT: the SIGTERM handler runs trigger() on whatever
        # the main thread was doing — including mid-trigger with this
        # lock held; a plain Lock would deadlock the dying process
        self._lock = lockorder.named_rlock("obs.flight._lock")
        self._spans: deque = deque(maxlen=self.capacity)
        self._logs: deque = deque(maxlen=self.capacity)
        self._metric_snaps: deque = deque(maxlen=METRIC_SNAPS_KEPT)
        self._triggers: deque = deque(maxlen=_TRIGGERS_KEPT)
        self._dump_paths: List[str] = []
        self._last_dump_t: Optional[float] = None
        self._pending: Optional[tuple] = None   # rate-limited trigger
        self._seq = 0
        self._write_warned = False

    # -- feeds (each a lock-free deque append: hot-path safe) ----------------

    def note_span(self, ev: dict) -> None:
        """Trace sink: every recorded span/instant event lands here
        too (obs/trace.py add_sink)."""
        self._spans.append(ev)

    def note_log(self, line: str) -> None:
        """Log sink: every emitted log line (utils/log.py add_sink)."""
        self._logs.append(line.rstrip("\n"))

    def note_metrics(self, snap: dict) -> None:
        """Exporter feed: keep the counters/gauges of the last few
        interval snapshots (the recent time series, compact — the
        full registry state is snapshotted fresh at dump time)."""
        self._metric_snaps.append({
            "ts": snap.get("ts"), "uptime_s": snap.get("uptime_s"),
            "counters": snap.get("counters", {}),
            "gauges": snap.get("gauges", {})})

    # -- triggers ------------------------------------------------------------

    def trigger(self, reason: str, context: Optional[dict] = None,
                force: bool = False) -> Optional[str]:
        """Record a trigger and dump the bundle unless rate-limited.
        -> the dump path, or None when the dump was coalesced (the
        trigger itself is still recorded and swept at exit)."""
        now = time.monotonic()
        rec = {"ts": round(time.time(), 3), "reason": str(reason)}
        if context:
            rec["context"] = context
        with self._lock:
            self._triggers.append(rec)
            capped = len(self._dump_paths) >= self.max_dumps
            limited = (self._last_dump_t is not None
                       and now - self._last_dump_t
                       < self.min_dump_interval_s)
            # ``force`` marks the moments that cannot recur (SIGTERM,
            # kill-action faults, budget exhaustion): they bypass the
            # interval AND the runaway cap — a capped process must
            # still leave the bundle that explains its death
            if (capped or limited) and not force:
                self._pending = (reason, context)
                suppress = True
            else:
                self._last_dump_t = now
                suppress = False
        self._reg.counter("flight/triggers").add(1)
        if suppress:
            self._reg.counter("flight/dumps_suppressed").add(1)
            return None
        return self.dump(reason, context)

    # -- the bundle ----------------------------------------------------------

    def document(self, reason: str,
                 context: Optional[dict] = None) -> dict:
        """The self-contained postmortem document (dump() writes it)."""
        slo_report = None
        try:
            from . import slo as _slo
            eng = _slo.global_engine()
            if eng is not None:
                # the non-reentrant read: evaluate() could itself
                # trigger (budget exhaustion) and recurse into a dump
                slo_report = eng.report(fresh=False)
        except Exception:               # noqa: BLE001 — best effort
            pass
        reqlog_recent: list = []
        try:
            from . import reqlog as _reqlog
            rl = _reqlog.get(create=False)
            if rl is not None:
                reqlog_recent = rl.recent(self.capacity)
        except Exception:               # noqa: BLE001 — best effort
            pass
        with self._lock:
            spans = list(self._spans)
            logs = list(self._logs)
            snaps = list(self._metric_snaps)
            triggers = list(self._triggers)
        return {
            "schema": FLIGHT_SCHEMA,
            "version": FLIGHT_VERSION,
            "created_unix": round(time.time(), 3),
            "pid": os.getpid(),
            "identity": identity.identity(),
            "reason": str(reason),
            "context": context or {},
            "triggers": triggers,
            "spans": spans,
            "log_lines": logs,
            "reqlog": reqlog_recent,
            "metrics": {
                "current": self._reg.snapshot(),
                "recent": snaps,
            },
            "slo": slo_report,
        }

    def dump(self, reason: str,
             context: Optional[dict] = None) -> Optional[str]:
        """Write one bundle (atomic); never raises — the black box
        must not add a failure mode to the failure it records."""
        try:
            doc = self.document(reason, context)
            with self._lock:
                self._seq += 1
                seq = self._seq
                self._pending = None
            # rank segment under world>1: N ranks dumping into one
            # shared directory (the incident sweep's precondition,
            # obs/incident.py) must never collide on a name
            rtag = (f"r{identity.rank()}_"
                    if identity.is_multiprocess() else "")
            name = (f"flight_{rtag}p{os.getpid()}_{seq:03d}_"
                    f"{_REASON_RE.sub('_', str(reason))[:40]}.json")
            path = os.path.join(self.directory, name)
            with atomic_write(path) as fh:
                json.dump(doc, fh)
            with self._lock:
                self._dump_paths.append(path)
            self._reg.counter("flight/dumps").add(1)
            log.warning("flight recorder: dumped postmortem bundle "
                        "(%s) -> %s", reason, path)
            return path
        except Exception as e:          # noqa: BLE001 — see docstring
            self._reg.counter("flight/dump_failures").add(1)
            if not self._write_warned:
                self._write_warned = True
                try:
                    log.warning("flight recorder could not dump to %s "
                                "(%s)", self.directory, e)
                except Exception:       # noqa: BLE001 — teardown
                    pass
            return None

    def dump_paths(self) -> List[str]:
        with self._lock:
            return list(self._dump_paths)

    def sweep_pending(self) -> Optional[str]:
        """Persist a trigger whose dump was rate-limited (the atexit
        safety net): the last coalesced reason still reaches disk."""
        with self._lock:
            pending = self._pending
            self._pending = None
        if pending is None:
            return None
        return self.dump(pending[0], pending[1])


# ---------------------------------------------------------------------------
# process-global recorder + hook installation
# ---------------------------------------------------------------------------

_global: Optional[FlightRecorder] = None
_global_lock = threading.Lock()
_hooks_installed = False
_sigterm_installed = False
_prev_sigterm = None
_prev_excepthook = None


def _on_sigterm(signum, frame):
    fr = _global
    if fr is not None:
        fr.trigger("sigterm", force=True)
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    else:
        # restore the default disposition and re-deliver so the exit
        # status still says "terminated by SIGTERM"
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _on_uncaught(tp, val, tb):
    fr = _global
    if fr is not None:
        fr.trigger("unhandled_exception",
                   {"type": getattr(tp, "__name__", str(tp)),
                    "message": str(val)[:400]}, force=True)
    hook = _prev_excepthook or sys.__excepthook__
    hook(tp, val, tb)


def _atexit_sweep() -> None:
    fr = _global
    if fr is not None:
        try:
            fr.sweep_pending()
        except Exception:               # noqa: BLE001 — teardown
            pass


def _install_hooks(recorder: FlightRecorder) -> None:
    """Feed sinks + teardown hooks. Sinks/atexit/excepthook install
    once per process and read the CURRENT global recorder, so a test
    swapping in a fresh one (configure) re-routes them without
    re-installing. The SIGTERM handler is tracked SEPARATELY and
    retried: python only allows the install from the main thread, and
    a process whose first booster inits on a worker thread must still
    get its SIGTERM dump armed by a later main-thread init."""
    global _hooks_installed, _sigterm_installed
    global _prev_sigterm, _prev_excepthook
    _trace.add_sink(_sink_span)
    log.add_sink(_sink_log)
    if not _hooks_installed:
        _hooks_installed = True
        atexit.register(_atexit_sweep)
        _prev_excepthook = sys.excepthook
        sys.excepthook = _on_uncaught
    if _sigterm_installed:
        return
    try:
        if threading.current_thread() is threading.main_thread():
            prev = signal.getsignal(signal.SIGTERM)
            if prev != signal.SIG_IGN:
                # a process that deliberately IGNORES SIGTERM keeps
                # ignoring it — the black box must never change
                # whether the host survives a signal, only what
                # evidence a death leaves
                signal.signal(signal.SIGTERM, _on_sigterm)
                _prev_sigterm = prev if callable(prev) else None
            # latched either way: the disposition was SEEN from the
            # main thread (an SIG_IGN choice is honored, not re-polled)
            _sigterm_installed = True
    except (ValueError, OSError):       # exotic env: retry next init
        pass


def _sink_span(ev: dict) -> None:
    fr = _global
    if fr is not None:
        fr.note_span(ev)


def _sink_log(line: str) -> None:
    fr = _global
    if fr is not None:
        fr.note_log(line)


def configure(capacity: int = DEFAULT_BUFFER, directory: str = "",
              min_dump_interval_s: float = MIN_DUMP_INTERVAL_S,
              max_dumps: int = MAX_DUMPS) -> Optional[FlightRecorder]:
    """Install (or replace) the process-global recorder; capacity <= 0
    uninstalls. Tests use this for a fresh, isolated instance."""
    global _global
    with _global_lock:
        if int(capacity) <= 0:
            _global = None
            return None
        _global = FlightRecorder(capacity, directory,
                                 min_dump_interval_s=min_dump_interval_s,
                                 max_dumps=max_dumps)
        _install_hooks(_global)
        return _global


def _dump_dir_from_config(config) -> str:
    """The first configured artifact path names the dump directory —
    postmortems land next to the run's other evidence.
    ``tpu_flight_dir`` overrides: multi-process drivers point every
    rank at ONE shared directory so the incident sweep
    (obs/incident.py) can collect all ranks' bundles."""
    d = str(config_get(config, "tpu_flight_dir", "") or "")
    if d:
        return d
    for knob in ("tpu_run_report", "tpu_reqlog", "tpu_metrics_export",
                 "tpu_trace"):
        p = str(config_get(config, knob, "") or "")
        if p:
            d = os.path.dirname(p)
            return d or "."
    return ""


def ensure_from_config(config) -> Optional[FlightRecorder]:
    """Start the always-on recorder from ``tpu_flight_buffer`` (every
    driver init calls this; 0 disables). Idempotent: a running
    recorder keeps its ring, honoring only a LARGER capacity (the
    tracer's grow-only rule) and adopting a directory when it is still
    on the temp-dir default."""
    global _global
    cap = int(config_get(config, "tpu_flight_buffer", DEFAULT_BUFFER))
    if cap <= 0:
        return _global          # 0 opts THIS driver out, never tears
        # down a recorder another driver is feeding
    directory = _dump_dir_from_config(config)
    with _global_lock:
        if _global is None:
            _global = FlightRecorder(cap, directory)
            _install_hooks(_global)
            return _global
        if cap > _global.capacity:
            # grow-only resize, keeping the newest entries. Swap in
            # the fresh ring FIRST and then drain the old one via
            # popleft: the sinks append lock-free from other threads,
            # and iterating a deque they are appending to would raise
            # ("deque mutated during iteration") out of a driver init
            _global.capacity = cap
            for attr in ("_spans", "_logs"):
                old = getattr(_global, attr)
                new: deque = deque(maxlen=cap)
                setattr(_global, attr, new)
                # newest-first pop + appendleft keeps original order
                # AND places drained entries before any events the
                # sinks appended to the fresh ring mid-drain
                while True:
                    try:
                        new.appendleft(old.pop())
                    except IndexError:
                        break
        if directory and _global.directory == tempfile.gettempdir():
            _global.directory = directory
        return _global


def get() -> Optional[FlightRecorder]:
    return _global


def active() -> bool:
    return _global is not None


def trigger(reason: str, context: Optional[dict] = None,
            force: bool = False) -> Optional[str]:
    """Trigger the global recorder; no-op (None) when none installed."""
    fr = _global
    if fr is None:
        return None
    return fr.trigger(reason, context, force=force)


def dump_paths() -> List[str]:
    """Paths of every bundle dumped so far this process (run reports
    cross-link these as ``meta.flight_dumps``)."""
    fr = _global
    return fr.dump_paths() if fr is not None else []


def shutdown() -> None:
    """Drop the global recorder (tests); sinks stay installed but
    become no-ops."""
    global _global
    with _global_lock:
        _global = None
