"""jax profiler integration: the ``ProfileWindow`` iteration bracket.

Brackets training iterations with ``jax.profiler.start_trace`` /
``stop_trace`` (config ``tpu_profile_dir``). ``tpu_profile_iters = 0``
traces the whole boosting loop (the pre-existing engine.train
behavior); ``N > 0`` traces exactly N iterations starting at iteration
2, skipping the compile-dominated first iteration so the capture shows
steady-state device work. While a window is open, utils/timing.py
emits a ``jax.profiler.TraceAnnotation`` around every phase
(set_trace_annotations), so the engine's phase names appear as spans
inside the capture.

Resilient by design: a jax without the profiler, or a backend where
tracing fails, logs a warning and training proceeds untraced.
"""
from __future__ import annotations

from ..utils import log, timing


def profiler_available() -> bool:
    try:
        import jax
        return (hasattr(jax.profiler, "start_trace")
                and hasattr(jax.profiler, "stop_trace"))
    except Exception:                   # noqa: BLE001 — absence == off
        return False


class ProfileWindow:
    """start/stop_trace bracket over a configurable iteration window.

    Drivers call ``iter_begin(it)`` / ``iter_end(it)`` with 1-based
    iteration numbers and ``close()`` after the loop (idempotent; also
    the safety net for early stops while the trace is open). While a
    window is configured, timing.phase emits TraceAnnotations so the
    engine's phase names appear inside the captured trace.
    """

    def __init__(self, trace_dir: str = "", iters: int = 0):
        self.trace_dir = trace_dir or ""
        self.iters = max(int(iters or 0), 0)
        self._active = False
        self._done = False
        self._annotations_installed = False
        if self.trace_dir and not profiler_available():
            log.warning("tpu_profile_dir=%s set but jax.profiler is "
                        "unavailable; tracing disabled", self.trace_dir)
            self.trace_dir = ""

    @property
    def enabled(self) -> bool:
        return bool(self.trace_dir)

    def _start_at(self) -> int:
        # whole-run trace starts at iteration 1; a bounded window skips
        # the compile-dominated first iteration
        return 1 if self.iters == 0 else 2

    def iter_begin(self, it: int) -> None:
        if (not self.enabled or self._active or self._done
                or it < self._start_at()):
            return
        try:
            import jax
            jax.profiler.start_trace(self.trace_dir)
        except Exception as e:          # noqa: BLE001 — tracing is an
            # observability aid; a failing profiler must not stop training
            log.warning("jax.profiler.start_trace(%s) failed: %s",
                        self.trace_dir, e)
            self.trace_dir = ""
            return
        self._active = True
        timing.set_trace_annotations(True)
        self._annotations_installed = True
        log.info("profiler trace started (dir=%s, window=%s)",
                 self.trace_dir,
                 "whole run" if self.iters == 0
                 else f"{self.iters} iterations from iteration "
                      f"{self._start_at()}")

    def iter_end(self, it: int) -> None:
        if (not self._active or self.iters == 0
                or it < self._start_at() + self.iters - 1):
            return
        self._stop()

    def close(self) -> None:
        if self._active:
            self._stop()
        if self._annotations_installed:
            timing.set_trace_annotations(False)
            self._annotations_installed = False

    def _stop(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", self.trace_dir)
        except Exception as e:          # noqa: BLE001
            log.warning("jax.profiler.stop_trace failed: %s", e)
        self._active = False
        self._done = True
