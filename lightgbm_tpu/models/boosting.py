"""Boosting variants: GOSS, DART, RF.

TPU-native counterparts of the reference boosting subclasses
(reference: src/boosting/goss.hpp:26-216, src/boosting/dart.hpp:17-190,
src/boosting/rf.hpp:18-172, factory src/boosting/boosting.cpp:57-83).

Design notes vs the reference:

- GOSS runs entirely in-jit as a gradient-sample hook inside the fused
  training step (gbdt.py:_get_step_fn): the top-rate threshold is an
  exact device sort, the other-rate draw is i.i.d. Bernoulli with the
  same expected count as the reference's sequential exact-count sampler
  (goss.hpp:89-133) — a deliberate TPU-native substitution: the exact
  sampler is a sequential scan over rows, the Bernoulli draw is one
  fused elementwise pass.
- The DEFAULT Bernoulli stream (``tpu_goss_hash != 0``) is the
  shard-invariant lowbias32 hash of (global row index, per-tree salt)
  — the PR-4 bagging scheme: each row's draw depends only on its
  global index, never on the padded width or the mesh layout, and the
  real row count rides the traced ``rvalid`` mask. That makes hashed
  GOSS step-cache ELIGIBLE (ops/step_cache.py): sliding-window GOSS
  retrains hit the process-wide registry at 0 compile. The legacy
  positional-PRNG sampler (``tpu_goss_hash=0``) is kept verbatim as
  the parity/repro oracle and stays per-booster-jitted.
- DART keeps the reference's host-driven drop bookkeeping (tree weights,
  skip/max/uniform drop, normalization algebra dart.hpp:86-190) but all
  score adjustments replay device TreeRecords — no host transfer.
- RF replaces the base class's fused step with an averaging step
  (scores = running mean of tree outputs, rf.hpp:112-151) and fixed
  bagged targets (g = -label / one-hot, h = 1, rf.hpp:81-107).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.predict import add_leaf_outputs, replay_partition
from ..utils import log
from .gbdt import GBDT


def create_boosting(boosting_type: str) -> GBDT:
    """Boosting::CreateBoosting (boosting.cpp:57-83)."""
    return {"gbdt": GBDT, "goss": GOSS, "dart": DART, "rf": RF}[
        boosting_type]()


# stream-separation salt for the hashed GOSS draw: the step's PRNG
# seed also salts the grower's stochastic-rounding streams (salt and
# salt ^ 0x9E3779B9, ops/wave_grower.py), so GOSS xors a third
# constant to keep its uniform draws independent of the rounding
_GOSS_SALT = 0x27D4EB2F


class GOSS(GBDT):
    """Gradient-based One-Side Sampling (goss.hpp:26-216)."""

    # class default covers the legacy positional-PRNG oracle
    # (tpu_goss_hash=0): its jax.random.uniform stream depends on the
    # padded width, so bucket-padded it would not be bit-exact. The
    # hashed sampler flips the gate per-instance in init().
    _step_cache_ok = False

    def init(self, config, train_data, objective, training_metrics=()):
        # must precede super().init(): eligibility is snapshotted
        # during grower setup
        self._step_cache_ok = config.tpu_goss_hash != 0
        super().init(config, train_data, objective, training_metrics)
        self._reset_goss()

    def _sample_static_key(self):
        """Everything the hashed hook closes over (geometry-key
        component): the sampling rates. The legacy oracle never
        reaches the registry, so its closure ints don't ride here."""
        if self.config.tpu_goss_hash == 0:
            return ("goss_legacy",)
        return ("goss_hash", float(self.config.top_rate),
                float(self.config.other_rate))

    def _reset_goss(self):
        cfg = self.config
        if not (cfg.top_rate + cfg.other_rate <= 1.0):
            log.fatal("top_rate + other_rate cannot be larger than 1.0")
        if not (cfg.top_rate > 0.0 and cfg.other_rate > 0.0):
            log.fatal("top_rate and other_rate should be larger than 0")
        if cfg.bagging_freq > 0 and cfg.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS%s",
                 "" if cfg.tpu_goss_hash != 0 else " (legacy sampler)")
        self._hook_rng = np.random.default_rng(cfg.bagging_seed)
        # GOSS starts after 1/learning_rate warmup iterations
        # (goss.hpp:137-139); traced as a flag so the step doesn't
        # retrace when it switches on
        self._goss_warmup = int(1.0 / max(cfg.learning_rate, 1e-12))
        self._sample_hook = (self._hash_hook() if cfg.tpu_goss_hash != 0
                             else self._legacy_hook())
        self._step_key = None

    def _hash_hook(self):
        """The shard-invariant sampler: top-gradient threshold from an
        exact device sort over VALID rows, uniform-rest draw from the
        lowbias32 hash of (global row index, per-tree salt). Closes
        only over the two rates (covered by _sample_static_key), so
        the hook rides the process-wide shared step; the real row
        count, threshold index and amplification factor are all TRACED
        from ``rvalid`` — boosters with different N share one compiled
        step."""
        from ..ops.wave_grower import _hash_uniform
        top_rate = float(self.config.top_rate)
        other_rate = float(self.config.other_rate)

        def hook(g_all, h_all, mask, key, rvalid):
            # PRNGKey stores the seed in word 1 (word 0 is the high
            # half, zero for any sub-2^32 seed); the warmup dummy is
            # PRNGKey(0) and real seeds are drawn from [1, 2^31)
            on = key[1] != jnp.uint32(0)
            score = jnp.sum(jnp.abs(g_all * h_all), axis=0)  # [width]
            width = score.shape[0]
            if rvalid is None:
                # legacy routing (tpu_step_cache=0): exact row shapes,
                # every row real
                nf = jnp.float32(width)
                score_v = score
            else:
                nf = jnp.sum(rvalid.astype(jnp.float32))
                # pad rows sort to the bottom and never enter the top
                # set (real scores are >= 0)
                score_v = jnp.where(rvalid, score, -1.0)
            top_k = jnp.maximum(jnp.floor(nf * jnp.float32(top_rate)),
                                1.0)
            other_k = jnp.maximum(
                jnp.floor(nf * jnp.float32(other_rate)), 1.0)
            multiply = (nf - top_k) / other_k
            sorted_desc = -jnp.sort(-score_v)
            thr = jnp.take(sorted_desc, top_k.astype(jnp.int32) - 1)
            is_top = score_v >= thr
            p = other_k / jnp.maximum(nf - top_k, 1.0)
            u = _hash_uniform(jnp.arange(width, dtype=jnp.uint32),
                              key[1] ^ jnp.uint32(_GOSS_SALT))
            sampled = (u < p) & ~is_top
            if rvalid is not None:
                sampled = sampled & rvalid
            amp = jnp.where(sampled, multiply, 1.0)
            keep = (is_top | sampled).astype(jnp.float32)
            keep = jnp.where(on, keep, 1.0)
            amp = jnp.where(on, amp, 1.0)
            # tail = alignment pad + any valid-set passenger rows; its
            # mask is already zero, keep it that way
            tail = mask.shape[0] - width
            if tail:
                keep = jnp.concatenate(
                    [keep, jnp.zeros(tail, jnp.float32)])
            return g_all * amp, h_all * amp, mask * keep
        return hook

    def _legacy_hook(self):
        """The pre-hash positional-PRNG sampler, kept VERBATIM as the
        parity/repro oracle (tpu_goss_hash=0): its uniform stream is
        positional (padded-width dependent) and its count scalars are
        closure ints, so it stays per-booster-jitted and step-cache
        ineligible."""
        cfg = self.config
        n = self._n
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        multiply = (n - top_k) / other_k

        def hook(g_all, h_all, mask, key, rvalid=None):
            on = key[1] != jnp.uint32(0)
            score = jnp.sum(jnp.abs(g_all * h_all), axis=0)   # [N]
            thr = jax.lax.top_k(score, top_k)[0][-1]
            is_top = score >= thr
            p = other_k / max(n - top_k, 1)
            sampled = (jax.random.uniform(key, (n,)) < p) & ~is_top
            amp = jnp.where(sampled, jnp.float32(multiply), 1.0)
            keep = (is_top | sampled).astype(jnp.float32)
            keep = jnp.where(on, keep, 1.0)
            amp = jnp.where(on, amp, 1.0)
            # tail = alignment pad + any valid-set passenger rows; its
            # mask is already zero, keep it that way (read off the
            # traced mask shape so added valid sets retrace correctly)
            tail = mask.shape[0] - n
            if tail:
                keep = jnp.concatenate(
                    [keep, jnp.zeros(tail, jnp.float32)])
            g_all = g_all * amp
            h_all = h_all * amp
            return g_all, h_all, mask * keep
        return hook

    def train_one_iter(self, grad=None, hess=None):
        # during warmup, signal the hook off through a zeroed key
        if self.iter_ < self._goss_warmup:
            rng_state = self._hook_rng
            self._hook_rng = _ZeroKeyRng()
            try:
                return super().train_one_iter(grad, hess)
            finally:
                self._hook_rng = rng_state
        return super().train_one_iter(grad, hess)


class _ZeroKeyRng:
    """Stands in for the GOSS RNG during warmup: a zero key tells the
    in-jit hook to pass gradients through unsampled."""

    def integers(self, *_args, **_kw):
        return 0


class DART(GBDT):
    """Dropouts meet Multiple Additive Regression Trees
    (dart.hpp:17-190)."""

    def init(self, config, train_data, objective, training_metrics=()):
        super().init(config, train_data, objective, training_metrics)
        self._drop_rng = np.random.default_rng(config.drop_seed)
        self._tree_weight = []          # per iteration (uniform_drop off)
        self._sum_weight = 0.0
        self._drop_index = []

    def train_one_iter(self, grad=None, hess=None):
        """TrainOneIter (dart.hpp:52-66): drop, train on adjusted
        scores, normalize."""
        self._dropping_trees()
        ret = super().train_one_iter(grad, hess)
        if ret:
            return ret
        self._normalize()
        if not self.config.uniform_drop:
            self._tree_weight.append(self.shrinkage_rate)
            self._sum_weight += self.shrinkage_rate
        return False

    def _select_drops(self):
        cfg = self.config
        drops = []
        if self._drop_rng.random() < cfg.skip_drop:
            return drops
        drop_rate = cfg.drop_rate
        if not cfg.uniform_drop:
            if self._sum_weight <= 0:
                return drops
            inv_avg = len(self._tree_weight) / self._sum_weight
            if cfg.max_drop > 0:
                drop_rate = min(drop_rate,
                                cfg.max_drop * inv_avg / self._sum_weight)
            for i in range(self.iter_):
                if self._drop_rng.random() < \
                        drop_rate * self._tree_weight[i] * inv_avg:
                    drops.append(i)
                    if len(drops) >= cfg.max_drop > 0:
                        break
        else:
            if cfg.max_drop > 0 and self.iter_ > 0:
                drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
            for i in range(self.iter_):
                if self._drop_rng.random() < drop_rate:
                    drops.append(i)
                    if len(drops) >= cfg.max_drop > 0:
                        break
        return drops

    def _dropping_trees(self):
        """DroppingTrees (dart.hpp:86-135): subtract the dropped trees
        from the train scores and lower the shrinkage for the new tree."""
        cfg = self.config
        self._drop_index = self._select_drops()
        K = self.num_tree_per_iteration
        if self._drop_index:
            # hoisted: the packed4 tier's nibble-unpack is a full-
            # matrix pass — one per drop round, not one per tree
            tb = self._train_bins_unpacked()
        for i in self._drop_index:
            for k in range(K):
                rec = self.records[i * K + k]
                leaf = replay_partition(rec, tb,
                                        self._meta)[:self._n_score]
                self._scores = self._scores.at[k].set(add_leaf_outputs(
                    self._scores[k], leaf, rec.leaf_output, -1.0))
        kdrop = len(self._drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + kdrop)
        else:
            self.shrinkage_rate = (
                cfg.learning_rate if kdrop == 0
                else cfg.learning_rate / (cfg.learning_rate + kdrop))

    def _normalize(self):
        """Normalize (dart.hpp:137-190): rescale dropped trees to
        k/(k+1) of their old weight and patch train/valid scores."""
        cfg = self.config
        kdrop = float(len(self._drop_index))
        if not self._drop_index:
            return
        K = self.num_tree_per_iteration
        tb = self._train_bins_unpacked()   # hoisted full-matrix unpack
        if not cfg.xgboost_dart_mode:
            keep_scale = kdrop / (kdrop + 1.0)    # final tree weight
            weight_sub = 1.0 / (kdrop + 1.0)      # dart.hpp:163
        else:
            # sr = lr/(lr+k): final weight k*sr/lr = k/(lr+k)
            keep_scale = kdrop * self.shrinkage_rate / cfg.learning_rate
            weight_sub = 1.0 / (kdrop + cfg.learning_rate)  # dart.hpp:181
        for i in self._drop_index:
            for k in range(K):
                t = i * K + k
                rec = self.records[t]
                old_out = rec.leaf_output
                # valid: had +old, now should have keep_scale*old
                for vi in range(len(self.valid_sets)):
                    vleaf = replay_partition(
                        rec, self._valid_bins_dev[vi], self._meta)
                    self._valid_scores[vi] = \
                        self._valid_scores[vi].at[k].set(add_leaf_outputs(
                            self._valid_scores[vi][k], vleaf, old_out,
                            keep_scale - 1.0))
                # train: was subtracted fully, add back keep_scale*old
                leaf = replay_partition(rec, tb, self._meta)[:self._n_score]
                self._scores = self._scores.at[k].set(add_leaf_outputs(
                    self._scores[k], leaf, old_out, keep_scale))
                self.records[t] = rec._replace(
                    leaf_output=old_out * keep_scale,
                    internal_value=rec.internal_value * keep_scale)
                self.models[t] = None     # refresh host mirror lazily
            if not cfg.uniform_drop:
                self._sum_weight -= self._tree_weight[i] * weight_sub
                self._tree_weight[i] *= keep_scale


class RF(GBDT):
    """Random Forest (rf.hpp:18-172): bagged trees on fixed targets,
    averaged predictions."""

    # no shared-step reuse: RF replaces the base fused step with the
    # running-mean averaging step below (its own _get_step_fn)
    _step_cache_ok = False

    def __init__(self):
        super().__init__()
        self.average_output = True

    def init(self, config, train_data, objective, training_metrics=()):
        if not (config.bagging_freq > 0
                and 0.0 < config.bagging_fraction < 1.0):
            log.fatal("RF needs bagging_freq > 0 and bagging_fraction in "
                      "(0, 1)")
        super().init(config, train_data, objective, training_metrics)
        if train_data.metadata.init_score is not None:
            log.fatal("Cannot use init_score with RF")
        self.shrinkage_rate = 1.0
        self._rf_targets()

    def _rf_targets(self):
        """GetRFTargets (rf.hpp:81-107): fixed gradients from labels."""
        n, K = self._n, self.num_tree_per_iteration
        label = np.asarray(self._label_np, np.float32)
        g = np.zeros((K, n), np.float32)
        if K == 1:
            g[0] = -label
        else:
            g[label.astype(np.int64), np.arange(n)] = -1.0
        self._rf_g = jnp.asarray(g)
        self._rf_h = jnp.ones((K, n), jnp.float32)

    def boost_from_average(self, class_id):
        return 0.0

    def _get_step_fn(self, custom: bool):
        """RF step: same fused tree build, but scores are the RUNNING
        MEAN of tree outputs (MultiplyScore dance, rf.hpp:139-143) and
        the leaf outputs are renewed against a zero baseline."""
        key_id = ("rf", len(self._valid_bins_dev))
        if getattr(self, "_step_key", None) == key_id:
            return self._step_fn
        grower = self._grower
        K = self.num_tree_per_iteration
        n = self._n
        pad_rows = self._n_total - n
        valid_slices = tuple(self._valid_row_slices)
        meta = self._meta
        obj = self.objective
        L = self._grower_cfg.num_leaves
        renew = obj is not None and obj.is_renew_tree_output()
        if renew:
            from ..ops.renew import renew_leaf_outputs
            renew_label = jnp.asarray(
                obj.trans_label if hasattr(obj, "trans_label")
                else obj.label, jnp.float32)
            w = getattr(obj, "label_weight", None)
            if w is None:
                w = obj.weights
            renew_w = None if w is None else jnp.asarray(w, jnp.float32)
            renew_alpha = float(obj.renew_tree_output_percentile())

        def step(bins, scores, valid_scores, mask, fmask,
                 iter_f, init_bias, g_in, h_in, key):
            recs = []
            vs = list(valid_scores)
            for k in range(K):
                g_k, h_k = g_in[k], h_in[k]
                if pad_rows:
                    zpad = jnp.zeros(pad_rows, jnp.float32)
                    g_k = jnp.concatenate([g_k, zpad])
                    h_k = jnp.concatenate([h_k, zpad])
                rec, leaf_full = grower(bins, g_k, h_k, mask, fmask)
                leaf_ids = leaf_full[:n]
                if renew:
                    # baseline is zero scores (tmp_score_, rf.hpp:146)
                    new_out = renew_leaf_outputs(
                        leaf_ids, renew_label, renew_w, L, renew_alpha,
                        rec.leaf_output, mask[:n])
                    new_out = jnp.where(rec.num_leaves > 1, new_out,
                                        rec.leaf_output)
                    rec = rec._replace(leaf_output=new_out)
                grew = rec.num_leaves > 1
                # scores = (scores * it + tree_out) / (it + 1); skipped
                # entirely for splitless trees (rf.hpp:139-145)
                upd = (scores[k] * iter_f + rec.leaf_output[leaf_ids]) \
                    / (iter_f + 1.0)
                scores = scores.at[k].set(jnp.where(grew, upd, scores[k]))
                for vi, (voff, vn) in enumerate(valid_slices):
                    vleaf = leaf_full[voff:voff + vn]
                    vupd = (vs[vi][k] * iter_f
                            + rec.leaf_output[vleaf]) / (iter_f + 1.0)
                    vs[vi] = vs[vi].at[k].set(
                        jnp.where(grew, vupd, vs[vi][k]))
                recs.append(rec)
            return scores, tuple(vs), recs

        # jit-capture: ok(K, n, pad_rows, grower, renew, renew_label,
        # renew_w) — RF's averaging step is step-cache-INELIGIBLE by
        # design (CHANGES.md PR 5): this jit is per-booster, cached on
        # self._step_fn, and the captured aux arrays are this
        # booster's own — never registry-shared.
        self._step_fn = jax.jit(step, donate_argnums=(1, 2))
        self._step_key = key_id
        return self._step_fn

    def train_one_iter(self, grad=None, hess=None):
        """TrainOneIter (rf.hpp:112-151): fixed targets, averaged
        scores, never finishes on its own."""
        if grad is not None or hess is not None:
            log.fatal("RF does not support custom objectives")
        mask_np = self._bagging_mask(self.iter_)
        if mask_np is None:
            mask = self._full_mask_dev
        else:
            tail = self._n_total - self._n
            if tail:
                mask_np = np.concatenate(
                    [mask_np, np.zeros(tail, np.float32)])
            mask = jnp.asarray(mask_np)
        fmask = self._feature_mask_dev()
        step = self._get_step_fn(False)
        self._scores, new_valids, recs = step(
            self._bins_dev,
            self._scores, tuple(self._valid_scores), mask, fmask,
            jnp.float32(self.iter_), self._zero_bias, self._rf_g,
            self._rf_h, self._dummy_key)
        self._valid_scores = list(new_valids)
        for rec in recs:
            self.records.append(rec)
            self.models.append(None)
            self._tree_shrinkage.append(1.0)
        self.iter_ += 1
        self._bump_model_gen()
        # RF never stops on a splitless bag (rf.hpp TrainOneIter always
        # returns false): a degenerate bagging draw says nothing about
        # later draws, and splitless trees are harmless 1-leaf no-ops
        return False

    def finish_training(self):
        return

    def _effective_num_models(self):
        # splitless trees stay in an RF model (no trimming)
        return len(self.models)

    def rollback_one_iter(self):
        """RollbackOneIter (rf.hpp:153-166): un-average the last trees."""
        if self.iter_ <= 0:
            return
        K = self.num_tree_per_iteration
        it = self.iter_
        for k in range(K - 1, -1, -1):
            rec = self.records.pop()
            self.models.pop()
            self._tree_shrinkage.pop()
            if int(rec.num_leaves) > 1:
                leaf = replay_partition(rec, self._train_bins_unpacked(),
                                        self._meta)[:self._n]
                self._scores = self._scores.at[k].set(
                    (self._scores[k] * it
                     - rec.leaf_output[leaf]) / max(it - 1, 1))
                for vi in range(len(self.valid_sets)):
                    vleaf = replay_partition(
                        rec, self._valid_bins_dev[vi], self._meta)
                    self._valid_scores[vi] = \
                        self._valid_scores[vi].at[k].set(
                            (self._valid_scores[vi][k] * it
                             - rec.leaf_output[vleaf]) / max(it - 1, 1))
        self.iter_ -= 1
        self._clean_groups = min(self._clean_groups, self.iter_)
        self._stopped = False
        # rollback + retrain lands on the SAME (gen, len) without this
        # bump — the stacked-predictor fast path would serve the
        # rolled-back trees
        self._bump_model_gen()
