"""Decision tree model (host-side arrays + serialization).

Counterpart of the reference Tree (reference: include/LightGBM/tree.h:1-518,
src/io/tree.cpp:209-355). Same array-of-nodes representation and the same
model text format (v2), so model files interoperate with the reference:

- node i is created by split i; leaves are encoded as ``~leaf_index`` in
  child pointers (tree.h left_child_/right_child_ convention)
- decision_type bit flags: bit0 categorical, bit1 default_left,
  bits 2-3 missing_type (tree.h:14-15,183-201)
- thresholds are real-valued bin upper bounds (Tree::Split via
  RealThreshold; infinities clamped by Common::AvoidInf, common.h:661)
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.binning import MissingType
from ..utils import log

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
_MAX_DOUBLE = 1e300


def avoid_inf(x: float) -> float:
    """Common::AvoidInf (common.h:661)."""
    if np.isnan(x):
        return 0.0
    return float(np.clip(x, -_MAX_DOUBLE, _MAX_DOUBLE))


class Tree:
    """Fixed-arity tree as parallel arrays."""

    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        self.num_leaves = 1
        self.num_cat = 0
        self.split_feature: List[int] = []     # [num_leaves-1] real feat idx
        self.split_gain: List[float] = []
        self.threshold_in_bin: List[int] = []
        self.threshold: List[float] = []
        self.decision_type: List[int] = []
        self.left_child: List[int] = []
        self.right_child: List[int] = []
        self.leaf_value: List[float] = [0.0]
        self.leaf_count: List[int] = [0]
        self.internal_value: List[float] = []
        self.internal_count: List[int] = []
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.shrinkage = 1.0
        # leaf -> (parent_node, is_left) for child-pointer fixups
        self._leaf_ptr = {0: None}

    # -- growth (host mirror of Tree::Split, tree.h:53) ---------------------

    def split(self, leaf: int, feature: int, threshold_bin: int,
              threshold_real: float, left_value: float, right_value: float,
              left_count: int, right_count: int, gain: float,
              missing_type: int, default_left: bool) -> int:
        node = self.num_leaves - 1
        # fix parent pointer that referenced `leaf`
        ptr = self._leaf_ptr.get(leaf)
        if ptr is not None:
            pnode, is_left = ptr
            if is_left:
                self.left_child[pnode] = node
            else:
                self.right_child[pnode] = node
        dtype = 0
        if default_left:
            dtype |= K_DEFAULT_LEFT_MASK
        dtype |= (missing_type & 3) << 2
        self.split_feature.append(feature)
        self.split_gain.append(gain)
        self.threshold_in_bin.append(threshold_bin)
        self.threshold.append(avoid_inf(threshold_real))
        self.decision_type.append(dtype)
        self.left_child.append(~leaf)
        self.right_child.append(~self.num_leaves)
        self.internal_value.append(
            self.leaf_value[leaf] if leaf < len(self.leaf_value) else 0.0)
        self.internal_count.append(left_count + right_count)
        new_leaf = self.num_leaves
        self._leaf_ptr[leaf] = (node, True)
        self._leaf_ptr[new_leaf] = (node, False)
        # left keeps slot `leaf`
        if leaf < len(self.leaf_value):
            self.leaf_value[leaf] = left_value
            self.leaf_count[leaf] = left_count
        self.leaf_value.append(right_value)
        self.leaf_count.append(right_count)
        self.num_leaves += 1
        return node

    def split_categorical(self, leaf: int, feature: int,
                          cat_values, left_value: float,
                          right_value: float, left_count: int,
                          right_count: int, gain: float,
                          missing_type: int) -> int:
        """Tree::SplitCategorical (src/io/tree.cpp): the left-set is a
        bitset over CATEGORY values; threshold_in_bin/threshold index
        into cat_boundaries."""
        cat_values = sorted(int(v) for v in cat_values if v >= 0)
        max_cat = max(cat_values, default=0)
        n_words = max_cat // 32 + 1
        words = [0] * n_words
        for v in cat_values:
            words[v // 32] |= 1 << (v % 32)
        ci = self.num_cat
        node = self.num_leaves - 1
        ptr = self._leaf_ptr.get(leaf)
        if ptr is not None:
            pnode, is_left = ptr
            if is_left:
                self.left_child[pnode] = node
            else:
                self.right_child[pnode] = node
        dtype = K_CATEGORICAL_MASK | ((missing_type & 3) << 2)
        self.split_feature.append(feature)
        self.split_gain.append(gain)
        self.threshold_in_bin.append(ci)
        self.threshold.append(float(ci))
        self.decision_type.append(dtype)
        self.left_child.append(~leaf)
        self.right_child.append(~self.num_leaves)
        self.internal_value.append(
            self.leaf_value[leaf] if leaf < len(self.leaf_value) else 0.0)
        self.internal_count.append(left_count + right_count)
        new_leaf = self.num_leaves
        self._leaf_ptr[leaf] = (node, True)
        self._leaf_ptr[new_leaf] = (node, False)
        if leaf < len(self.leaf_value):
            self.leaf_value[leaf] = left_value
            self.leaf_count[leaf] = left_count
        self.leaf_value.append(right_value)
        self.leaf_count.append(right_count)
        self.num_leaves += 1
        self.cat_boundaries.append(self.cat_boundaries[-1] + n_words)
        self.cat_threshold.extend(words)
        self.num_cat += 1
        return node

    def set_internal_value(self, node: int, value: float) -> None:
        self.internal_value[node] = value

    def apply_shrinkage(self, rate: float) -> None:
        """Tree::Shrinkage (tree.h:139-150)."""
        self.leaf_value = [v * rate for v in self.leaf_value]
        self.internal_value = [v * rate for v in self.internal_value]
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """Tree::AddBias (tree.h:151)."""
        self.leaf_value = [v + val for v in self.leaf_value]
        self.internal_value = [v + val for v in self.internal_value]
        self.shrinkage = 1.0

    # -- prediction (tree.h:212-266) ---------------------------------------

    def _decision(self, fval: float, node: int) -> int:
        dt = self.decision_type[node]
        if dt & K_CATEGORICAL_MASK:
            return self._categorical_decision(fval, node)
        missing_type = (dt >> 2) & 3
        if np.isnan(fval) and missing_type != MissingType.NAN:
            fval = 0.0
        if ((missing_type == MissingType.ZERO and
             -1e-35 <= fval <= 1e-35)
                or (missing_type == MissingType.NAN and np.isnan(fval))):
            if dt & K_DEFAULT_LEFT_MASK:
                return self.left_child[node]
            return self.right_child[node]
        if fval <= self.threshold[node]:
            return self.left_child[node]
        return self.right_child[node]

    def _categorical_decision(self, fval: float, node: int) -> int:
        if np.isnan(fval):
            return self.right_child[node]
        cat = int(fval)
        if cat < 0:
            return self.right_child[node]
        i = self.threshold_in_bin[node]  # cat index into cat_boundaries
        lo = self.cat_boundaries[i]
        hi = self.cat_boundaries[i + 1]
        for word_idx in range(lo, hi):
            pos = (word_idx - lo) * 32
            if pos <= cat < pos + 32:
                if (self.cat_threshold[word_idx] >> (cat - pos)) & 1:
                    return self.left_child[node]
        return self.right_child[node]

    def _traverse(self, X: np.ndarray) -> np.ndarray:
        """Vectorized level-synchronous traversal: all rows advance one
        node per pass (numpy gathers replace the per-row while loop the
        reference runs under OpenMP, tree.h:212-266)."""
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.full(n, -1, np.int64)     # ~0: the single leaf
        feat = np.asarray(self.split_feature, np.int64)
        thresh = np.asarray(self.threshold, np.float64)
        dtyp = np.asarray(self.decision_type, np.int64)
        left = np.asarray(self.left_child, np.int64)
        right = np.asarray(self.right_child, np.int64)
        is_cat = (dtyp & K_CATEGORICAL_MASK) != 0
        def_left = (dtyp & K_DEFAULT_LEFT_MASK) != 0
        mtype = (dtyp >> 2) & 3
        cat_bound = np.asarray(self.cat_boundaries, np.int64)
        cat_words = np.asarray(self.cat_threshold, np.uint32)

        node = np.zeros(n, np.int64)
        active = np.arange(n)
        while active.size:
            cur = node[active]
            fval = X[active, feat[cur]]
            nan = np.isnan(fval)
            mt = mtype[cur]
            # numerical decision with missing handling (tree.h:183-201)
            fz = np.where(nan & (mt != MissingType.NAN), 0.0, fval)
            miss = ((mt == MissingType.ZERO)
                    & (fz >= -1e-35) & (fz <= 1e-35)) \
                | ((mt == MissingType.NAN) & nan)
            go_left = np.where(miss, def_left[cur], fz <= thresh[cur])
            if is_cat.any():
                cat_rows = is_cat[cur]
                if cat_rows.any():
                    cc = cur[cat_rows]
                    cv = fval[cat_rows]
                    ok = ~np.isnan(cv) & (cv >= 0)
                    cat = np.where(ok, cv, 0).astype(np.int64)
                    ci = np.asarray(self.threshold_in_bin,
                                    np.int64)[cc]
                    lo, hi = cat_bound[ci], cat_bound[ci + 1]
                    word = lo + cat // 32
                    in_range = ok & (word < hi)
                    bit = np.zeros(len(cc), bool)
                    if in_range.any():
                        w = cat_words[word[in_range]]
                        bit[in_range] = (
                            (w >> (cat[in_range] % 32)) & 1) != 0
                    go_left[cat_rows] = bit
            node[active] = np.where(go_left, left[cur], right[cur])
            active = active[node[active] >= 0]
        return node

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Raw leaf values per row (vectorized traversal)."""
        leaves = ~self._traverse(np.asarray(X, np.float64))
        return np.asarray(self.leaf_value, np.float64)[leaves]

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        return (~self._traverse(np.asarray(X, np.float64))).astype(np.int32)

    # -- SHAP contributions (tree.h:118 PredictContrib) ----------------------

    def expected_value(self, node: int = 0) -> float:
        """Cover-weighted mean output of the (sub)tree — the SHAP base
        value (tree.h ExpectedValue)."""
        if self.num_leaves == 1:
            return self.leaf_value[0]
        if node < 0:
            return self.leaf_value[~node]
        total = max(self.internal_count[node], 1)
        lc, rc = self.left_child[node], self.right_child[node]
        lw = (self.leaf_count[~lc] if lc < 0 else self.internal_count[lc])
        rw = (self.leaf_count[~rc] if rc < 0 else self.internal_count[rc])
        return (lw * self.expected_value(lc)
                + rw * self.expected_value(rc)) / max(lw + rw, 1)

    def predict_contrib(self, X: np.ndarray, out: np.ndarray) -> None:
        """TreeSHAP (Lundberg & Lee): exact Shapley values for one tree,
        accumulated into ``out`` [N, F+1]; last column is the bias.
        Mirrors the reference's TreeSHAP port (tree.h PredictContrib /
        tree.cpp TreeSHAP recursion)."""
        X = np.asarray(X, np.float64)
        base = self.expected_value()
        out[:, -1] += base
        if self.num_leaves == 1:
            return
        for i in range(X.shape[0]):
            self._tree_shap(X[i], out[i], 0, [], 1.0, 1.0, -1)

    def _node_cover(self, node: int) -> float:
        return float(self.leaf_count[~node] if node < 0
                     else self.internal_count[node])

    def _tree_shap(self, x, phi, node, path, pzero, pone, pfeat):
        # path: list of [feature, zero_frac, one_frac, pweight]
        path = [p[:] for p in path]
        _extend(path, pzero, pone, pfeat)
        if node < 0:                       # leaf
            leaf_v = self.leaf_value[~node]
            for i in range(1, len(path)):
                w = _unwound_sum(path, i)
                phi[path[i][0]] += w * (path[i][2] - path[i][1]) * leaf_v
            return
        hot = self._decision(x[self.split_feature[node]], node)
        cold = (self.right_child[node]
                if hot == self.left_child[node] else self.left_child[node])
        cover = self._node_cover(node)
        hot_frac = self._node_cover(hot) / cover
        cold_frac = self._node_cover(cold) / cover
        incoming_zero, incoming_one = 1.0, 1.0
        feat = self.split_feature[node]
        path_idx = next((i for i in range(1, len(path))
                         if path[i][0] == feat), -1)
        if path_idx >= 0:
            incoming_zero = path[path_idx][1]
            incoming_one = path[path_idx][2]
            _unwind(path, path_idx)
        self._tree_shap(x, phi, hot, path,
                        incoming_zero * hot_frac, incoming_one, feat)
        self._tree_shap(x, phi, cold, path,
                        incoming_zero * cold_frac, 0.0, feat)

    # -- serialization (src/io/tree.cpp:209-243) ----------------------------

    def to_string(self) -> str:
        nl = self.num_leaves
        buf = [f"num_leaves={nl}", f"num_cat={self.num_cat}"]

        def arr(name, a, fmt=str):
            buf.append(f"{name}=" + " ".join(fmt(x) for x in a))

        arr("split_feature", self.split_feature[:nl - 1])
        arr("split_gain", self.split_gain[:nl - 1], _fmt_float)
        arr("threshold", self.threshold[:nl - 1], _fmt_double)
        arr("decision_type", self.decision_type[:nl - 1])
        arr("left_child", self.left_child[:nl - 1])
        arr("right_child", self.right_child[:nl - 1])
        arr("leaf_value", self.leaf_value[:nl], _fmt_double)
        arr("leaf_count", self.leaf_count[:nl])
        arr("internal_value", self.internal_value[:nl - 1], _fmt_float)
        arr("internal_count", self.internal_count[:nl - 1])
        if self.num_cat > 0:
            arr("cat_boundaries", self.cat_boundaries[:self.num_cat + 1])
            arr("cat_threshold", self.cat_threshold)
        buf.append(f"shrinkage={_fmt_float(self.shrinkage)}")
        buf.append("")
        return "\n".join(buf)

    @classmethod
    def from_string(cls, s: str) -> "Tree":
        """Tree parse ctor (src/io/tree.cpp:377+ semantics)."""
        kv = {}
        for line in s.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        t = cls(int(kv["num_leaves"]))
        t.num_leaves = int(kv["num_leaves"])
        t.num_cat = int(kv.get("num_cat", 0))

        def ints(key, default=None):
            if key not in kv or kv[key] == "":
                return default if default is not None else []
            return [int(float(x)) for x in kv[key].split()]

        def floats(key, default=None):
            if key not in kv or kv[key] == "":
                return default if default is not None else []
            return [float(x) for x in kv[key].split()]

        nl = t.num_leaves
        t.split_feature = ints("split_feature")
        t.split_gain = floats("split_gain")
        t.threshold = floats("threshold")
        t.decision_type = ints("decision_type", [0] * (nl - 1))
        t.left_child = ints("left_child")
        t.right_child = ints("right_child")
        t.leaf_value = floats("leaf_value", [0.0])
        t.leaf_count = ints("leaf_count", [0] * nl)
        t.internal_value = floats("internal_value", [0.0] * (nl - 1))
        t.internal_count = ints("internal_count", [0] * (nl - 1))
        t.threshold_in_bin = [
            int(th) if (dt & K_CATEGORICAL_MASK) else 0
            for th, dt in zip(t.threshold, t.decision_type)]
        if t.num_cat > 0:
            t.cat_boundaries = ints("cat_boundaries")
            t.cat_threshold = ints("cat_threshold")
        t.shrinkage = float(kv.get("shrinkage", 1))
        return t

    def to_json(self) -> dict:
        """Tree::ToJSON (src/io/tree.cpp:245-300)."""
        d = {
            "num_leaves": self.num_leaves,
            "num_cat": self.num_cat,
            "shrinkage": self.shrinkage,
        }
        if self.num_leaves == 1:
            d["tree_structure"] = {"leaf_value": self.leaf_value[0]}
        else:
            d["tree_structure"] = self._node_to_json(0)
        return d

    def _node_to_json(self, index: int) -> dict:
        if index >= 0:
            dt = self.decision_type[index]
            node = {
                "split_index": index,
                "split_feature": self.split_feature[index],
                "split_gain": self.split_gain[index],
                "threshold": self.threshold[index],
                "decision_type": ("==" if dt & K_CATEGORICAL_MASK else "<="),
                "default_left": bool(dt & K_DEFAULT_LEFT_MASK),
                "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
                "internal_value": self.internal_value[index],
                "internal_count": self.internal_count[index],
                "left_child": self._node_to_json(self.left_child[index]),
                "right_child": self._node_to_json(self.right_child[index]),
            }
            return node
        leaf = ~index
        return {
            "leaf_index": leaf,
            "leaf_value": self.leaf_value[leaf],
            "leaf_count": self.leaf_count[leaf],
        }

    # -- misc ---------------------------------------------------------------

    def leaf_output(self, leaf: int) -> float:
        return self.leaf_value[leaf]

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = value


def _extend(path, pzero, pone, pfeat):
    """TreeSHAP ExtendPath: grow the feature path by one split."""
    path.append([pfeat, pzero, pone, 1.0 if len(path) == 0 else 0.0])
    n = len(path) - 1
    for i in range(n - 1, -1, -1):
        path[i + 1][3] += pone * path[i][3] * (i + 1) / (n + 1)
        path[i][3] = pzero * path[i][3] * (n - i) / (n + 1)


def _unwind(path, path_idx):
    """TreeSHAP UnwindPath: remove the split at path_idx."""
    n = len(path) - 1
    pone = path[path_idx][2]
    pzero = path[path_idx][1]
    next_one = path[n][3]
    for i in range(n - 1, -1, -1):
        if pone != 0:
            tmp = path[i][3]
            path[i][3] = next_one * (n + 1) / ((i + 1) * pone)
            next_one = tmp - path[i][3] * pzero * (n - i) / (n + 1)
        else:
            path[i][3] = path[i][3] * (n + 1) / (pzero * (n - i))
    for i in range(path_idx, n):
        path[i][0] = path[i + 1][0]
        path[i][1] = path[i + 1][1]
        path[i][2] = path[i + 1][2]
    path.pop()


def _unwound_sum(path, path_idx):
    """TreeSHAP UnwoundPathSum: total weight had path_idx been skipped."""
    n = len(path) - 1
    pone = path[path_idx][2]
    pzero = path[path_idx][1]
    next_one = path[n][3]
    total = 0.0
    for i in range(n - 1, -1, -1):
        if pone != 0:
            tmp = next_one * (n + 1) / ((i + 1) * pone)
            total += tmp
            next_one = path[i][3] - tmp * pzero * ((n - i) / (n + 1))
        elif pzero != 0:
            total += (path[i][3] / pzero) * (n + 1) / (n - i)
    return total


def _fmt_float(x) -> str:
    return np.format_float_positional(
        np.float32(x), unique=True, trim="0") if np.isfinite(x) else str(x)


def _fmt_double(x) -> str:
    if not np.isfinite(x):
        return str(x)
    return repr(float(x))


def record_arrays_from_tree(tree: Tree, real_to_inner: dict, mappers,
                            max_leaves: int) -> dict:
    """Inverse of ``tree_from_record``: host Tree -> TreeRecord-shaped
    numpy arrays in bin space, so loaded models get device-resident
    records (fast prediction + continued training; the reference
    rebuilds its in-memory model the same way in
    GBDT::LoadModelFromString, gbdt_model_text.cpp:339-450).

    Split order: node i IS split i (Tree::Split numbering), and the leaf
    a node split is recovered by descending left children to a leaf —
    when leaf ``l`` is re-split, its left child keeps slot ``l``.
    Thresholds return to bin space through the mapper: thresholds are
    bin upper bounds, so ``value_to_bin`` is exact on the same mappers.
    """
    L = max_leaves
    nl = tree.num_leaves
    if nl > L:
        log.fatal(f"Loaded tree has {nl} leaves > num_leaves cap {L}; "
                  "raise num_leaves to continue training this model")
    s = max(L - 1, 1)
    out = {
        "num_leaves": np.int32(nl),
        "split_leaf": np.full(s, -1, np.int32),
        "split_feature": np.zeros(s, np.int32),
        "split_bin": np.zeros(s, np.int32),
        "split_gain": np.zeros(s, np.float32),
        "split_default_left": np.zeros(s, bool),
        "leaf_output": np.zeros(L, np.float32),
        "leaf_count": np.zeros(L, np.float32),
        "leaf_sum_g": np.zeros(L, np.float32),
        "leaf_sum_h": np.zeros(L, np.float32),
        "internal_value": np.zeros(s, np.float32),
        "internal_count": np.zeros(s, np.float32),
        "split_is_cat": np.zeros(s, bool),
        "split_cat_words": np.zeros((s, 8), np.int32),
    }
    for i in range(nl - 1):
        c = tree.left_child[i]
        while c >= 0:
            c = tree.left_child[c]
        out["split_leaf"][i] = ~c
        real = tree.split_feature[i]
        inner = real_to_inner.get(real)
        if inner is None:
            log.fatal(f"Loaded model splits on feature {real} which is "
                      "trivial/unused in the new training data")
        out["split_feature"][i] = inner
        if tree.decision_type[i] & K_CATEGORICAL_MASK:
            # category-space bitset -> bin-space words via the mapper
            ci = tree.threshold_in_bin[i]
            lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
            words = np.zeros(8, np.uint32)
            for cat, b in mappers[inner].categorical_2_bin.items():
                w = cat // 32
                if lo + w < hi and b < 256 and cat >= 0 \
                        and (tree.cat_threshold[lo + w] >> (cat % 32)) & 1:
                    words[b // 32] |= np.uint32(1 << (b % 32))
            out["split_is_cat"][i] = True
            out["split_cat_words"][i] = words.astype(np.int32)
        else:
            out["split_bin"][i] = int(mappers[inner].value_to_bin(
                np.asarray([tree.threshold[i]]))[0])
            out["split_default_left"][i] = bool(
                tree.decision_type[i] & K_DEFAULT_LEFT_MASK)
        out["split_gain"][i] = tree.split_gain[i]
        out["internal_value"][i] = tree.internal_value[i]
        out["internal_count"][i] = tree.internal_count[i]
    out["leaf_output"][:nl] = tree.leaf_value[:nl]
    out["leaf_count"][:nl] = tree.leaf_count[:nl]
    return out


def tree_from_record(rec, mappers, real_features, shrinkage: float,
                     max_leaves: int) -> Tree:
    """Build a host Tree from a device TreeRecord (grower output).

    ``mappers``: BinMapper per inner feature; ``real_features``: inner
    feature index -> original column index mapping.
    """
    rec_np = (rec if isinstance(rec, dict)
              else {k: np.asarray(v) for k, v in rec._asdict().items()})
    nl = int(rec_np["num_leaves"])
    t = Tree(max_leaves)
    cat_flags = rec_np.get("split_is_cat")
    cat_words = rec_np.get("split_cat_words")
    for i in range(nl - 1):
        leaf = int(rec_np["split_leaf"][i])
        if leaf < 0:
            break
        feat = int(rec_np["split_feature"][i])
        tbin = int(rec_np["split_bin"][i])
        mapper = mappers[feat]
        if cat_flags is not None and bool(cat_flags[i]):
            # bin-space bitset -> category values via the mapper
            words = np.asarray(cat_words[i]).astype(np.int64)
            cats = [mapper.bin_2_categorical[b]
                    for b in range(len(mapper.bin_2_categorical))
                    if (words[b // 32] >> (b % 32)) & 1]
            node = t.split_categorical(
                leaf=leaf,
                feature=int(real_features[feat]),
                cat_values=cats,
                left_value=0.0, right_value=0.0,
                left_count=0, right_count=0,
                gain=float(rec_np["split_gain"][i]),
                missing_type=mapper.missing_type,
            )
        else:
            node = t.split(
                leaf=leaf,
                feature=int(real_features[feat]),
                threshold_bin=tbin,
                threshold_real=mapper.bin_to_value(tbin),
                left_value=0.0, right_value=0.0,
                left_count=0, right_count=0,
                gain=float(rec_np["split_gain"][i]),
                missing_type=mapper.missing_type,
                default_left=bool(rec_np["split_default_left"][i]),
            )
        t.set_internal_value(node, float(rec_np["internal_value"][i]))
        t.internal_count[node] = int(round(float(rec_np["internal_count"][i])))
    for leaf in range(nl):
        t.leaf_value[leaf] = float(rec_np["leaf_output"][leaf])
        t.leaf_count[leaf] = int(round(float(rec_np["leaf_count"][leaf])))
    t.apply_shrinkage(shrinkage)
    return t
