"""GBDT boosting engine.

TPU-native counterpart of the reference GBDT
(reference: src/boosting/gbdt.{h,cpp}: Init gbdt.cpp:47, TrainOneIter
gbdt.cpp:333-412, Bagging gbdt.cpp:182-243, UpdateScore gbdt.cpp:451,
EvalAndCheckEarlyStopping gbdt.cpp:432, model text
src/boosting/gbdt_model_text.cpp:240-540).

Design: scores, gradients, bagging masks and the per-tree growth all stay
on device; the host drives one jitted tree-build per (iteration, class)
and keeps lightweight python Tree mirrors for serialization/prediction on
raw features. Bagging uses a 0/1 device mask folded into the histogram
weights (equivalent to the reference's index-subset bagging — histograms,
counts and leaf sums see only bagged rows).

The training loop performs ZERO device→host transfers per iteration:
TreeRecords stay on device, host Tree mirrors are materialized lazily
from ONE packed stacked download (pack_record), and the reference's
"no more leaves to split" stop (gbdt.cpp:393-409) is detected by a
periodic check every ``tpu_stop_check_interval`` iterations plus
``finish_training()`` after the boosting loop; serialization
independently caps at the first splitless iteration so mid-training
checkpoints stay reference-equivalent. This matters doubly on TPU: each
host transfer is a high-latency RPC, and the reference's own GPU path
had the same host-roundtrip problem (gpu_tree_learner.cpp:891-1073
hides it with async copies; we remove the transfers instead).
"""
from __future__ import annotations

import contextlib
import json
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import TpuDataset
from ..metrics import Metric
from ..obs import reqlog as obs_reqlog
from ..obs import trace as obs_trace
from ..objectives import ObjectiveFunction
from ..ops.grower import pack_record, unpack_record
from ..ops.predict import add_leaf_outputs, replay_partition
from ..ops.split import SplitParams
from ..ops.wave_grower import WaveGrowerConfig
from ..utils import log, timing
from ..analysis import lockorder
from .tree import Tree, tree_from_record

K_MODEL_VERSION = "v2"     # gbdt.h kModelVersion


class GBDT:
    """Gradient Boosting Decision Tree driver (boosting.h:22 interface)."""

    # gate for the compiled-step registry: variants whose step is not
    # a pure function of the shared geometry opt out — RF replaces the
    # step entirely; GOSS flips this per-INSTANCE (models/boosting.py):
    # the hashed sampler (tpu_goss_hash != 0) is pad/shard-invariant
    # and rides the shared step, the legacy positional-PRNG oracle
    # (tpu_goss_hash=0) keeps the per-booster closure
    _step_cache_ok = True

    def __init__(self):
        self.config: Optional[Config] = None
        self.train_data: Optional[TpuDataset] = None
        self.objective: Optional[ObjectiveFunction] = None
        # host trees, class-major order; None = not yet materialized from
        # the device record (lazily built, see _ensure_host_trees)
        self.models: List[Optional[Tree]] = []
        self.records: List = []                # device TreeRecords (same order)
        self._tree_shrinkage: List[float] = []  # per-tree file shrinkage
        self.iter_ = 0
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.shrinkage_rate = 0.1
        self.max_feature_idx = 0
        self.label_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.average_output = False
        self.valid_sets: List[TpuDataset] = []
        self.valid_names: List[str] = []
        self.valid_metrics: List[List[Metric]] = []
        self.training_metrics: List[Metric] = []
        self.best_score: Dict = {}
        self.loaded_parameter = ""
        self._grower = None
        # boosting-variant hooks (models/boosting.py): an in-jit
        # gradient sampler (GOSS) and a per-iteration PRNG stream
        self._sample_hook = None
        self._hook_rng = None
        # serving-path state: the cached StackedModel, the exact tree
        # objects it stacked (identity-checked for incremental extend),
        # and the lock that keeps a predict() racing a retrain from
        # ever seeing a half-built predictor (RLock: _bump_model_gen
        # runs under it from paths _stacked_model may itself trigger)
        self._stacked_lock = lockorder.named_rlock(
            "gbdt._stacked_lock")
        self._stacked_cache = None        # guarded-by: _stacked_guard()
        self._stacked_ref: Optional[List] = None  # guarded-by: _stacked_guard()
        self._model_gen = 0               # guarded-by: _stacked_guard()

    # -- init (gbdt.cpp:47-117) --------------------------------------------

    def init(self, config: Config, train_data: TpuDataset,
             objective: Optional[ObjectiveFunction],
             training_metrics: Sequence[Metric] = ()):
        self.config = config
        self.train_data = train_data
        # kernel autotuner + persistent XLA compile cache: tile choices
        # come from the on-disk tuning cache (timed once per shape) and
        # repeated runs skip recompilation entirely (ops/autotune.py)
        from ..ops import autotune, step_cache
        autotune.configure(config.tpu_autotune,
                           config.tpu_tuning_cache or None)
        autotune.ensure_compile_cache(mode=config.tpu_compile_cache)
        # process-wide compiled-step registry (ops/step_cache.py):
        # eligible boosters share ONE jitted training step per geometry
        step_cache.configure(config.tpu_step_cache, config.tpu_row_bucket)
        # ... and its serving twin (ops/predict_cache.py): stacked
        # predict dispatch keyed by explicit geometry, online batches
        # padded to serve buckets
        from ..ops import predict_cache
        predict_cache.configure(config.tpu_predict_cache,
                                config.tpu_serve_bucket)
        # multi-host cluster (parallel/cluster.py): adopt an already-
        # initialized jax.distributed runtime (the elastic worker
        # bootstraps BEFORE dataset construction; embedders may too) so
        # the placement seams below know the mesh spans processes.
        # Single-process runs return immediately. This runs BEFORE the
        # obs daemons below: their rank-dependent decisions — export
        # path suffixing, the rank-0-only HTTP bind, trace/reqlog rank
        # stamping (obs/identity.py) — need the topology resolved
        from ..parallel import cluster
        cluster.initialize_from_config(config)
        # streaming telemetry (obs/): the span tracer and the live
        # metrics exporter are process-global daemons — the first
        # booster with the knobs set starts them, every later one
        # (each sliding window's fresh booster) joins
        from ..obs import export as obs_export
        from ..obs import flight as obs_flight
        from ..obs import slo as obs_slo
        obs_trace.ensure_from_config(config)
        obs_export.ensure_from_config(config)
        # serving observability (obs/): the request-scoped wide-event
        # log, the SLO/error-budget engine the exporter thread
        # evaluates, and the always-on flight recorder — same
        # first-starts, later-joins discipline as the daemons above
        obs_reqlog.ensure_from_config(config)
        obs_slo.ensure_from_config(config)
        obs_flight.ensure_from_config(config)
        # deterministic fault injection (utils/faults.py): the
        # tpu_faults knob arms the recovery drills' injection points
        from ..utils import faults
        faults.configure_from_config(config)
        self.objective = objective
        self.training_metrics = list(training_metrics)
        self.iter_ = 0
        self.num_class = config.num_class
        self.shrinkage_rate = config.learning_rate
        self.num_tree_per_iteration = (
            objective.num_model_per_iteration if objective else config.num_class)
        self.max_feature_idx = train_data.num_total_features - 1
        self.feature_names = list(train_data.feature_names)
        self.feature_infos = train_data.feature_infos()

        n = train_data.num_data
        self._n = n
        self._meta = train_data.feature_meta()
        # fresh init: score buffers are rebuilt below, so _setup_grower
        # must not freeze shape decisions to a previous dataset's
        # (reset_parameter, which keeps the buffers, re-enters with
        # _scores live and DOES freeze them)
        self._scores = None
        self._setup_grower()
        # feature-major device layout [F, N] (ops/hist_wave.py); EFB
        # bundles share columns (io/efb.py)
        host_bins = (train_data.bundled_bins if self._use_bundles
                     else train_data.bins)
        dev_bins = (train_data.bins_t_dev
                    if host_bins is None and not self._use_bundles
                    else None)
        if dev_bins is not None:
            # streamed ingest (io/ingest.py): the bins are already
            # device-resident in the grower's [F, N] layout — pad and
            # nibble-pack on device; no host matrix ever existed. A
            # sharded ingest already carries (-n) % D zero-bin pad
            # columns; only the difference up to this learner's row
            # alignment is padded here (and surplus pad is sliced off
            # if a re-init changed the learner mode).
            bins_t = dev_bins
            ingest_pad = getattr(train_data, "bins_t_dev_pad", 0)
            extra = self._pad_rows - ingest_pad
            if extra > 0:
                if self._mesh is not None and ingest_pad:
                    # adoption missed (ingest's alignment guess vs the
                    # tuned chunk): one-time full-matrix re-layout
                    log.info("sharded ingest pad %d < grower pad %d: "
                             "re-padding the mesh-resident bins once "
                             "at init", ingest_pad, self._pad_rows)
                bins_t = jnp.pad(bins_t, ((0, 0), (0, extra)))
            elif extra < 0:
                bins_t = bins_t[:, :self._n + self._pad_rows]
            if self._pad_features:
                bins_t = jnp.pad(bins_t,
                                 ((0, self._pad_features), (0, 0)))
            self._num_bin_rows = bins_t.shape[0]
            if self._grower_cfg.packed4:
                bins_t = self._pack4_dev(bins_t)
        else:
            bins_t = np.ascontiguousarray(host_bins.T)
            if bins_t.dtype == np.uint16:
                # device kernels take uint8 or int32; the uint16 tier
                # only sizes host storage (io/dataset.py bin_dtype)
                bins_t = bins_t.astype(np.int32)
            if self._pad_rows:
                bins_t = np.pad(bins_t, ((0, 0), (0, self._pad_rows)))
            if self._pad_features:
                bins_t = np.pad(bins_t,
                                ((0, self._pad_features), (0, 0)))
            self._num_bin_rows = bins_t.shape[0]
            if self._grower_cfg.packed4:
                # 4-bit tier: two features per HBM byte (low nibble =
                # even feature). The grower's kernels unpack in VMEM;
                # every OTHER consumer of the training bins
                # (replay_partition in early-stop trimming, continued
                # training, refit) must go through
                # _train_bins_unpacked().
                bins_t = self._pack4_host(bins_t)
                log.info("4-bit packed bins: %.1f MB HBM "
                         "(vs %.1f MB unpacked)",
                         bins_t.nbytes / 1e6, 2 * bins_t.nbytes / 1e6)
        with timing.phase("init/upload_bins") as ph:
            # grower-facing matrix: train rows (+ alignment) with every
            # valid set's rows appended as weight-0 passengers (see
            # _rebuild_grower_bins); no valids yet at init. The train
            # part is always the first _train_width columns — kept as
            # a slice view, not a second resident copy. The watch
            # blocks at phase exit so upload/ingest device time is
            # attributed here, not to the first training iteration.
            # Sharded learners place the matrix under the mesh's
            # NamedSharding HERE, once — the jitted step then sees
            # inputs already laid out as its shard_map wants them and
            # never pays a per-iteration reshard.
            self._bins_dev = ph.watch(self._place_bins(bins_t))
        if isinstance(bins_t, np.ndarray):
            # host->device bulk upload (the streamed-ingest path never
            # builds a host matrix, so nothing to count there)
            from ..obs import registry as obs
            obs.counter("transfer/h2d_bins_bytes").add(int(bins_t.nbytes))
            obs.counter("transfer/h2d_uploads").add(1)
        self._train_width = bins_t.shape[1]
        # sparse histogram tier: device coordinate planes, bucketed so
        # same-geometry sparse boosters (the sliding-window pattern)
        # share one compiled step (ops/step_cache.py bucket_entries)
        self._sparse_dev = (self._build_sparse_planes()
                            if self._grower_cfg.sparse_hist else None)
        self._valid_row_slices: List[tuple] = []
        self._n_total = self._n + self._pad_rows
        self._full_mask_dev = self._place_rows(np.concatenate(
            [np.ones(self._n, np.float32),
             np.zeros(self._pad_rows, np.float32)]))
        self._init_scores()
        self._bagging_rng = np.random.default_rng(config.bagging_seed)
        self._feature_rng = np.random.default_rng(config.feature_fraction_seed)
        self._label_np = (train_data.metadata.label
                          if train_data.metadata.label is not None
                          else np.zeros(n, np.float32))
        self._valid_bins_dev: List[jax.Array] = []
        self._stop_check_interval = max(1, config.tpu_stop_check_interval)
        self._dispatch_sync_interval = config.tpu_dispatch_sync_interval
        self._stopped = False
        # per-run eval-value history ((iteration, dataset, metric,
        # value) tuples, global iteration numbering) — part of the
        # checkpoint bundle so a resumed run's bookkeeping matches the
        # uninterrupted run's (utils/checkpoint.py)
        self._eval_history: List[tuple] = []
        # number of leading iteration-groups already verified productive,
        # so each periodic stop check scans only the new tail
        self._clean_groups = 0
        # fused-step state (see _get_step_fn)
        self._step_key = None
        self._zero_bias = jnp.zeros(self.num_tree_per_iteration,
                                    jnp.float32)
        self._dummy_gh = jnp.zeros((1, 1), jnp.float32)
        self._dummy_key = jax.random.PRNGKey(0)
        self._fmask_cache = None
        # shared-step arguments (ops/step_cache.py): the row-validity
        # mask distinguishing real rows from bucket-pad rows, and the
        # per-booster aux pytree built lazily on first step build
        rv = np.zeros(self._n_score, bool)
        rv[:self._n] = True
        self._rvalid_dev = self._place_step_rows(rv)
        self._step_dispatched = False

    def _setup_grower(self):
        cfg = self.config
        hp = SplitParams(
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            max_delta_step=cfg.max_delta_step,
            min_data_in_leaf=float(cfg.min_data_in_leaf),
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=cfg.min_gain_to_split,
            max_cat_to_onehot=cfg.max_cat_to_onehot,
            max_cat_threshold=cfg.max_cat_threshold,
            cat_l2=cfg.cat_l2, cat_smooth=cfg.cat_smooth,
            min_data_per_group=float(cfg.min_data_per_group),
            has_cat=any(m.bin_type == 1
                        for m in self.train_data.mappers))

        # distributed learner selection (tree_learner.cpp:9-33 analog):
        # tree_learner = serial|feature|data|voting over the device mesh
        from ..parallel.learners import (make_grower_for_mode,
                                         training_mesh)
        mode = cfg.tree_learner
        mesh = None
        if mode != "serial":
            # same policy sharded ingest used (learners.training_mesh),
            # so the bins are already under this exact mesh
            mesh = training_mesh(cfg)
            if mesh is None:
                log.warning("tree_learner=%s requested but only one device"
                            " is available; falling back to serial", mode)
                mode = "serial"
        self._mesh = mesh
        self._learner_mode = mode
        D = mesh.devices.size if mesh is not None else 1
        # EFB rides the histogram seam (bundle columns in, member
        # histograms out) and the meta-driven partition decode, which
        # compose with the serial grower, the row-sharded data/voting
        # learners, AND feature-parallel (where the device slice is of
        # BUNDLE columns; each device expands its slice to its members'
        # histograms and the election runs on the usual global argmax).
        self._use_bundles = (self.train_data.bundles is not None
                             and mode in ("serial", "data", "voting",
                                          "feature"))

        f = max(self.train_data.num_features, 1)
        self._pad_rows = 0
        self._pad_features = 0
        # fresh per-feature metadata each entry: reset_parameter
        # re-enters this method, and re-padding an already-padded
        # self._meta would corrupt the pad (it also picks up
        # monotone/penalty changes from the new config)
        meta = self.train_data.feature_meta()
        self._meta = meta

        # wave size: leaves split per device step (ops/wave_grower.py);
        # 0 = auto. Capped by the Pallas channel budget AND kept a
        # multiple of 8: weight blocks concatenate on the sublane axis,
        # and misaligned 25-row pieces cost ~15x in relayout shuffles
        # (measured 1.7s vs 83ms per tree at 1M rows). hi/lo f32-grade
        # accumulation (tpu_use_dp) needs 5W <= 128 -> W = 24; single
        # bf16 fused needs 4W <= 128 -> W = 32.
        quant = cfg.tpu_quantized_hist
        # sparse histogram tier (config.tpu_sparse, io/sparse.py):
        # wave histograms scatter over the dataset's retained nnz
        # coordinates instead of the dense one-hot pass. Structural
        # gates here (serial learner, no EFB bundles, coordinates
        # present); the (density, exactness) rule is the autotuner's
        # (ops/autotune.py tune_hist_tier). Decided BEFORE the
        # count-proxy gate — the tiers are mutually exclusive.
        td_s = self.train_data
        sparse_tier = False
        if (getattr(td_s, "sparse_coords", None) is not None
                and mode == "serial" and not self._use_bundles):
            from ..ops.autotune import tune_hist_tier
            sparse_tier = tune_hist_tier(
                requested=cfg.tpu_sparse,
                density=td_s.sparse_density or 0.0,
                nnz=td_s.sparse_nnz,
                F=max(td_s.num_features, 1),
                B=max(td_s.max_bin_global, 2), W=0, quant=quant)
        elif (cfg.tpu_sparse == 1
              and getattr(td_s, "sparse_density", None) is not None):
            log.warning("tpu_sparse=1 needs the serial tree learner "
                        "without EFB bundles and a CSR-constructed "
                        "train set carrying coordinates; using the "
                        "dense histogram tier")
        if (getattr(self, "_scores", None) is not None
                and hasattr(self, "_grower_cfg")):
            # reset_parameter re-entry: the coordinate planes were
            # built (or not) at init — a flipped knob cannot
            # materialize them mid-life
            sparse_tier = self._grower_cfg.sparse_hist
        # count-proxy (see config.tpu_count_proxy): int8-only, needs the
        # fused kernel's default seams — serial/data modes, no EFB
        # bundles, no forced splits (voting reads LOCAL count sums in
        # its election, which proxy's global synthesis would corrupt)
        # (categorical excluded: _categorical_tables derives right-side
        # counts as num_data - left, which would turn the proxy's lower
        # bounds into over-estimates)
        proxy = (quant and mode in ("serial", "data")
                 and not self._use_bundles
                 and not cfg.forcedsplits_filename
                 and not hp.has_cat
                 and not sparse_tier
                 and cfg.tpu_count_proxy != 0)
        if cfg.tpu_count_proxy == 1 and not proxy:
            log.warning("tpu_count_proxy needs tpu_quantized_hist with "
                        "tree_learner serial/data, no EFB bundles, no "
                        "forced splits and no categorical features; "
                        "using exact counts")
        if proxy and cfg.tpu_count_proxy == -1:
            # auto-engaged (default -1): the mode changes tree structure
            # near the min_data_in_leaf gate (per-bin counts become
            # conservative lower bounds), so say so where a changed
            # model can be traced back to it
            log.info("tpu_count_proxy auto-enabled (int8 count-proxy "
                     "histograms, 64-leaf waves): per-bin counts are "
                     "conservative lower bounds for the "
                     "min_data_in_leaf gate; set tpu_count_proxy=0 for "
                     "exact counts")
        # 4-bit packed HBM bins: ride the proxy tier OR the hi/lo
        # exact tier (the kernels' nibble unpack is channel-layout
        # independent, so max_bin <= 16 datasets keep half-size HBM
        # bins under exact semantics too). Forced splits excluded —
        # the forced prefix reads unpacked bins (ops/wave_grower.py).
        packed4_exact = (not quant and cfg.tpu_use_dp
                         and mode in ("serial", "data")
                         and not self._use_bundles and not sparse_tier
                         and not cfg.forcedsplits_filename)
        packed4 = ((proxy or packed4_exact)
                   and self.train_data.max_bin_global <= 16
                   and cfg.tpu_packed_bins != 0)
        exact_variant = "hilo5"
        if quant and proxy:
            precision, w_cap = "int8", 64    # 2ch (count-proxy) cap 64
            hp = hp._replace(count_lb=True)  # conservative min_data gate
        elif quant:
            precision, w_cap = "int8", 40    # 3ch cap 42, 8-aligned 40
        elif cfg.tpu_use_dp:
            # exact tier: the hi/lo channel layout (and with it the
            # wave-width cap — passes per tree) is an autotuned choice
            # per (F, B, device) among the bit-equivalent variants of
            # ops/hist_wave.py (tune_exact_tier). Reduced-channel
            # layouts need the default kernel seams, so feature/voting
            # learners, EFB bundles and the sparse tier keep "hilo5".
            # "hilo3" fuses the hess plane with the count plane, which
            # is only sound when hessians are identically 1 and rows
            # unweighted (the L1/L2 family without weights; GOSS
            # amplifies hessians, custom gradients are unknowable) —
            # see the train_one_iter guard for the custom-grad corner.
            precision = "highest"
            if (mode in ("serial", "data") and not self._use_bundles
                    and not sparse_tier):
                from ..ops.autotune import (EXACT_TIER_CAPS,
                                            tune_exact_tier)
                obj = self.objective
                const_h = bool(
                    obj is not None
                    and getattr(obj, "is_constant_hessian", False)
                    and cfg.boosting_type() == "gbdt")
                td_e = self.train_data
                host_b = td_e.bins
                exact_variant = tune_exact_tier(
                    F=max(td_e.num_features, 1),
                    B=max(td_e.max_bin_global, 2),
                    n_rows=self._n,
                    constant_hessian=const_h,
                    any_cat=bool(hp.has_cat),
                    bins_bytes=(1 if (host_b.dtype == np.uint8
                                      if host_b is not None
                                      else td_e.max_bin_global <= 256)
                                else 4),
                    requested=cfg.tpu_exact_tier)
                w_cap = EXACT_TIER_CAPS[exact_variant]
            else:
                w_cap = 24
        else:
            precision, w_cap = "default", 32
        W = cfg.tpu_wave_size or w_cap
        if W > w_cap:
            log.warning("tpu_wave_size=%d exceeds the Pallas lane cap for "
                        "this precision; clamping to %d", W, w_cap)
        W = max(1, min(W, w_cap, max(cfg.num_leaves, 2) - 1))

        # effective Pallas row chunk (must match the WaveGrowerConfig
        # chunk below): rows are padded to a chunk multiple so the wave
        # kernels never re-pad the [F, N] bins — an XLA pad there is a
        # full-matrix copy per wave pass (~1 ms at the HIGGS shape,
        # x11 passes/iter). tpu_hist_chunk=0 routes the choice through
        # the kernel autotuner (ops/autotune.py): first encounter of
        # this (kernel, features, bins, tier, device) shape times a
        # small VMEM-feasible candidate set and persists the winner;
        # off-TPU the measured per-tier default is used untouched.
        # compiled-step registry eligibility decides shape policy from
        # here on: eligible boosters pad the histogram bin axis to a
        # power-of-two bucket (step_cache.bucket_bins) so boosters whose
        # OBSERVED max bin counts differ — every sliding window of the
        # lrb.py workload — still share one compiled step. Padded
        # columns are inert: no bin value reaches them and the split
        # finder masks per-feature via the traced meta.num_bin.
        from ..ops import step_cache
        prev_elig = getattr(self, "_cache_eligible", None)
        self._cache_eligible = self._step_cache_eligible(mode)
        if (prev_elig is not None
                and getattr(self, "_scores", None) is not None):
            # mid-life reset_parameter cannot switch step
            # implementations: the score/bins widths are frozen to the
            # live device buffers below, and the legacy closure cannot
            # consume a bucketed width (nor the shared step an exact
            # one) — a flipped knob only affects future boosters
            self._cache_eligible = prev_elig
        from ..obs import registry as obs
        # eligibility split by objective family: which production
        # workloads actually ride the registry vs fall back to the
        # per-booster closure (run reports + Prometheus export)
        family = (self.objective.name if self.objective is not None
                  else "none")
        verdict = "eligible" if self._cache_eligible else "ineligible"
        # bounded-cardinality: family is an in-tree objective class
        # name ("none" for custom-gradient boosters), verdict one of
        # eligible/ineligible
        obs.counter(f"step_cache/{verdict}/{family}").add(1)
        B_hist = max(self.train_data.max_bin_global, 2)
        if self._cache_eligible:
            B_hist = step_cache.bucket_bins(B_hist, cfg.tpu_row_bucket)
            # the FEATURE axis is data-dependent too (the dataset
            # excludes trivial columns, so a 53-column window sample
            # can surface 51 features and the next 52): bucket F to a
            # multiple of 8 with trivial pad features — num_bin=1
            # yields zero split candidates and the fmask pads False,
            # exactly the feature-parallel mode's proven pad scheme
            self._pad_features = (-f) % 8
        if cfg.tpu_hist_chunk > 0:
            kchunk = cfg.tpu_hist_chunk
        else:
            from ..ops import autotune
            td = self.train_data
            bundled = self._use_bundles
            host_bins = td.bundled_bins if bundled else td.bins
            kchunk = autotune.tune_hist_chunk(
                # fused-kernel eligibility mirrors wave_grower's
                # default-seams rule: serial/data without bundles
                fused=not bundled and mode in ("serial", "data"),
                F=(len(td.bundles) if bundled
                   else max(td.num_features, 1) + self._pad_features),
                B=(max(td.bundle_width, 2) if bundled else B_hist),
                W=W, precision=precision, count_proxy=proxy,
                packed4=packed4, any_cat=bool(hp.has_cat),
                variant=exact_variant,
                bins_bytes=(1 if (host_bins.dtype == np.uint8
                                  if host_bins is not None
                                  else td.max_bin_global <= 256)
                            else 4),
                # per-device rows: only data/voting shard rows across
                # the mesh (rounded UP — padding below aligns shards
                # to a chunk multiple, and the int8 overflow filter
                # must see the padded worst case, not floor(n/D));
                # serial and feature-parallel kernels see every row
                n_rows=(-(-self._n // D) if mode in ("data", "voting")
                        else self._n))
        if mode in ("data", "voting"):
            self._pad_rows = (-self._n) % D
            if self._n >= 4 * D * kchunk:
                # large shards: chunk-align each shard's rows too (the
                # per-shard fused kernel re-pads otherwise); small test
                # datasets skip this (padding would dwarf the data)
                self._pad_rows = (-self._n) % (D * kchunk)
            ing = getattr(self.train_data, "bins_t_dev_pad", 0)
            if ing > self._pad_rows:
                unit = step_cache.shard_align_unit(self._n, D, kchunk)
                if (self._n + ing) % unit == 0:
                    # sharded ingest already padded wider (32k-aligned
                    # shards) AND its width satisfies this learner's
                    # alignment — adopt it wholesale: the matrix is
                    # mesh-resident at that width, and re-padding
                    # would reshard every shard boundary
                    self._pad_rows = ing
        elif mode == "serial":
            from ..utils.device import backend_kind
            if backend_kind() in ("tpu", "gpu"):
                # both Pallas kernel families pad rows to a chunk
                # multiple internally — aligning up front avoids the
                # per-step re-pad
                self._pad_rows = (-self._n) % kchunk
        # alignment unit the row padding above respects — the bucketed
        # score width must stay a multiple of it (even shards for the
        # data/voting learners, chunk-aligned rows for the accelerator
        # kernels)
        if mode in ("data", "voting"):
            unit = step_cache.shard_align_unit(self._n, D, kchunk)
        elif mode == "serial":
            from ..utils.device import backend_kind
            unit = kchunk if backend_kind() in ("tpu", "gpu") else 1
        else:
            unit = 1
        self._row_align_unit = unit
        # compiled-step registry (ops/step_cache.py): eligible boosters
        # bucket the score-block width so boosters whose row counts
        # land in the same bucket share ONE compiled step; the bins
        # matrix widens to at least that width. Ineligible
        # configurations keep exact shapes (n_score == n), as does the
        # f32 data-parallel learner: bucketing moves the row->shard
        # boundaries, which regroups the f32 histogram/root psums and
        # drifts the last bit — the quantized path's integer wire is
        # grouping-invariant, so it buckets freely. Exact-shape cached
        # boosters still share steps between same-N runs.
        prev_ns = getattr(self, "_n_score", None)
        self._n_score = self._n
        if self._cache_eligible and (mode == "serial" or quant):
            ns = step_cache.bucket_rows(self._n, unit,
                                        cfg.tpu_row_bucket)
            local = ns // (D if mode in ("data", "voting") else 1)
            if quant and 127 * local >= 2 ** 31:
                # bucket pad would push the padded shard past the int8
                # kernels' int32 histogram-sum bound: keep exact shapes
                # (the registry still shares between same-N boosters)
                ns = self._n
            self._n_score = max(ns, self._n)
        if prev_ns is not None and getattr(self, "_scores",
                                           None) is not None:
            # reset_parameter re-entry: the score/rvalid widths were
            # allocated at init and are frozen — a changed bucket
            # decision must not orphan the live buffers
            self._n_score = prev_ns
        self._pad_rows = max(self._pad_rows,
                             self._n_score - self._n)
        if mode == "feature" and not self._use_bundles:
            self._pad_features = (-f) % D
        if (prev_ns is not None
                and getattr(self, "_scores", None) is not None
                and getattr(self, "_f_pad", None) is not None):
            # re-entry: the [F_pad, N] bins matrix is device-resident
            # at the width chosen at init — a changed pad decision
            # (e.g. reset_parameter flipping a step-cache knob) must
            # not orphan it
            self._pad_features = self._f_pad - f
        if self._pad_features:
            pad = self._pad_features
            meta = type(meta)(
                num_bin=np.concatenate(
                    [meta.num_bin, np.ones(pad, np.int32)]),
                missing_type=np.concatenate(
                    [meta.missing_type, np.zeros(pad, np.int32)]),
                default_bin=np.concatenate(
                    [meta.default_bin, np.zeros(pad, np.int32)]),
                monotone=np.concatenate(
                    [meta.monotone, np.zeros(pad, np.int32)]),
                penalty=np.concatenate(
                    [meta.penalty, np.ones(pad, np.float32)]),
                is_cat=np.concatenate(
                    [np.broadcast_to(np.asarray(meta.is_cat,
                                                np.int32), (f,)),
                     np.zeros(pad, np.int32)]))
            self._meta = meta
        self._n_pad = self._n + self._pad_rows
        self._f_pad = f + self._pad_features

        # quantized histogram reduction (tpu_quantized_psum): on the
        # data-parallel path the wave-histogram psum carries the RAW
        # int32 quantized representation and dequantizes after the
        # collective — exact integer addition on the wire and, with the
        # count-proxy tier, a 2-channel payload. Needs the default
        # seams (no EFB hist_fn) and global scales (already pmax'd);
        # the int-vs-f32 wire choice is autotuned on real meshes
        # (ops/autotune.py tune_hist_psum).
        quant_psum = False
        if (quant and mode == "data" and mesh is not None
                and not self._use_bundles):
            from ..ops.autotune import tune_hist_psum
            quant_psum = tune_hist_psum(
                # the PADDED axes: that is the [W, F, B, C] block the
                # psum actually carries (F pads to /8 when eligible)
                mesh=mesh, W=W, F=self._f_pad,
                B=B_hist,
                channels=2 if proxy else 3,
                n_rows_global=self._n_pad,
                requested=cfg.tpu_quantized_psum)
        elif cfg.tpu_quantized_psum == 1:
            log.warning("tpu_quantized_psum=1 needs tpu_quantized_hist "
                        "with tree_learner=data on a multi-device mesh "
                        "and no EFB bundles; using the f32 reduction")

        # packed wire + overlap slots (tpu_psum_wire / tpu_async_psum):
        # both arms live in the grower config so the step-cache
        # geometry key separates programs compiled for different
        # wire/slot choices, and both are bit-identical to the legacy
        # collective (parallel/learners.py make_hist_reduce)
        psum_wire = "int32"
        psum_slots = 1
        if mode == "data" and mesh is not None:
            from ..ops.autotune import (tune_hist_psum_async,
                                        tune_psum_wire)
            if quant_psum:
                psum_wire = tune_psum_wire(
                    n_rows_global=self._n_pad,
                    requested=cfg.tpu_psum_wire)
            elif cfg.tpu_psum_wire == 1:
                log.warning("tpu_psum_wire=1 needs the quantized psum "
                            "(tpu_quantized_psum) active; the f32 "
                            "wire cannot be narrowed exactly")
            psum_slots = tune_hist_psum_async(
                mesh=mesh, W=W, F=self._f_pad, B=B_hist,
                channels=2 if proxy else 3,
                wire=psum_wire if quant_psum else "f32",
                requested=cfg.tpu_async_psum)
        elif cfg.tpu_async_psum == 1:
            log.warning("tpu_async_psum=1 needs tree_learner=data on a "
                        "multi-device mesh; the serial histogram has "
                        "no collective to overlap")

        from ..ops.autotune import tune_hist_route
        gcfg = WaveGrowerConfig(
            num_leaves=max(cfg.num_leaves, 2),
            # >= 2 so the per-feature split scan is never empty (the
            # all-trivial-features case has one dummy single-bin feature)
            num_bins=B_hist,
            wave_size=W,
            max_depth=cfg.max_depth,
            # autotuned row chunk (ops/autotune.py; defaults: 16384
            # int8 / 8192 otherwise). kchunk (computed above) kept in
            # sync for row padding.
            chunk=kchunk,
            hp=hp,
            precision=precision,
            exact_variant=exact_variant,
            forced=self._parse_forced_splits(),
            count_proxy=proxy,
            packed4=packed4,
            quant_psum=quant_psum,
            psum_wire=psum_wire,
            psum_slots=psum_slots,
            sparse_hist=sparse_tier,
            # resolved per device kind so the step-cache geometry key
            # (which hashes this config) separates programs compiled
            # for different kernel families — a GPU-route step never
            # serves a CPU restore of the same geometry
            route=tune_hist_route(
                fused_eligible=not self._use_bundles
                and not sparse_tier))
        self._grower_cfg = gcfg
        hist_fn = None
        efb_feature = None
        if self._use_bundles:
            # EFB: the wave kernel runs over BUNDLE columns, then member
            # histograms are reconstructed (io/efb.py docstring)
            from ..io.efb import expand_bundle_histogram
            from ..ops.hist_wave import wave_histogram
            td = self.train_data
            Bb = max(td.bundle_width, 2)
            mb = jnp.asarray(td.member_bundle)
            mo = jnp.asarray(td.member_offset)
            nb_m = jnp.asarray(meta.num_bin)
            db_m = jnp.asarray(meta.default_bin)
            B_out = gcfg.num_bins
            if mode == "feature":
                # feature-parallel slices BUNDLE columns; the learner
                # builds its own per-device slice-and-expand seam
                efb_feature = (td.member_bundle, td.member_offset,
                               meta.num_bin, meta.default_bin, Bb,
                               B_out, td.bundled_bins.shape[1])
            else:
                def hist_fn(bins_t, g, h, leaf_ids, wave_leaves,
                            gh_scale=None):
                    bh = wave_histogram(bins_t, g, h, leaf_ids,
                                        wave_leaves,
                                        num_bins=Bb, chunk=gcfg.chunk,
                                        use_pallas=gcfg.use_pallas,
                                        precision=gcfg.precision,
                                        gh_scale=gh_scale)
                    return expand_bundle_histogram(bh, mb, mo, nb_m,
                                                   db_m, B_out)
        self._grower = make_grower_for_mode(
            mode, gcfg, meta, mesh, self._f_pad, cfg.top_k,
            hist_fn=hist_fn, efb_feature=efb_feature)
        self._step_key = None       # grower changed: rebuild fused step

    def _step_cache_eligible(self, mode: str) -> bool:
        """True when this booster's fused step can be served by the
        process-wide registry (ops/step_cache.py): serial/data learner
        without EFB bundles, an objective with a pure gradient seam
        (or none — custom gradients are traced arguments anyway), and
        a boosting variant whose step is the standard one. Reads THIS
        booster's config knob, not the module global — another
        booster's init must not flip a live booster's shape policy."""
        if self.config.tpu_step_cache == 0 or not self._step_cache_ok:
            return False
        if self._use_bundles or mode not in ("serial", "data"):
            return False
        if mode == "data":
            # externally-injected collectives (LGBM_NetworkInitWith-
            # Functions) are arbitrary callables the geometry key
            # cannot cover — a cached step would silently bypass the
            # injected wrapper (or serve a program traced with a
            # different one); trace per-instance instead
            from ..parallel.learners import _collective_overrides
            if _collective_overrides:
                return False
        obj = self.objective
        if obj is not None and obj.gradient_builder() is None:
            return False
        return True

    # -- sharded iteration state (data/voting over a mesh) -------------------

    @property
    def num_devices(self) -> int:
        """Devices the training step actually spans: the mesh size for
        the sharded learners, 1 for serial (public — bench/reporting
        must not reach into ``_mesh``)."""
        mesh = getattr(self, "_mesh", None)
        return int(mesh.devices.size) if mesh is not None else 1

    @property
    def learner_mode(self) -> str:
        """Resolved tree learner — may be 'serial' after a one-device
        fallback, unlike config.tree_learner (public, for reporting)."""
        return getattr(self, "_learner_mode", "serial")

    def _row_sharded(self) -> bool:
        """True when iteration state lives row-sharded over the mesh
        (data/voting): bins [F, N], scores [K, N], grad/hess/bagging
        masks and leaf ids all partition on the row axis, matching the
        shard_map specs — so the per-iteration step moves NO data
        between chips except the wave-histogram psum (and O(N)-vector
        boundary shuffles where train/valid slices cross shard edges)."""
        return (self._mesh is not None
                and self._learner_mode in ("data", "voting"))

    def _named_sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel.learners import AXIS
        spec = tuple(AXIS if s == "rows" else None for s in spec)
        return NamedSharding(self._mesh, PartitionSpec(*spec))

    def _multiprocess_mesh(self) -> bool:
        """True when the training mesh spans >1 OS process (real
        multi-host run, parallel/cluster.py): device_put cannot reach
        non-addressable devices, so every placement below switches to
        the global-array constructors. Host-side inputs stay
        HOST-GLOBAL (every rank passes the same full-length value —
        labels, masks, scores), which is what makes the seams the only
        multi-process-aware code in this class."""
        from ..parallel import cluster
        return cluster.spans_processes(getattr(self, "_mesh", None))

    def _global_put(self, x, *spec):
        from ..parallel import cluster
        from ..parallel.learners import AXIS
        return cluster.host_to_global(
            x, self._mesh, *tuple(AXIS if s == "rows" else None
                                  for s in spec))

    def _place_rows(self, x):
        """[N_total] row vector onto the mesh (P over rows), or the
        default device for serial."""
        if not self._row_sharded():
            return jnp.asarray(x)
        if self._multiprocess_mesh():
            return self._global_put(x, "rows")
        return jax.device_put(x, self._named_sharding("rows"))

    def _place_bins(self, x):
        """[F, N_total] bin matrix: feature axis replicated, row axis
        sharded. device_put of a host matrix distributes each shard
        straight to its chip; re-placing an already-matching sharded
        array (the sharded-ingest path) is a no-op. Under a
        multi-process mesh the matrix is REQUIRED to already be the
        multihost-assembled global array (io/ingest.py
        bin_matrix_multihost) — no single host holds the full matrix
        to place."""
        if not self._row_sharded():
            return jnp.asarray(x)
        if self._multiprocess_mesh():
            if not hasattr(x, "sharding"):
                raise ValueError(
                    "multi-process training needs the bin matrix "
                    "assembled by the multihost ingest "
                    "(io/distributed.py construct_multihost) — a host "
                    "matrix cannot be placed across processes")
            return x
        return jax.device_put(x, self._named_sharding(None, "rows"))

    def _place_scores(self, x):
        """[K, N] score block, row axis sharded. jax only places
        explicit shardings on evenly divisible axes, so score blocks
        whose (unpadded) row count doesn't divide the mesh stay on the
        default device — the step still computes correctly (GSPMD
        moves the [N] f32 vectors at the slice boundary), it just
        pays an O(N)-vector shuffle instead of staying shard-local.
        Production-scale row counts are D-aligned; tiny test sets may
        not be."""
        if (not self._row_sharded()
                or np.shape(x)[-1] % self.num_devices):
            return jnp.asarray(x)
        if self._multiprocess_mesh():
            return (x if hasattr(x, "sharding")
                    else self._global_put(x, None, "rows"))
        return jax.device_put(x, self._named_sharding(None, "rows"))

    def _place_step_rows(self, x):
        """Row-aligned shared-step argument ([..., n_score]: rvalid,
        padded objective aux): sharded on the row axis when the
        iteration state is, so the jitted step never reshards it."""
        x = np.asarray(x)
        if (not self._row_sharded()
                or x.shape[-1] % self.num_devices):
            return jnp.asarray(x)
        spec = ("rows",) if x.ndim == 1 else (None, "rows")
        if self._multiprocess_mesh():
            return self._global_put(x, *spec)
        return jax.device_put(x, self._named_sharding(*spec))

    def _parse_forced_splits(self) -> tuple:
        """forcedsplits_filename JSON -> BFS-ordered
        ((parent_leaf, inner_feature, bin), ...) matching the
        reference's ForceSplits leaf numbering
        (serial_tree_learner.cpp:546-701: left child keeps the parent
        leaf, right child takes the next id in application order)."""
        cfg = self.config
        if not cfg.forcedsplits_filename:
            return ()
        import collections
        import json as _json
        try:
            with open(cfg.forcedsplits_filename) as fh:
                spec = _json.load(fh)
        except (OSError, ValueError) as e:
            log.fatal(f"Cannot read forced splits file "
                      f"{cfg.forcedsplits_filename!r}: {e}")
        td = self.train_data
        out = []
        q = collections.deque([(spec, 0)])
        next_leaf = 1
        cap = max(cfg.num_leaves, 2) - 1
        while q and len(out) < cap:
            node, leaf = q.popleft()
            if not isinstance(node, dict) or "feature" not in node:
                continue
            if "threshold" not in node:
                log.fatal(f"Forced split node missing 'threshold': "
                          f"{node!r}")
            inner = td.real_to_inner.get(int(node["feature"]))
            if inner is None:
                log.warning("Forced split on unused feature %s skipped",
                            node["feature"])
                continue
            if td.mappers[inner].bin_type == 1:   # BinType.CATEGORICAL
                log.warning("Forced split on categorical feature %s is "
                            "not supported; skipped", node["feature"])
                continue
            tbin = int(td.mappers[inner].value_to_bin(
                np.asarray([float(node["threshold"])]))[0])
            out.append((leaf, int(inner), tbin))
            right_leaf = next_leaf
            next_leaf += 1
            if node.get("left"):
                q.append((node["left"], leaf))
            if node.get("right"):
                q.append((node["right"], right_leaf))
        if out:
            log.info("Applying %d forced splits per tree", len(out))
        return tuple(out)

    def _init_scores(self):
        n, k = self._n, self.num_tree_per_iteration
        ns = self._n_score
        # score block at the (possibly bucketed) width: columns past n
        # are pad rows whose gradients the step forces to exact +0.0
        # (step_cache.build_train_step rvalid mask) — their score
        # values are never read by metrics or predictions
        init = np.zeros((k, ns), np.float32)
        self._boost_from_avg_done = [False] * k
        md = self.train_data.metadata
        if md.init_score is not None:
            init[:, :n] += np.asarray(md.init_score,
                                      np.float32).reshape(k, n)
        self._scores = self._place_scores(init)
        self._valid_scores: List[jax.Array] = []

    def add_valid_data(self, valid_data: TpuDataset,
                       metrics: Sequence[Metric], name: str = "") -> None:
        self.valid_sets.append(valid_data)
        self.valid_names.append(name or f"valid_{len(self.valid_sets)}")
        self.valid_metrics.append(list(metrics))
        k, nv = self.num_tree_per_iteration, valid_data.num_data
        init = np.zeros((k, nv), np.float32)
        if valid_data.metadata.init_score is not None:
            init += np.asarray(valid_data.metadata.init_score,
                               np.float32).reshape(k, nv)
        self._valid_scores.append(self._place_scores(init))
        # replay existing model on the new valid set (bins cached on device
        # once — uploads are cheap, downloads are not)
        v_host = (valid_data.bundled_bins
                  if (self._use_bundles
                      and valid_data.bundles is not None)
                  else valid_data.bins)
        if v_host is None and valid_data.bins_t_dev is not None:
            # streamed ingest: the valid bins are already [F, N] on
            # device (a device-ingested valid set implies an unbundled
            # train set — io/dataset.py _device_ingest_ok)
            vb = valid_data.bins_t_dev
        else:
            vt = np.ascontiguousarray(v_host.T)
            from ..obs import registry as obs
            obs.counter("transfer/h2d_bins_bytes").add(int(vt.nbytes))
            obs.counter("transfer/h2d_uploads").add(1)
            vb = jnp.asarray(vt)
        self._valid_bins_dev.append(vb)
        for t_idx, rec in enumerate(self.records):
            cls = t_idx % self.num_tree_per_iteration
            leaf = replay_partition(rec, vb, self._meta)
            self._valid_scores[-1] = self._valid_scores[-1].at[cls].set(
                add_leaf_outputs(self._valid_scores[-1][cls], leaf,
                                 rec.leaf_output, 1.0))
        # future iterations: this set's rows ride the wave partition
        self._rebuild_grower_bins()

    def init_from_loaded(self, config: Config, train_data: TpuDataset,
                         objective: Optional[ObjectiveFunction],
                         training_metrics: Sequence[Metric] = ()):
        """Continued training (input_model): call after
        ``load_model_from_string``. Rebuilds device TreeRecords for the
        loaded host trees (bin-space thresholds via the new mappers) and
        replays them into the train scores, so training continues exactly
        where the loaded model stopped (boosting.cpp:30-55 +
        gbdt.cpp ResetTrainingData semantics)."""
        from ..ops.grower import TreeRecord
        loaded_models = [m for m in self.models if m is not None]
        if len(loaded_models) != len(self.models):
            log.fatal("init_from_loaded requires a fully loaded model")
        k_loaded = max(self.num_tree_per_iteration, 1)
        self.init(config, train_data, objective, training_metrics)
        if self.num_tree_per_iteration != k_loaded:
            log.fatal("num_class of input_model doesn't match config")
        L = self._grower_cfg.num_leaves
        from .tree import record_arrays_from_tree
        self.models = loaded_models
        self.records = []
        self._bump_model_gen()
        self._tree_shrinkage = [m.shrinkage if m.shrinkage else 1.0
                                for m in loaded_models]
        for t_idx, tree in enumerate(loaded_models):
            arrs = record_arrays_from_tree(
                tree, train_data.real_to_inner, train_data.mappers, L)
            rec = TreeRecord(**{k: jnp.asarray(v)
                                for k, v in arrs.items()})
            self.records.append(rec)
            cls = t_idx % self.num_tree_per_iteration
            leaf = replay_partition(rec, self._train_bins_unpacked(), self._meta)
            self._scores = self._scores.at[cls].set(add_leaf_outputs(
                self._scores[cls], leaf[:self._n_score],
                rec.leaf_output, 1.0))
        self.iter_ = len(loaded_models) // self.num_tree_per_iteration
        self._clean_groups = self.iter_
        log.info("Continuing training from iteration %d", self.iter_)

    # -- bagging (gbdt.cpp:161-243) -----------------------------------------

    def _bagging_mask(self, iteration: int) -> Optional[np.ndarray]:
        cfg = self.config
        if not (cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0):
            return None
        if iteration % cfg.bagging_freq != 0 and hasattr(self, "_bag_cache"):
            return self._bag_cache
        n = self._n
        cnt = int(n * cfg.bagging_fraction)
        idx = self._bagging_rng.choice(n, cnt, replace=False)
        mask = np.zeros(n, np.float32)
        mask[idx] = 1.0
        self._bag_cache = mask
        return mask

    @staticmethod
    def _pack4_host(bins_t: np.ndarray) -> np.ndarray:
        """Nibble-pack a [F, N] uint8 bin matrix (values <= 15): two
        features per byte, even feature in the low nibble."""
        if bins_t.shape[0] % 2:
            bins_t = np.pad(bins_t, ((0, 1), (0, 0)))
        return (bins_t[0::2] | (bins_t[1::2] << 4)).astype(np.uint8)

    @staticmethod
    def _pack4_dev(bins_t: jax.Array) -> jax.Array:
        """_pack4_host for device-resident ingest bins (same layout as
        the valid-set packing in _rebuild_grower_bins)."""
        if bins_t.shape[0] % 2:
            bins_t = jnp.pad(bins_t, ((0, 1), (0, 0)))
        return jnp.bitwise_or(bins_t[0::2],
                              jnp.left_shift(bins_t[1::2], jnp.uint8(4)))

    def _build_sparse_planes(self):
        """(codes, feat, row, zero_bins) device planes for the sparse
        histogram tier (ops/hist_wave.py wave_histogram_sparse), padded
        to the nnz bucket with sentinel entries (feature == padded F,
        dropped by every scatter). Works off host coords (the host
        scatter path) or the device planes sparse ingest assembled —
        either way the ingest's own sentinels (feature == unpadded F)
        are remapped past the padded width first."""
        from ..obs import registry as obs
        from ..ops import step_cache
        td = self.train_data
        f = max(td.num_features, 1)
        codes = self._upload_plane(td.sparse_coords[0])
        feat = self._upload_plane(td.sparse_coords[1])
        rows = self._upload_plane(td.sparse_coords[2])
        feat = jnp.where(feat >= f, jnp.int32(self._f_pad), feat)
        E = int(codes.shape[0])
        Ep = (step_cache.bucket_entries(E, self.config.tpu_row_bucket)
              if self._cache_eligible else E)
        pad = Ep - E
        if pad:
            codes = jnp.concatenate([codes, jnp.zeros(pad, jnp.int32)])
            feat = jnp.concatenate(
                [feat, jnp.full(pad, self._f_pad, jnp.int32)])
            rows = jnp.concatenate([rows, jnp.zeros(pad, jnp.int32)])
        zb = np.zeros(self._f_pad, np.int32)
        zbs = td.sparse_zero_bins
        zb[:len(zbs)] = zbs
        obs.counter("sparse/hist_tier_sparse").add(1)
        log.info("sparse histogram tier: %d coordinate entries "
                 "(bucketed to %d) over %d features", E, Ep,
                 self._f_pad)
        return (codes, feat, rows, jnp.asarray(zb))

    def _upload_plane(self, arr) -> jax.Array:
        """One sparse coordinate plane to device, delta-encoded across
        the host->device wire where tpu_psum_wire allows and the int16
        delta bound holds (io/sparse.py delta_pack_plane; 0 = legacy
        int32 transport). Reconstruction by int32 cumsum is exact, so
        the device plane is bit-identical either way."""
        if (self.config.tpu_psum_wire != 0
                and isinstance(arr, np.ndarray)):
            from ..io.sparse import delta_pack_plane
            packed = delta_pack_plane(arr)
            if packed is not None:
                base, d16 = packed
                from ..obs import registry as obs
                obs.counter("comm/wire_bytes_saved").add(2 * d16.size)
                return (jnp.int32(base)
                        + jnp.cumsum(jnp.asarray(d16).astype(jnp.int32)))
        return jnp.asarray(arr).astype(jnp.int32)

    def _step_bins(self):
        """The fused step's bins argument: the dense matrix, paired
        with the sparse coordinate planes when the sparse histogram
        tier is active (the grower unpacks the tuple)."""
        sp = getattr(self, "_sparse_dev", None)
        return self._bins_dev if sp is None else (self._bins_dev, sp)

    @property
    def _bins_train_dev(self) -> jax.Array:
        """The training columns of the grower bin matrix (valid-set
        passenger columns excluded)."""
        return self._bins_dev[:, :self._train_width]

    def _train_bins_unpacked(self) -> jax.Array:
        """Training bins as [F, N] — transient nibble-unpack when the
        4-bit packed tier is active (replay_partition and friends index
        per-feature rows; only the grower kernels understand packed
        bytes)."""
        if not self._grower_cfg.packed4:
            return self._bins_train_dev
        b = self._bins_train_dev
        lo = jnp.bitwise_and(b, jnp.uint8(15))
        hi = jnp.right_shift(b, jnp.uint8(4))
        return jnp.stack([lo, hi], axis=1).reshape(
            -1, b.shape[1])[:self._num_bin_rows]

    def _rebuild_grower_bins(self) -> None:
        """Append every valid set's bin columns to the grower's bin
        matrix as weight-0 passenger rows. The wave kernels then hand
        each valid row its leaf id in the SAME fused partition pass
        that places the training rows — the per-iteration valid-score
        update becomes a slice + leaf-output gather. The alternative
        (replaying num_leaves-1 splits per tree inside the step, the
        reference's per-row traversal transliterated) measured ~2.3x
        the whole iteration cost at 11M train + 500k valid rows;
        passenger rows cost ~Nv/N extra kernel time instead.

        Masked rows cannot influence training: their g/h/bagging mask
        are zero, histogram counts ride the mask channel, and the
        count-proxy's exact per-leaf counts only count in-bag rows."""
        base = self._bins_train_dev
        parts = [base]
        self._valid_row_slices = []
        off = base.shape[1]
        for vb in self._valid_bins_dev:
            nv = vb.shape[1]
            if self._pad_features:
                vb = jnp.pad(vb, ((0, self._pad_features), (0, 0)))
            if self._grower_cfg.packed4:
                vb = self._pack4_dev(vb)
            self._valid_row_slices.append((off, nv))
            parts.append(vb.astype(base.dtype))
            off += nv
        # re-align the combined width, mirroring the init-time row-
        # padding policy EXACTLY: chunk alignment only where init would
        # have applied it (serial on TPU; big data/voting shards) —
        # small CPU/test datasets must not balloon to a 16k multiple
        from ..utils.device import on_tpu
        mode = self._learner_mode
        D = self._mesh.devices.size if self._mesh is not None else 1
        from ..ops.autotune import DEFAULT_HIST_CHUNK
        kchunk = self._grower_cfg.chunk or DEFAULT_HIST_CHUNK
        align = 1
        if mode in ("data", "voting"):
            align = D * kchunk if off >= 4 * D * kchunk else D
        elif mode == "serial" and on_tpu():
            align = kchunk
        tail = (-off) % align
        if tail:
            parts.append(jnp.zeros((base.shape[0], tail), base.dtype))
        self._n_total = off + tail
        # re-place under the mesh sharding: passenger columns arrive on
        # one device, so the combined matrix reshards ONCE here instead
        # of every iteration
        self._bins_dev = self._place_bins(
            parts[0] if len(parts) == 1
            else jnp.concatenate(parts, axis=1))
        # masks/scores pad to the new total
        self._full_mask_dev = self._place_rows(jnp.concatenate(
            [jnp.ones(self._n, jnp.float32),
             jnp.zeros(self._n_total - self._n, jnp.float32)]))
        self._step_key = None        # step closure holds the slices

    def _feature_mask(self) -> np.ndarray:
        cfg = self.config
        # >= 1: the all-trivial-features case has one dummy feature
        f = max(self.train_data.num_features, 1)
        mask = np.ones(f, bool)
        if cfg.feature_fraction < 1.0:
            used = max(1, int(f * cfg.feature_fraction))
            sel = self._feature_rng.choice(f, used, replace=False)
            mask = np.zeros(f, bool)
            mask[sel] = True
        return mask

    def _feature_mask_dev(self) -> jax.Array:
        """Padded device feature mask; the all-features case is cached so
        the common path uploads nothing per iteration."""
        if self.config.feature_fraction >= 1.0:
            if self._fmask_cache is None:
                m = np.ones(max(self.train_data.num_features, 1), bool)
                if self._pad_features:
                    m = np.concatenate(
                        [m, np.zeros(self._pad_features, bool)])
                self._fmask_cache = jnp.asarray(m)
            return self._fmask_cache
        m = self._feature_mask()
        if self._pad_features:
            m = np.concatenate([m, np.zeros(self._pad_features, bool)])
        return jnp.asarray(m)

    # -- boosting (gbdt.cpp:333-412) ----------------------------------------

    def boost_from_average(self, class_id: int) -> float:
        """BoostFromAverage (gbdt.cpp:311-330): only when the model is
        still empty and no init score was supplied."""
        cfg = self.config
        if (self.models or not cfg.boost_from_average
                or self.objective is None
                or self.train_data.metadata.init_score is not None):
            return 0.0
        if self.objective.name in (
                "regression", "regression_l1", "quantile", "huber",
                "fair", "mape", "binary", "cross_entropy",
                "poisson", "gamma", "tweedie"):
            init = self.objective.boost_from_score(class_id)
            if init != 0.0:
                self._scores = self._scores.at[class_id].add(init)
                for i in range(len(self._valid_scores)):
                    self._valid_scores[i] = \
                        self._valid_scores[i].at[class_id].add(init)
                log.info("Start training from score %g", init)
            return init
        return 0.0

    # -- shared fused step (ops/step_cache.py) -------------------------------

    def _pad_step_aux(self, aux):
        """Host aux pytree -> device: every array leaf's LAST axis is
        the row axis (objectives/objective.py seam contract); pad it
        from n to the bucketed n_score with zeros and place it under
        the step's row sharding. Leaves under a dict key starting with
        ``_`` are NOT row-shaped (lambdarank's padded query tables):
        they are placed replicated, unpadded."""
        if aux is None:
            return None
        if isinstance(aux, dict):
            return {k: (self._place_step_raw(v) if k.startswith("_")
                        else self._pad_step_aux(v))
                    for k, v in aux.items()}
        a = np.asarray(aux)
        pad = self._n_score - a.shape[-1]
        if pad:
            a = np.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, pad)])
        return self._place_step_rows(a)

    def _place_step_raw(self, x):
        """Non-row-shaped shared-step aux leaf (the ``_``-prefixed seam
        keys): replicated over the mesh when one is live, so the jitted
        step never reshards it."""
        if x is None:
            return None
        x = np.asarray(x)
        if self._mesh is None:
            return jnp.asarray(x)
        spec = (None,) * x.ndim
        if self._multiprocess_mesh():
            return self._global_put(x, *spec)
        return jax.device_put(x, self._named_sharding(*spec))

    def _step_geometry_key(self, custom: bool, obj, renew_alpha,
                           aux_dev, meta_dev) -> tuple:
        """Hashable registry key covering EVERYTHING that shapes the
        step's trace — a hit is guaranteed to be a functionally
        identical program (data flows through traced arguments)."""
        from ..ops import step_cache
        mesh_key = (None if self._mesh is None else
                    tuple(int(d.id) for d in self._mesh.devices.flat))
        bins = self._bins_dev
        return (
            "train_step",
            self.num_tree_per_iteration, self._n_score, self._n_total,
            tuple(self._valid_row_slices),
            self._learner_mode, mesh_key,
            bool(self._row_sharded()
                 and self._n_score % self.num_devices == 0),
            self._grower_cfg, self._f_pad,
            (bins.shape[0], str(bins.dtype)),
            ("custom",) if custom or obj is None else obj.static_key(),
            renew_alpha,
            # in-jit sample hook statics (hashed GOSS closes over its
            # rates; GBDT contributes a no-sample marker)
            self._sample_static_key(),
            step_cache.aux_signature(aux_dev),
            step_cache.aux_signature(
                dict(zip(type(meta_dev)._fields, meta_dev))),
            # sparse histogram tier: the flag rides _grower_cfg above;
            # the bucketed nnz plane length shapes the trace
            ("sparse", None if getattr(self, "_sparse_dev", None) is None
             else int(self._sparse_dev[0].shape[0])),
        )

    def _sample_static_key(self) -> tuple:
        """Hashable statics of the in-jit sample hook — the geometry-
        key component covering everything a REGISTRY-ELIGIBLE hook
        closes over. GBDT has no hook; hashed GOSS overrides this with
        its sampling rates (models/boosting.py)."""
        return ("nosample",)

    @staticmethod
    def _renew_aux(obj):
        """(renew_alpha, host renew-aux dict) for objectives that
        refit leaf outputs (the L1 family), else (None, None) — the
        ONE source of the label/weight plumbing for BOTH step
        routings (registry + legacy), so they cannot drift."""
        if not obj.is_renew_tree_output():
            return None, None
        lbl = (obj.trans_label if hasattr(obj, "trans_label")
               else obj.label)
        w = getattr(obj, "label_weight", None)
        if w is None:
            w = obj.weights
        return (float(obj.renew_tree_output_percentile()),
                {"label": np.asarray(lbl, np.float32),
                 "w": None if w is None else np.asarray(w, np.float32)})

    def _get_cached_step(self, custom: bool):
        """Fetch (or build once per geometry, process-wide) the shared
        fused step and bind this booster's rvalid/meta/aux arguments."""
        from ..ops import step_cache
        key_local = ("cache", custom, len(self._valid_bins_dev))
        if getattr(self, "_step_key", None) == key_local:
            return self._step_fn
        obj = self.objective
        grad_fn = (None if custom or obj is None
                   else obj.gradient_builder())
        renew_alpha = aux_renew = None
        if grad_fn is not None:
            renew_alpha, aux_renew = self._renew_aux(obj)
        aux_host = {"obj": None, "renew": aux_renew}
        if grad_fn is not None:
            aux_host["obj"] = obj.gradient_aux()
        aux_dev = self._pad_step_aux(aux_host)
        meta = self._meta
        meta_dev = type(meta)(*[jnp.asarray(x) for x in meta])
        key = self._step_geometry_key(custom, obj, renew_alpha,
                                      aux_dev, meta_dev)
        grower = self._grower
        K = self.num_tree_per_iteration

        sample_hook = self._sample_hook

        def builder():
            # the hook is registry-shareable: an eligible hook closes
            # only over config scalars, all covered by the geometry
            # key's _sample_static_key() component
            return step_cache.build_train_step(
                grower=grower, K=K, n_score=self._n_score,
                n_total=self._n_total,
                valid_slices=tuple(self._valid_row_slices),
                num_leaves=self._grower_cfg.num_leaves,
                grad_fn=grad_fn, renew_alpha=renew_alpha,
                sample_hook=sample_hook)

        shared = step_cache.get_step(key, builder)
        rvalid = self._rvalid_dev

        def stepfn(bins, scores, valid_scores, mask, fmask, shrink,
                   init_bias, g_in, h_in, prng):
            return shared(bins, scores, valid_scores, mask, fmask,
                          shrink, init_bias, g_in, h_in, prng,
                          rvalid, meta_dev, aux_dev)

        self._step_fn = stepfn
        self._step_key = key_local
        return stepfn

    def _get_step_fn(self, custom: bool):
        """ONE jitted function for a full boosting iteration.

        Everything — gradients, K tree builds, renew, shrinkage fold,
        AddBias on the stored record, train+valid score updates — runs
        as a single XLA program. This is the TPU-critical design point:
        eager op dispatch is a high-latency host<->device RPC on this
        platform (measured ~24 ms per op on the tunneled backend), and
        an un-fused iteration pays ~100 of them. Fused: one dispatch.

        Eligible configurations route to the PROCESS-WIDE registry
        (ops/step_cache.py via _get_cached_step): the step is a pure
        function of a geometry key and is compiled once per geometry,
        not once per booster. Ineligible ones get a per-instance jit of
        the SAME step body (step_cache.build_train_step with
        rvalid/meta=None — one implementation, two routings). Retraces
        only when a valid set is added or the custom-gradient mode
        flips; shrinkage/init-bias are traced arguments.
        """
        if getattr(self, "_cache_eligible", False):
            return self._get_cached_step(custom)
        # legacy per-booster closure (GOSS/EFB/feature/voting/
        # tpu_step_cache=0): SAME step body as the registry path
        # (step_cache.build_train_step — one implementation, two
        # routings), but jitted per-instance with exact row shapes:
        # rvalid=None (no bucketing pad to mask) and meta=None (the
        # grower consumes its own closure metadata, which the
        # cache-ineligible learner seams require).
        key = (custom, len(self._valid_bins_dev))
        if getattr(self, "_step_key", None) == key:
            return self._step_fn
        from ..ops import step_cache
        obj = self.objective
        K = self.num_tree_per_iteration
        if custom or obj is None:
            grad_fn = None
        else:
            # closure-gradient seam: same get_gradients the objective's
            # pure gradient_builder delegates to, so the two routes
            # cannot drift (objectives/objective.py)
            def grad_fn(scores, _aux_obj, _obj=obj):
                return _obj.get_gradients(scores)
        renew_alpha = aux_renew = None
        if grad_fn is not None:
            renew_alpha, aux_renew = self._renew_aux(obj)
        aux = {"obj": None, "renew": None}
        if aux_renew is not None:
            aux["renew"] = {k: (None if v is None else jnp.asarray(v))
                            for k, v in aux_renew.items()}
        # bins (and the aux arrays) are ARGUMENTS, not closure
        # constants: closed-over arrays embed into the lowered program,
        # and at 11M rows the 308 MB constant blows the compile-RPC
        # size limit. Valid rows ride INSIDE ``bins`` as weight-0
        # passenger rows (_rebuild_grower_bins): the grower's partition
        # hands every valid row its leaf id, so the per-iteration
        # valid-score update is a slice + leaf-output gather instead of
        # a num_leaves-deep split replay per tree.
        shared = step_cache.build_train_step(
            grower=self._grower, K=K, n_score=self._n,
            n_total=self._n_total,
            valid_slices=tuple(self._valid_row_slices),
            num_leaves=self._grower_cfg.num_leaves,
            grad_fn=grad_fn, renew_alpha=renew_alpha,
            sample_hook=self._sample_hook)

        def stepfn(bins, scores, valid_scores, mask, fmask, shrink,
                   init_bias, g_in, h_in, prng):
            return shared(bins, scores, valid_scores, mask, fmask,
                          shrink, init_bias, g_in, h_in, prng,
                          None, None, aux)

        self._step_fn = stepfn
        self._step_key = key
        return self._step_fn

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration; returns True if training should stop
        (gbdt.cpp:333-412). grad/hess: optional custom [K, N] arrays.

        Stored TreeRecords are MODEL-equivalent: their ``leaf_output``
        already carries shrinkage and (for the first iteration) the
        boost-from-average bias, exactly like the reference's
        ``Shrinkage`` + ``AddBias`` on the saved tree (gbdt.cpp:371-377).

        Entirely device-resident: ONE fused jit call per iteration, no
        device->host transfer. The "no more splits" stop is detected by
        a periodic host check (every ``tpu_stop_check_interval``
        iterations).
        """
        from ..obs import trace
        tracer = trace.active()
        if tracer is not None:
            # iteration span at the single choke point EVERY driver
            # passes through (gbdt.train, engine/Booster.update, the
            # capi/lrb per-window loop, bench) — dispatch-issue wall,
            # like the phase clocks; queued device time drains in the
            # periodic queue_drain spans
            with tracer.span("iteration", cat="iteration",
                             args={"it": self.iter_ + 1}):
                return self._train_one_iter_inner(grad, hess)
        return self._train_one_iter_inner(grad, hess)

    def _train_one_iter_inner(self, grad, hess) -> bool:
        from ..parallel import cluster
        if cluster.is_multiprocess():
            # progress stamp for the no-hang watchdog
            # (cluster.DeadlineGuard): a peer death that BLOCKS a
            # collective instead of failing it is detected as a stall
            # at this label within tpu_collective_timeout_s
            cluster.tick(f"iteration {self.iter_ + 1}")
        K = self.num_tree_per_iteration
        init_scores = [0.0] * K
        custom = grad is not None and hess is not None
        if not custom:
            if self.objective is None:
                log.fatal("No objective; pass custom grad/hess")
            for k in range(K):
                init_scores[k] = self.boost_from_average(k)
            g_in = h_in = self._dummy_gh
        else:
            if self._grower_cfg.exact_variant == "hilo3":
                from ..utils.device import on_tpu
                if on_tpu():
                    # the hilo3 kernel reads the hess plane AS the
                    # count plane — custom hessians would silently
                    # corrupt both (the XLA oracle is layout-free, so
                    # off-TPU custom gradients are unaffected)
                    log.fatal(
                        "custom grad/hess with the hilo3 exact tier: "
                        "the fused hess/count plane assumes unit "
                        "hessians; set tpu_exact_tier=hilo4 (or "
                        "hilo5) for custom-objective training")
            g_in = jnp.asarray(grad, jnp.float32).reshape(K, self._n)
            h_in = jnp.asarray(hess, jnp.float32).reshape(K, self._n)
            pad = self._n_score - self._n
            if pad:
                # bucketed step width: pad custom gradients with exact
                # zeros (the rvalid mask re-zeroes them in-step anyway)
                g_in = jnp.pad(g_in, ((0, 0), (0, pad)))
                h_in = jnp.pad(h_in, ((0, 0), (0, pad)))

        mask_np = self._bagging_mask(self.iter_)
        if mask_np is None:
            mask = self._full_mask_dev  # precomputed padded all-ones mask
        else:
            tail = self._n_total - self._n   # align pad + valid rows
            if tail:
                mask_np = np.concatenate(
                    [mask_np, np.zeros(tail, np.float32)])
            mask = self._place_rows(mask_np)
        fmask = self._feature_mask_dev()

        first_iteration = not self.models
        init_bias = (jnp.asarray(init_scores, jnp.float32)
                     if first_iteration else self._zero_bias)
        step = self._get_step_fn(custom)
        if self._sample_hook is not None:
            key = jax.random.PRNGKey(self._hook_rng.integers(1, 2**31))
        else:
            key = self._dummy_key
        first_dispatch = not getattr(self, "_step_dispatched", True)
        if first_dispatch:
            import time as _time
            t0 = _time.monotonic()
        with timing.phase("train/step_dispatch"):
            self._scores, new_valids, recs = step(
                self._step_bins(),
                self._scores, tuple(self._valid_scores), mask, fmask,
                jnp.float32(self.shrinkage_rate), init_bias, g_in, h_in,
                key)
        if first_dispatch:
            # per-booster compile span: the first dispatch pays
            # trace+compile on a registry miss and ~nothing on a hit —
            # the spread of this timer across boosters IS the
            # amortization the step cache buys (run reports pick the
            # registry totals up via meta.step_cache)
            self._step_dispatched = True
            from ..obs import registry as obs
            obs.timer("step_cache/first_step_s").add(
                _time.monotonic() - t0)
        self._valid_scores = list(new_valids)
        for k, rec in enumerate(recs):
            shrinkage_for_file = self.shrinkage_rate
            if first_iteration and abs(init_scores[k]) > 1e-15:
                shrinkage_for_file = 1.0
            self.records.append(rec)
            self.models.append(None)
            self._tree_shrinkage.append(shrinkage_for_file)

        self.iter_ += 1
        self._bump_model_gen()
        sync_iv = self._dispatch_sync_interval
        if sync_iv > 0 and self.iter_ % sync_iv == 0:
            # drain the dispatch queue with ONE scalar readback: deep
            # async queues (hundreds of pending iterations) degrade
            # sustained throughput ~2.4x on RPC-tunneled backends,
            # while a bounded queue holds the short-chain rate. A
            # plain block_until_ready is not sufficient — it has been
            # observed returning early on the tunneled backend.
            with timing.phase("train/queue_drain"):
                np.asarray(recs[-1].num_leaves)
        if self.iter_ % self._stop_check_interval == 0:
            return self._check_stop()
        return False


    def leaves_and_waves(self, start_group: int = 0):
        """Per-iteration [class-tree] leaf counts and wave-pass counts
        for the stored records from ``start_group`` on — ONE stacked
        device download. Public: the run report (train) and bench both
        derive their comm accounting from these."""
        K = self.num_tree_per_iteration
        recs = self.records[start_group * K:]
        if not recs:
            return [], []
        nl = self._num_leaves_host(recs)
        leaves = nl.reshape(-1, K).tolist()
        W = max(self._grower_cfg.wave_size, 1)
        waves = [sum(max(-(-(int(l) - 1) // W), 1) for l in grp)
                 for grp in leaves]
        return leaves, waves

    def wire_encoding(self) -> str:
        """The histogram-collective wire encoding this booster trains
        with: "" off the data-parallel path (no collective), "f32" for
        the dequantize-first wire, else the quantized wire's dtype
        ("int32"/"int16"/"int8", config.tpu_psum_wire). Surfaces as
        ``meta.wire`` in run reports."""
        if self._mesh is None or self._learner_mode != "data":
            return ""
        gcfg = self._grower_cfg
        return gcfg.psum_wire if gcfg.quant_psum else "f32"

    def record_comm_bytes(self, recorder, waves) -> Optional[list]:
        """Attach per-iteration psum payload bytes (and the cumulative
        comm counters, including the packed-wire savings and the
        measured stall-time estimate) to a RunRecorder; returns the
        byte list, or None off the data-parallel path."""
        comm = self._comm_bytes_per_iteration(waves)
        if comm:
            from ..obs import registry as obs
            for i, cb in enumerate(comm):
                recorder.set_field(i + 1, "comm_bytes", cb)
            obs.counter("comm/psum_bytes").add(sum(comm))
            passes = (sum(waves)
                      + self.num_tree_per_iteration * len(waves))
            obs.counter("comm/psum_passes").add(passes)
            saved = self._wire_bytes_saved_per_pass() * passes
            if saved:
                obs.counter("comm/wire_bytes_saved").add(saved)
            stall = self.psum_stall_estimate_s(passes)
            if stall is not None:
                obs.counter("comm/psum_stall_s").add(stall)
        return comm

    def psum_stall_estimate_s(self, passes: int) -> Optional[float]:
        """Seconds the run would stall on the histogram collective:
        MEASURED per-pass wall of the real psum payload on the real
        mesh (ops/autotune.py measure_psum_s — outside the compiled
        step, where in-step timing is impossible) x pass count. None
        off the data-parallel path."""
        if self._mesh is None or self._learner_mode != "data" \
                or passes <= 0:
            return None
        gcfg = self._grower_cfg
        from ..ops.autotune import measure_psum_s
        from ..parallel.learners import _WIRE_DTYPES
        C = self._wire_channels()
        dtype = (_WIRE_DTYPES[gcfg.psum_wire] if gcfg.quant_psum
                 else jnp.float32)
        shape = (gcfg.wave_size, self._f_pad, gcfg.num_bins, C)
        try:
            per_pass = measure_psum_s(self._mesh, shape, dtype)
        except Exception as e:        # a measurement must never take
            log.debug("psum stall measurement failed: %s", e)
            return None               # accounting (or training) down
        return float(per_pass) * int(passes)

    def _wire_channels(self) -> int:
        """Channel count of the histogram-collective payload."""
        from ..utils.device import on_tpu
        # the 2-channel proxy wire only exists where the Pallas fused
        # kernel runs (the XLA oracle keeps 3 exact channels)
        return 2 if (self._grower_cfg.count_proxy and on_tpu()) else 3

    def _wire_entry_bytes(self) -> int:
        """Bytes per histogram entry on the wire: 4 for f32/int32, 2
        for the packed int16 wire, 1 for int8 (tpu_psum_wire)."""
        gcfg = self._grower_cfg
        if not gcfg.quant_psum:
            return 4
        return {"int8": 1, "int16": 2}.get(gcfg.psum_wire, 4)

    def _wire_bytes_saved_per_pass(self) -> int:
        """Bytes per collective pass the packed wire keeps off the
        DCN relative to the 4-byte legacy wire."""
        width_saved = 4 - self._wire_entry_bytes()
        if not width_saved:
            return 0
        gcfg = self._grower_cfg
        F_h = max(self.train_data.num_features, 1)
        return (gcfg.wave_size * F_h * gcfg.num_bins
                * self._wire_channels() * width_saved)

    def _comm_bytes_per_iteration(self, waves) -> Optional[list]:
        """Per-iteration cross-chip psum payload bytes on the
        data-parallel path (None otherwise): each class tree pays one
        root histogram pass plus one per wave step, and each pass
        reduces a [W, F_hist, B, C] block (entry width set by the
        wire — 4 bytes f32/int32, 2/1 packed int16/int8; the
        count-proxy tier carries 2 channels instead of 3). Scalar
        reductions (root aggregates, quantization pmax) are a few
        hundred bytes per tree and are not counted."""
        if self._mesh is None or self._learner_mode != "data":
            return None
        gcfg = self._grower_cfg
        C = self._wire_channels()
        F_h = max(self.train_data.num_features, 1)
        per_pass = (gcfg.wave_size * F_h * gcfg.num_bins * C
                    * self._wire_entry_bytes())
        K = self.num_tree_per_iteration
        return [(int(w) + K) * per_pass for w in waves]

    def _num_leaves_host(self, records) -> np.ndarray:
        """Download num_leaves for a list of records in ONE transfer."""
        if not records:
            return np.zeros(0, np.int32)
        stacked = jnp.stack([r.num_leaves for r in records])
        return np.asarray(stacked)

    def _drop_last_iterations(self, n_groups: int) -> None:
        """Remove the last ``n_groups`` boosting iterations AND subtract
        their score contributions (shared by stop-trim and rollback)."""
        K = self.num_tree_per_iteration
        for _ in range(n_groups):
            for k in range(K - 1, -1, -1):
                rec = self.records.pop()
                self.models.pop()
                self._tree_shrinkage.pop()
                leaf = replay_partition(rec, self._train_bins_unpacked(),
                                        self._meta)[:self._n_score]
                self._scores = self._scores.at[k].set(add_leaf_outputs(
                    self._scores[k], leaf, rec.leaf_output, -1.0))
                for vi in range(len(self.valid_sets)):
                    vleaf = replay_partition(rec, self._valid_bins_dev[vi],
                                             self._meta)
                    self._valid_scores[vi] = \
                        self._valid_scores[vi].at[k].set(add_leaf_outputs(
                            self._valid_scores[vi][k], vleaf,
                            rec.leaf_output, -1.0))
            self.iter_ -= 1
        self._clean_groups = min(self._clean_groups, self.iter_)
        self._bump_model_gen()

    def _first_splitless_group(self) -> Optional[int]:
        """Index of the first iteration in which NO class tree could
        split — where the reference stops (gbdt.cpp:393-409). Scans only
        groups not yet verified productive; one device download of the
        scanned tail. None if every iteration was productive."""
        K = self.num_tree_per_iteration
        num_groups = len(self.records) // K
        if num_groups <= self._clean_groups:
            return None
        tail = self.records[self._clean_groups * K:num_groups * K]
        nl = self._num_leaves_host(tail)
        groups = nl.reshape(-1, K)
        for i in range(len(groups)):
            if (groups[i] <= 1).all():
                return self._clean_groups + i
            self._clean_groups += 1
        return None

    def _trim_at_splitless(self, gi: int) -> None:
        """Drop the splitless iteration ``gi`` and everything after it.
        A splitless iteration 0 is kept as the reference's constant first
        tree (gbdt.cpp:378-396) but still stops training."""
        keep = max(gi, 1)
        self._drop_last_iterations(self.iter_ - keep)
        self._stopped = True
        log.warning("Stopped training because there are no more leaves "
                    "that meet the split requirements")

    def _check_stop(self) -> bool:
        """Periodic host check for the reference's early stop; removes
        the splitless iteration and everything trained after it (score
        contributions subtracted, so state stays consistent)."""
        if self._stopped:
            return True
        gi = self._first_splitless_group()
        if gi is None:
            return False
        self._trim_at_splitless(gi)
        return True

    def finish_training(self) -> None:
        """Final trim; call once after the boosting loop. Mirrors
        _check_stop for splitless iterations that landed after the last
        periodic check."""
        if self._stopped:
            return
        gi = self._first_splitless_group()
        if gi is not None:
            self._trim_at_splitless(gi)

    # -- lazy host-tree materialization --------------------------------------

    def _ensure_host_trees(self) -> None:
        """Build host Tree mirrors for all device records that don't have
        one yet — a single packed stacked download for all of them."""
        missing = [i for i, m in enumerate(self.models) if m is None]
        if not missing:
            return
        packed = jnp.stack([pack_record(self.records[i]) for i in missing])
        packed_np = np.asarray(packed)
        L = self._grower_cfg.num_leaves
        for row, i in enumerate(missing):
            rec_np = unpack_record(packed_np[row], L)
            tree = tree_from_record(
                rec_np, self.train_data.mappers,
                self.train_data.used_feature_map, 1.0, L)
            tree.shrinkage = self._tree_shrinkage[i]
            self.models[i] = tree

    def _stacked_guard(self) -> threading.RLock:
        """The serving-path lock — created lazily for instances
        deserialized around __init__ (copy/pickle shims)."""
        lk = getattr(self, "_stacked_lock", None)
        if lk is None:
            lk = self._stacked_lock = lockorder.named_rlock(
                "gbdt._stacked_lock")
        return lk

    def _bump_model_gen(self) -> None:
        """Invalidate prediction caches — call from every path that
        mutates the ensemble (train, rollback, refit, load). Runs
        under the serving lock so a concurrent predict() never reads a
        generation that is mid-bump."""
        with self._stacked_guard():
            self._model_gen = getattr(self, "_model_gen", 0) + 1

    def _invalidate_stacked(self) -> None:
        """Hard-drop the stacked predictor. Needed by paths that
        mutate a host tree IN PLACE (LGBM_BoosterSetLeafValue): tree
        identity survives such edits, so the prefix-reuse check in
        _stacked_model cannot see them — the stale stacks must go."""
        with self._stacked_guard():
            self._model_gen = getattr(self, "_model_gen", 0) + 1
            self._stacked_cache = None
            self._stacked_ref = None

    def _stacked_model(self):
        """Cached whole-ensemble device predictor (ops/stacked_predict);
        None when the model shape can't be stacked.

        Serving-grade reuse: the whole check-build-publish runs under
        one lock (a predict() during a retrain serializes behind the
        build instead of racing a half-built StackedModel), and a
        generation bump no longer forces a full re-stack — when the
        previously stacked trees are still a prefix of the live
        ensemble (continued training appends; rollback trims), the
        cached predictor is EXTENDED with only the new tree chunk
        (StackedModel.extend) or reused as-is with the caller's ntree
        slicing. Only a genuinely different ensemble (retrain on a
        fresh booster, refit, shuffle, load) pays a full stack."""
        with self._stacked_guard():
            # snapshot BOUND first: a training thread may append a
            # record (models gains a not-yet-materialized None tail
            # entry) at any moment — everything below operates on the
            # prefix that existed here, which _ensure_host_trees is
            # guaranteed to have materialized
            n_live = len(self.models)
            self._ensure_host_trees()
            models = list(self.models[:n_live])
            key = (getattr(self, "_model_gen", 0), len(models))
            cached = getattr(self, "_stacked_cache", None)
            if cached is not None and cached[0] == key:
                return cached[1]
            sm = None
            prev = cached[1] if cached is not None else None
            # invariant: _stacked_ref lists EXACTLY the tree objects
            # prev has stacked, in order — every reuse decision below
            # is an identity check against it
            ref = getattr(self, "_stacked_ref", None)
            if prev is not None and prev.ok and ref:
                shared = min(len(ref), len(models))
                if all(a is b for a, b in zip(ref[:shared],
                                              models[:shared])):
                    if len(models) <= len(ref):
                        # trim/rollback or a pure gen bump: the stacks
                        # already cover every live tree — predict()
                        # slices by ntree; ref keeps describing prev's
                        # FULL contents (a later append on top of the
                        # trim must not extend past stale positions)
                        sm = prev
                    else:
                        # copy-on-write: extend() re-bins the WHOLE
                        # table layout in place, so it must never run
                        # on the published object — a predict() in
                        # flight outside this lock would read mixed
                        # old/new tables mid-mutation. Extend a clone
                        # and publish that instead; in-flight readers
                        # keep the consistent original.
                        cand = prev.clone_for_extend()
                        if cand.extend(models[len(ref):]):
                            sm = cand
                            self._stacked_ref = models
            if sm is None:
                from ..ops.stacked_predict import StackedModel
                nf = self.max_feature_idx + 1
                if nf <= 0 and models:
                    nf = max([max(t.split_feature, default=-1)
                              for t in models]) + 1
                cfg = self.config
                sm = StackedModel(
                    models, max(nf, 1), self.num_tree_per_iteration,
                    serve_bucket=(cfg.tpu_serve_bucket
                                  if cfg is not None else None))
                sm = sm if sm.ok else None
                self._stacked_ref = models if sm is not None else None
            self._stacked_cache = (key, sm)
            return sm

    def prepare_serving(self, warm_rows: int = 0) -> bool:
        """Pre-build this model's serving path BEFORE it is published
        into a live request stream — the swap seam of the pipelined
        lrb loop: the trainer thread calls this on the freshly trained
        booster, so the atomic model swap hands over a predictor whose
        stacked tables (and, with ``warm_rows`` > 0, the compiled
        program for that serve-bucket shape) are already warm. Runs
        under the serving lock like every stacked build; returns True
        when a stacked predictor is available."""
        sm = self._stacked_model() if len(self.models) >= 1 else None
        if sm is None:
            return False
        if warm_rows > 0:
            sm.warmup(warm_rows)
            if self.objective is not None:
                # warm the FULL wire path, not just raw scores: the
                # objective transform compiles per serve bucket too
                # (see predict), and a live request stream must never
                # pay that trace — the fleet daemon registers models
                # through here (serve/tenants.py)
                self.predict(np.zeros((int(warm_rows),
                                       max(self.max_feature_idx + 1, 1)),
                                      np.float64))
        return True

    def rollback_one_iter(self) -> None:
        """RollbackOneIter (gbdt.cpp:414-430). Training may resume
        afterwards, so the stop latch is cleared."""
        if self.iter_ <= 0:
            return
        self._drop_last_iterations(1)
        self._stopped = False

    # -- evaluation (gbdt.cpp:432-534) --------------------------------------

    def get_eval_at(self, data_idx: int) -> List[tuple]:
        """Returns [(metric_name, value, bigger_better)] for dataset
        data_idx (0 = train, 1.. = valid).

        When every metric for the dataset has a device implementation
        (metrics/metric.py device_eval_builder), evaluation runs as ONE
        jitted reduction and only len(metrics) scalars cross the wire —
        per-iteration eval (early stopping) no longer downloads the
        full [K, N] score tensor."""
        out = []
        if data_idx == 0:
            scores = self.train_scores()
            metrics = self.training_metrics
        else:
            scores = self._valid_scores[data_idx - 1]
            metrics = self.valid_metrics[data_idx - 1]
        with timing.phase("eval/metrics"):
            fn = self._device_eval_fn(data_idx, metrics)
            if fn is not None:
                vals = np.asarray(fn(scores))
                return [(m.name, float(v), m.bigger_is_better)
                        for m, v in zip(metrics, vals)]
            raw = np.asarray(scores)
            for m in metrics:
                for name, val in m.eval(raw, self.objective):
                    out.append((name, val, m.bigger_is_better))
        return out

    def train_scores(self) -> jax.Array:
        """[K, n] train scores with any bucket-pad columns sliced off —
        every consumer outside the fused step (metrics, fobj, inner
        predict) must read scores through this, not ``_scores``."""
        if self._n_score != self._n:
            return self._scores[:, :self._n]
        return self._scores

    def _device_eval_fn(self, data_idx: int, metrics):
        """Jitted scores -> stacked metric scalars, cached per dataset;
        None when any metric lacks a device implementation."""
        cache = getattr(self, "_dev_eval_fns", None)
        if cache is None:
            cache = self._dev_eval_fns = {}
        if data_idx in cache:
            return cache[data_idx]
        fn = None
        if metrics:
            builders = [m.device_eval_builder(self.objective)
                        for m in metrics]
            if all(b is not None for b in builders):
                # jit-capture: ok(builders) — per-booster jit cached
                # on self._dev_eval_fns keyed by dataset; the metric
                # builders close over THIS booster's eval arrays,
                # never registry-shared
                fn = jax.jit(
                    lambda s: jnp.stack([b(s) for b in builders]))
        cache[data_idx] = fn
        return fn

    # -- prediction ---------------------------------------------------------

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    start_iteration: int = 0,
                    pred_early_stop: bool = False,
                    pred_early_stop_freq: int = 10,
                    pred_early_stop_margin: float = 10.0) -> np.ndarray:
        """Raw scores [N] or [N, K]. Device path: bin with train mappers,
        replay trees on device, ONE download (gbdt_prediction.cpp:9-30).

        ``pred_early_stop``: stop accumulating trees for rows whose
        prediction margin exceeds the threshold, re-checked every
        ``freq`` trees (prediction_early_stop.cpp:20-84: binary margin
        = 2|raw|, multiclass margin = top1 - top2). Rows stop in
        batches of ``freq`` — inherently data-dependent, so it runs on
        the host tree path."""
        out = self._predict_sparse_chunked(
            X, lambda Xd: self.predict_raw(
                Xd, num_iteration, start_iteration, pred_early_stop,
                pred_early_stop_freq, pred_early_stop_margin))
        if out is not None:
            return out
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        k = self.num_tree_per_iteration
        # live predictions see the same trees a checkpoint would contain
        ntree = self._effective_num_models()
        if num_iteration >= 0:
            ntree = min(ntree, (start_iteration + num_iteration) * k)
        first = start_iteration * k
        # the reference enables early stop only where approximate
        # predictions are acceptable: binary / multiclass
        # (NeedAccuratePrediction, prediction_early_stop.cpp)
        if pred_early_stop and k == 1 and not (
                self.objective is not None
                and self.objective.name in ("binary", "multiclassova",
                                            "cross_entropy")):
            log.warning("pred_early_stop is only supported for "
                        "binary/multiclass objectives; ignoring")
            pred_early_stop = False
        if pred_early_stop and k >= 1 and ntree > first:
            self._ensure_host_trees()
            out = np.zeros((k, n), np.float64)
            active = np.arange(n)
            Xa = X                      # re-sliced only when rows stop
            for t_idx in range(first, ntree):
                cls = t_idx % k
                out[cls, active] += self.models[t_idx].predict(Xa)
                done_group = ((t_idx - first + 1) % max(
                    pred_early_stop_freq * k, 1) == 0)
                if done_group and len(active):
                    if k == 1:
                        margin = 2.0 * np.abs(out[0, active])
                    else:
                        part = np.sort(out[:, active], axis=0)
                        margin = part[-1] - part[-2]
                    keep = margin <= pred_early_stop_margin
                    if not keep.all():
                        active = active[keep]
                        Xa = X[active]
                    if not len(active):
                        break
            if self.average_output:
                out /= max((ntree - first) // k, 1)
            return out[0] if k == 1 else out.T
        # no row floor: with serve buckets (ops/predict_cache.py) a
        # 1-row online request rides the same warm compiled program as
        # a 4096-row batch — the host walk stays only for tiny
        # ensembles where stacking cannot pay for itself
        sm = (self._stacked_model() if (ntree - first) >= 4 and n >= 1
              else None)
        if sm is not None:
            # whole-ensemble MXU scan: one dispatch chain instead of one
            # replay per tree (ops/stacked_predict.py). A serving-path
            # caller with an active request context (obs/reqlog.py —
            # the lrb loop, bench --serve) gets its dispatch spanned
            # with the request identity, so the trace timeline answers
            # "which request was on the device" during a stall.
            rctx = obs_reqlog.current()
            if rctx is not None:
                args = {"req_id": rctx.req_id, "rows": int(n)}
                if rctx.window is not None:
                    args["window"] = rctx.window
                span = obs_trace.span("predict/stacked", cat="serve",
                                      args=args)
            else:
                span = contextlib.nullcontext()
            with span:
                out = sm.predict(X, first, ntree).astype(np.float64)
        else:
            self._ensure_host_trees()
            out = np.zeros((k, n), np.float64)
            for t_idx in range(first, ntree):
                out[t_idx % k] += self.models[t_idx].predict(X)
        if self.average_output:
            # reference divides by the iteration count actually predicted
            # (gbdt_prediction.cpp:51-65)
            used_iters = max((ntree - first) // k, 1)
            out /= used_iters
        return out[0] if k == 1 else out.T

    @staticmethod
    def _predict_sparse_chunked(X, fn):
        """CSR predict input (io/sparse.py SparseMatrix) densifies in
        bounded row chunks through ``fn`` — never the whole [N, F]
        matrix; the chunk shrinks with the column count so even a
        100k-column hashed matrix stays under the densify byte budget.
        Bit-exact: every predict path is row-independent. Returns None
        for non-sparse input (the caller proceeds dense)."""
        from ..io.sparse import SparseMatrix, predict_chunk_rows
        if not isinstance(X, SparseMatrix):
            return None
        n = X.shape[0]
        chunk = predict_chunk_rows(X.shape[1])
        if n <= chunk:
            return fn(X.to_dense())
        parts = [fn(X.to_dense_rows(r0, min(r0 + chunk, n)))
                 for r0 in range(0, n, chunk)]
        return np.concatenate(parts, axis=0)

    def _bin_input(self, X: np.ndarray) -> np.ndarray:
        """Bin raw rows with the train mappers -> [F, N] feature-major
        (bundle-encoded when the train set used EFB)."""
        ds = self.train_data
        f = max(ds.num_features, 1)
        dtype = np.uint8 if ds.max_bin_global <= 256 else np.int32
        bins = np.zeros((X.shape[0], f), dtype)
        for i, real in enumerate(ds.used_feature_map):
            bins[:, i] = ds.mappers[i].value_to_bin(
                X[:, real]).astype(dtype)
        if ds.bundles is not None and getattr(self, "_use_bundles",
                                              False):
            from ..io.efb import bundle_bins
            db = np.array([m.default_bin for m in ds.mappers], np.int32)
            nb = np.array([m.num_bin for m in ds.mappers], np.int32)
            bins, _, _, _ = bundle_bins(bins, ds.bundles, db, nb)
        return np.ascontiguousarray(bins.T)

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                **pred_kw) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration, **pred_kw)
        if self.objective is not None:
            # convert_output operates class-major [K, N] like the
            # reference's ConvertOutput; predict_raw returns [N, K]
            r = raw.T if raw.ndim == 2 else raw
            # pad the transform to the SAME serve bucket the forest
            # predict rode: convert_output is a per-row jax op, so an
            # online stream of odd batch sizes would otherwise
            # re-trace it once per distinct size — a serving-path
            # stall the bucketed forest predict already paid to avoid.
            # Rows are independent (sigmoid/per-row softmax); the pad
            # is sliced off, so results are bit-identical.
            from ..ops import predict_cache
            n = int(r.shape[-1])
            cfg = self.config
            b = predict_cache._bucket_rows(
                n, cfg.tpu_serve_bucket if cfg is not None else None)
            if b > n:
                r = np.pad(np.asarray(r),
                           [(0, 0)] * (r.ndim - 1) + [(0, b - n)])
            out = np.asarray(self.objective.convert_output(jnp.asarray(r)))
            out = out[..., :n]
            return out.T if raw.ndim == 2 else out
        return raw

    def predict_leaf_index(self, X: np.ndarray,
                           num_iteration: int = -1) -> np.ndarray:
        out = self._predict_sparse_chunked(
            X, lambda Xd: self.predict_leaf_index(Xd, num_iteration))
        if out is not None:
            return out
        self._ensure_host_trees()
        X = np.asarray(X, np.float64)
        ntree = self._effective_num_models()
        if num_iteration >= 0:
            ntree = min(ntree, num_iteration * self.num_tree_per_iteration)
        sm = (self._stacked_model() if ntree >= 4 and X.shape[0] >= 1
              else None)
        if sm is not None:
            return sm.predict(X, 0, ntree, pred_leaf=True)
        out = np.zeros((X.shape[0], ntree), np.int32)
        for t in range(ntree):
            out[:, t] = self.models[t].predict_leaf_index(X)
        return out

    def predict_contrib(self, X: np.ndarray,
                        num_iteration: int = -1) -> np.ndarray:
        """SHAP feature contributions [N, F+1] (or [N, K*(F+1)] for
        multiclass): per-feature Shapley values + bias column
        (gbdt.h PredictContrib / tree.h:118)."""
        out = self._predict_sparse_chunked(
            X, lambda Xd: self.predict_contrib(Xd, num_iteration))
        if out is not None:
            return out
        self._ensure_host_trees()
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        k = self.num_tree_per_iteration
        f1 = self.max_feature_idx + 2
        ntree = self._effective_num_models()
        if num_iteration >= 0:
            ntree = min(ntree, num_iteration * k)
        out = np.zeros((k, n, f1), np.float64)
        for t_idx in range(ntree):
            self.models[t_idx].predict_contrib(X, out[t_idx % k])
        if self.average_output:
            out /= max(ntree // k, 1)
        if k == 1:
            return out[0]
        return out.transpose(1, 0, 2).reshape(n, k * f1)

    def refit_existing(self, decay_rate: Optional[float] = None) -> None:
        """RefitTree (gbdt.cpp:265-289) against the CURRENT train_data:
        keep every tree's structure, re-learn its leaf outputs on the
        new data's gradients, blending with refit_decay_rate
        (FitByExistingTree, serial_tree_learner.cpp:223-253:
        new = decay*old + (1-decay) * (-sum_g/(sum_h+l2)) * shrinkage).
        Sequential like the reference: iteration i's gradients see the
        refit outputs of iterations 0..i-1. Call after
        ``init_from_loaded`` bound this booster to the new dataset."""
        cfg = self.config
        decay = cfg.refit_decay_rate if decay_rate is None else decay_rate
        if self.objective is None:
            log.fatal("Refit requires an objective")
        K = self.num_tree_per_iteration
        L = self._grower_cfg.num_leaves
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        mds = cfg.max_delta_step
        from ..ops.split import KEPSILON, calculate_leaf_output

        @jax.jit
        def refit_one(scores_k, rec_leaf_output, leaf, g_k, h_k, shrink):
            sg = jnp.zeros(L, jnp.float32).at[leaf].add(g_k)
            sh = jnp.full(L, KEPSILON, jnp.float32).at[leaf].add(h_k)
            new_out = calculate_leaf_output(sg, sh, l1, l2, mds) * shrink
            out = decay * rec_leaf_output + (1.0 - decay) * new_out
            return scores_k + out[leaf], out

        self._init_scores()
        n_iters = len(self.records) // K
        n = self._n
        for it in range(n_iters):
            # gradients see the REAL rows only (objective arrays are
            # [n]; bucket-pad score columns are sliced off)
            sc = self.train_scores()
            g_all, h_all = self.objective.get_gradients(
                sc if K > 1 else sc[0])
            if K == 1:
                g_all, h_all = g_all[None, :], h_all[None, :]
            for k in range(K):
                t = it * K + k
                rec = self.records[t]
                leaf = replay_partition(rec, self._train_bins_unpacked(),
                                        self._meta)[:n]
                new_scores, out = refit_one(
                    self._scores[k, :n], rec.leaf_output, leaf,
                    g_all[k], h_all[k],
                    jnp.float32(self._tree_shrinkage[t]))
                self._scores = self._scores.at[k, :n].set(new_scores)
                self.records[t] = rec._replace(leaf_output=out)
                self.models[t] = None
        self._bump_model_gen()
        log.info("Refit %d trees with decay_rate=%g", len(self.records),
                 decay)

    # -- CLI training driver (gbdt.cpp:245-263 GBDT::Train) ------------------

    def train(self, snapshot_freq: int = -1, output_model: str = "",
              resume_from: str = "") -> None:
        """The application-side training loop: boosting iterations with
        per-iteration metric output (OutputMetric, gbdt.cpp:466-534),
        reference-style early stopping (EvalAndCheckEarlyStopping,
        gbdt.cpp:432-448: pop the last ``early_stopping_round``
        iterations on stop), and periodic snapshots.

        Fault tolerance (utils/checkpoint.py): with
        ``tpu_checkpoint_dir``/``tpu_checkpoint_freq`` set, the loop
        periodically writes a resumable checkpoint bundle (atomic,
        pruned to ``tpu_snapshot_keep``); ``resume_from`` (a bundle
        path or a checkpoint directory — newest valid bundle wins)
        restores a killed run and continues it BIT-IDENTICALLY to the
        uninterrupted run, in the same global iteration numbering.

        Telemetry seam (obs/): every iteration is spanned by a
        RunRecorder (wall time, HBM, transfer-byte deltas, eval values;
        per-iteration leaf counts are filled at the end from ONE
        stacked download), the slow-iteration watchdog warns with the
        phase table, and tpu_profile_dir/tpu_profile_iters bracket a
        configurable iteration window with the jax profiler."""
        import time

        from ..obs.profiler import ProfileWindow
        from ..obs.recorder import RunRecorder
        from ..utils import faults
        cfg = self.config
        # best_score_[i][j] per (valid set, metric), in
        # bigger-is-better orientation
        self._best_score = [[-np.inf] * len(ms) for ms in self.valid_metrics]
        self._best_iter = [[0] * len(ms) for ms in self.valid_metrics]
        self._best_msg = [[""] * len(ms) for ms in self.valid_metrics]
        start_iter = 0
        if resume_from:
            # restore overwrites the best-score lists initialized
            # above, the RNG streams, the bagging mask and the device
            # scores — the loop below then continues at start_iter + 1
            # with the uninterrupted run's numbering. The checkpoint
            # stores TOTAL tree groups; the loop counts ADDITIONAL
            # rounds on top of any loaded input_model (gbdt.cpp:248),
            # so a continued-training resume subtracts the base the
            # input model contributed.
            from ..utils import checkpoint as ckpt
            pre_groups = (len(self.records)
                          // max(self.num_tree_per_iteration, 1))
            restored = ckpt.restore(self, ckpt.resolve_resume(
                resume_from))
            start_iter = restored - pre_groups
            if start_iter < 0:
                log.fatal(f"checkpoint at iteration {restored} predates "
                          f"the loaded input_model ({pre_groups} "
                          f"iterations) — it belongs to a different run")
        start_time = time.monotonic()
        is_finished = False
        recorder = RunRecorder(
            path=cfg.tpu_run_report,
            watchdog_factor=cfg.tpu_watchdog_factor,
            meta={"driver": "gbdt.train", "objective": cfg.objective,
                  "tree_learner": self._learner_mode,
                  "mesh_devices": self.num_devices,
                  "num_iterations": cfg.num_iterations,
                  "num_leaves": cfg.num_leaves,
                  "wave_size": self._grower_cfg.wave_size,
                  "num_data": self._n,
                  "num_features": self.train_data.num_features,
                  "num_class": self.num_class,
                  **({"resumed_from_iteration": start_iter}
                     if start_iter else {})}).start()
        self._recorder = recorder
        profile = ProfileWindow(cfg.tpu_profile_dir,
                                cfg.tpu_profile_iters)

        def materialize_batch(batch):
            """[(it, handles)] -> [(it, {idx: [(name, val, bigger)]})]
            with ONE device concat and ONE download for the whole
            batch: every np.asarray pays a full tunnel round-trip
            (~100 ms here), so per-handle downloads re-serialize the
            training loop no matter how the evals are pipelined."""
            flat = [entry[1] for _, ph in batch
                    for entry in ph.values() if entry is not None]
            vals = (np.asarray(jnp.concatenate(flat)) if flat
                    else np.zeros(0, np.float32))
            out = []
            pos = 0
            for pit, ph in batch:
                values = {}
                for idx, entry in ph.items():
                    if entry is None:
                        values[idx] = []
                        continue
                    metrics = entry[0]
                    v = vals[pos:pos + len(metrics)]
                    pos += len(metrics)
                    values[idx] = [
                        (m.name, float(x), m.bigger_is_better)
                        for m, x in zip(metrics, v)]
                out.append((pit, values))
            return out

        # Pipelined evaluation with a BATCHED lookahead, like
        # engine._train_loop but K deep: iteration N's device metric
        # scalars are dispatched right after its update and
        # materialized up to K training iterations later, in order. On
        # an RPC-tunneled backend any device->host read waits behind
        # EVERY queued dispatch (the transfer stream is ordered), so a
        # per-iteration materialize silently re-serializes the loop to
        # train-time + round-trip; batching K evals amortizes that
        # drain to RTT/K per round. Semantics are unchanged: metric
        # lines keep the reference format and indices (gbdt.cpp:466-
        # 534, printed in small batches), and an early stop detected
        # late pops the extra lookahead iterations (extra_drop), so
        # the kept model is identical to the synchronous path's. Falls
        # back to the synchronous path when any metric lacks a device
        # implementation.
        pipeline_ok = True
        pending: List[tuple] = []    # [(iteration index, handles)]
        trained = 0
        kdepth = 16

        def flush_pending():
            """Materialize ALL queued evals (one batched download) and
            process them in order; True = early stop fired (the extra
            lookahead iterations are popped)."""
            if not pending:
                return False
            batch = materialize_batch(pending)
            pending.clear()
            for pit, values in batch:
                if self._eval_and_check_early_stopping(
                        pit, values=values, extra_drop=trained - pit):
                    return True
            return False

        # num_iterations counts ADDITIONAL rounds on top of a loaded
        # input_model, like the reference's train loop (gbdt.cpp:248
        # iterates config num_iterations times from the loaded state);
        # the log/snapshot index is likewise the ADDITIONAL-round
        # counter (gbdt.cpp:255-260 uses its loop-local iter + 1)
        # groups already present before this loop (continued
        # training): the report's per-iteration leaf rows must
        # align with the ADDITIONAL-round numbering used above
        base_groups = len(self.records) // self.num_tree_per_iteration
        try:
            for add in range(start_iter, cfg.num_iterations):
                if faults.active():
                    # the kill-and-resume drills aim here (train.iter)
                    faults.check("train.iter", context=add + 1)
                profile.iter_begin(add + 1)
                recorder.begin_iteration(add + 1)
                is_finished = self.train_one_iter()
                # periodic drain/stop-check iterations block on the
                # device and absorb the queued dispatch backlog — tag
                # them so the watchdog compares like spans with like
                sync_iv = self._dispatch_sync_interval
                drained = ((sync_iv > 0 and self.iter_ % sync_iv == 0)
                           or self.iter_ % self._stop_check_interval == 0)
                recorder.end_iteration(
                    add + 1, kind="sync" if drained else "iter")
                profile.iter_end(add + 1)
                trained = add + 1
                if not is_finished:
                    it = add + 1
                    handles = (self._eval_dispatch(it) if pipeline_ok
                               else None)
                    if handles is None:
                        pipeline_ok = False
                    if pipeline_ok:
                        pending.append((it, handles))
                        if len(pending) >= kdepth:
                            # ONE drain per K rounds: the wait rides the
                            # already-queued training work, costing ~one
                            # round-trip per batch instead of per round
                            is_finished = flush_pending()
                    else:
                        # drain the lookahead before going synchronous
                        is_finished = flush_pending()
                        if not is_finished:
                            is_finished = \
                                self._eval_and_check_early_stopping(it)
                log.info("%f seconds elapsed, finished iteration %d",
                         time.monotonic() - start_time, add + 1)
                if snapshot_freq > 0 and (add + 1) % snapshot_freq == 0:
                    # flush the pipelined evals BEFORE snapshotting: a
                    # late-detected early stop pops its lookahead
                    # iterations, and a snapshot written first would
                    # contain trees the pop then removes
                    if not is_finished:
                        is_finished = flush_pending()
                    self._write_snapshot(output_model, add + 1)
                if (cfg.tpu_checkpoint_freq > 0 and cfg.tpu_checkpoint_dir
                        and (add + 1) % cfg.tpu_checkpoint_freq == 0):
                    # same flush-first rule as snapshots: the bundle
                    # must not capture lookahead trees an early stop
                    # is about to pop
                    if not is_finished:
                        is_finished = flush_pending()
                    self.write_checkpoint(cfg.tpu_checkpoint_dir)
                if is_finished:
                    break
            # flush the tail so the last iterations' metric lines (and a
            # late-detected stop) are not lost
            flush_pending()
            profile.close()
            self.finish_training()
            if output_model:
                with timing.phase("io/save_model"):
                    self.save_model_to_file(output_model)
                log.info("Finished training; model saved to %s", output_model)
            # run report: per-iteration leaf counts come from ONE stacked
            # download of the surviving records; wave counts derive from
            # them (a W-leaf wave pass grows up to W leaves per tree).
            # finish() snapshots the phase table BEFORE log_report resets.
            self._recorder = None
            leaves = waves = None
            K = self.num_tree_per_iteration
            # the stacked download is only paid when a report will
            # actually be written (it is a blocking device->host
            # transfer — ~a full tunnel round-trip on RPC backends).
            # Resumed runs skip it: their iteration numbering continues
            # at start_iter + 1 while the leaf lists would start at
            # row 1, misaligning the report.
            if cfg.tpu_run_report and start_iter == 0 \
                    and len(self.records) > base_groups * K:
                leaves, waves = self.leaves_and_waves(base_groups)
                # cross-chip traffic: every root/wave histogram pass
                # moves one [W, F, B, C] block through the psum
                self.record_comm_bytes(recorder, waves)
            from ..ops import predict_cache, step_cache
            # registry totals are process-wide; booster_eligible is
            # THIS booster's routing (the global "enabled" is
            # last-init-wins and may describe a different booster)
            recorder.meta["step_cache"] = dict(
                step_cache.stats(),
                booster_eligible=bool(getattr(self, "_cache_eligible",
                                              False)))
            recorder.meta["predict_cache"] = predict_cache.stats()
            recorder.meta["wire"] = self.wire_encoding()
            recorder.finish(
                leaves_per_iteration=leaves, waves_per_iteration=waves,
                extra={"trained_iterations": self.iter_,
                       "stopped_early": bool(self._stopped)})
        finally:
            # background checkpoint writes drain before train()
            # returns — callers may read the directory (or kill the
            # process) the moment control comes back
            self._drain_checkpoints()
            # exception path: close an open trace, write the partial
            # report, clear the log prefix (finish() is idempotent —
            # the normal path above already finished with leaf counts)
            profile.close()
            self._recorder = None
            from ..ops import predict_cache, step_cache
            recorder.meta.setdefault("step_cache", step_cache.stats())
            recorder.meta.setdefault("predict_cache",
                                     predict_cache.stats())
            recorder.meta.setdefault("wire", self.wire_encoding())
            recorder.finish(extra={"aborted": True})
        timing.log_report("training phase timings "
                          "(serial_tree_learner.cpp:14-41 analog)")

    def _write_snapshot(self, output_model: str, it: int) -> None:
        """Periodic model snapshot (save_period): atomic write + prune
        to the last ``tpu_snapshot_keep`` — a crash mid-write can no
        longer leave a torn ``.snapshot_iter_N`` file, and old
        snapshots no longer accumulate without bound. A failed write
        warns and training continues."""
        from ..utils.fileio import atomic_write, prune_numbered
        path = f"{output_model}.snapshot_iter_{it}"
        try:
            with atomic_write(path) as fh:
                fh.write(self.model_to_string())
        except OSError as e:
            log.warning("snapshot %s failed (%s); training continues",
                        path, e)
            return
        prune_numbered(output_model, ".snapshot_iter_*",
                       r"\.snapshot_iter_(\d+)$",
                       self.config.tpu_snapshot_keep)

    def write_checkpoint(self, directory: str) -> Optional[str]:
        """Write a resumable checkpoint bundle (utils/checkpoint.py);
        returns the path, or None on failure. Failures — disk full,
        an injected ``checkpoint.write`` fault — warn and NEVER stop
        or corrupt training: the atomic write leaves the previous
        complete bundle intact. Public: engine.train's periodic
        checkpoint wiring calls this too.

        With tpu_ckpt_async (-1 auto = on) the file writes ride a
        background writer thread (utils/checkpoint.py
        AsyncCheckpointWriter): the collective score gather and the
        bundle construction still happen here, on-path; only the
        serialization + atomic writes are hidden behind subsequent
        iterations. The queue drains at train end and before any
        resume read."""
        from ..utils import checkpoint as ckpt
        writer = None
        if self.config.tpu_ckpt_async != 0:
            writer = getattr(self, "_ckpt_writer", None)
            if writer is None:
                writer = self._ckpt_writer = ckpt.new_writer()
        try:
            return ckpt.save_checkpoint(
                self, directory, keep=max(self.config.tpu_snapshot_keep,
                                          1), writer=writer)
        except Exception as e:      # noqa: BLE001 — durability aid:
            # a checkpoint is insurance, never the failure itself
            from ..obs import registry as obs
            obs.counter("checkpoint/write_failures").add(1)
            log.warning("checkpoint write to %s failed at iteration %d "
                        "(%s: %s); training continues — the previous "
                        "checkpoint is intact", directory,
                        self.current_iteration, type(e).__name__, e)
            return None

    def _drain_checkpoints(self) -> None:
        """Block until this booster's background checkpoint writer has
        committed every queued bundle (no-op when sync or none were
        written). Called at train end; resolve_resume drains all
        writers itself before any read."""
        writer = getattr(self, "_ckpt_writer", None)
        if writer is not None:
            writer.drain()

    def _eval_and_check_early_stopping(self, it: int, values=None,
                                       extra_drop: int = 0) -> bool:
        # ``it`` counts additional rounds like the reference's iter_
        # (reset to 0 on model load, gbdt_model_text.cpp:485).
        # ``values``: pre-materialized {data_idx: [(name, val,
        # bigger)]} from the pipelined dispatch; ``extra_drop``:
        # lookahead iterations trained beyond ``it`` that must also be
        # popped on stop so the kept model still ends at it - es.
        best_msg = self._output_metric(it, values)
        if not best_msg:
            return False
        es = self.config.early_stopping_round
        # report in additional-round numbers so the lines match the
        # "Iteration:N" metric output (reference iter_ semantics)
        log.info("Early stopping at iteration %d, the best iteration "
                 "round is %d", it, it - es)
        log.info("Output of best iteration round:\n%s", best_msg)
        self._drop_last_iterations(es + extra_drop)
        return True

    def _eval_dispatch(self, it: int):
        """Dispatch (without materializing) the device-metric
        reductions iteration ``it`` will need. Returns {data_idx:
        (metrics, device_values) | None-for-empty} or None when some
        needed dataset has no all-device metric set (sync fallback)."""
        cfg = self.config
        need_output = cfg.metric_freq > 0 and (it % cfg.metric_freq) == 0
        es_round = cfg.early_stopping_round
        want = {}
        if need_output and self.training_metrics:
            want[0] = self.training_metrics
        if need_output or es_round > 0:
            for i in range(len(self.valid_sets)):
                want[i + 1] = self.valid_metrics[i]
        out = {}
        for idx, metrics in want.items():
            if not metrics:
                out[idx] = None
                continue
            fn = self._device_eval_fn(idx, metrics)
            if fn is None:
                return None
            scores = (self.train_scores() if idx == 0
                      else self._valid_scores[idx - 1])
            out[idx] = (metrics, fn(scores))
        return out

    def _output_metric(self, it: int, values=None) -> str:
        """OutputMetric (gbdt.cpp:466-534): print metrics at metric_freq
        and run the early-stopping bookkeeping; returns the best-round
        message when the stop condition is met. ``values``: optional
        pre-materialized {data_idx: [(name, val, bigger)]} (the
        pipelined train loop) instead of synchronous get_eval_at."""
        cfg = self.config
        need_output = cfg.metric_freq > 0 and (it % cfg.metric_freq) == 0
        es_round = cfg.early_stopping_round

        def evals(idx):
            out = (values.get(idx, []) if values is not None
                   else self.get_eval_at(idx))
            rec = getattr(self, "_recorder", None)
            hist = getattr(self, "_eval_history", None)
            if out and (rec is not None or hist is not None):
                dname = ("training" if idx == 0
                         else self.valid_names[idx - 1])
                for name, val, _ in out:
                    if rec is not None:
                        rec.record_eval(it, dname, name, val)
                    if hist is not None:
                        # checkpoint-bundle eval history (global
                        # iteration numbering, utils/checkpoint.py)
                        hist.append((it, dname, name, float(val)))
            return out

        ret = ""
        msg_lines: List[str] = []
        if need_output:
            for name, val, _ in evals(0):
                line = f"Iteration:{it}, training {name} : {val:g}"
                log.info("%s", line)
                if es_round > 0:
                    msg_lines.append(line)
        met_best: List[tuple] = []
        if need_output or es_round > 0:
            for i in range(len(self.valid_sets)):
                for j, (name, val, bigger) in enumerate(
                        evals(i + 1)):
                    line = (f"Iteration:{it}, valid_{i + 1} {name}"
                            f" : {val:g}")
                    if need_output:
                        log.info("%s", line)
                    if es_round > 0:
                        msg_lines.append(line)
                        cur = val if bigger else -val
                        if cur > self._best_score[i][j]:
                            self._best_score[i][j] = cur
                            self._best_iter[i][j] = it
                            met_best.append((i, j))
                        elif not ret and \
                                it - self._best_iter[i][j] >= es_round:
                            ret = self._best_msg[i][j]
        msg = "\n".join(msg_lines)
        for i, j in met_best:
            self._best_msg[i][j] = msg
        return ret

    # -- feature importance (gbdt.cpp FeatureImportance) ---------------------

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = 0) -> np.ndarray:
        self._ensure_host_trees()
        n_models = self._effective_num_models()
        if iteration > 0:
            n_models = min(n_models, iteration * self.num_tree_per_iteration)
        imp = np.zeros(self.max_feature_idx + 1, np.float64)
        for t in self.models[:n_models]:
            for i in range(t.num_leaves - 1):
                if importance_type == "split":
                    imp[t.split_feature[i]] += 1.0
                else:
                    imp[t.split_feature[i]] += max(t.split_gain[i], 0.0)
        return imp

    # -- model text serialization (gbdt_model_text.cpp:240-338) --------------

    def _effective_num_models(self) -> int:
        """Number of trees a reference-equivalent model would contain:
        everything before the first splitless iteration. Non-mutating, so
        mid-training checkpoints don't alter the booster."""
        n = len(self.models)
        if self.records and not self._stopped:
            gi = self._first_splitless_group()
            if gi is not None:
                n = min(n, max(gi, 1) * self.num_tree_per_iteration)
        return n

    def model_to_string(self, start_iteration: int = 0,
                        num_iteration: int = -1) -> str:
        self._ensure_host_trees()
        lines = ["tree"]
        lines.append(f"version={K_MODEL_VERSION}")
        lines.append(f"num_class={self.num_class}")
        lines.append(f"num_tree_per_iteration={self.num_tree_per_iteration}")
        lines.append(f"label_index={self.label_idx}")
        lines.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective is not None:
            lines.append(f"objective={self.objective.to_string()}")
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("feature_infos=" + " ".join(self.feature_infos))

        eff = self._effective_num_models()
        total_iter = eff // max(self.num_tree_per_iteration, 1)
        start_iteration = max(0, min(start_iteration, total_iter))
        num_used = eff
        if num_iteration > 0:
            num_used = min((start_iteration + num_iteration)
                           * self.num_tree_per_iteration, num_used)
        start_model = start_iteration * self.num_tree_per_iteration

        tree_strs = []
        for i in range(start_model, num_used):
            s = f"Tree={i - start_model}\n" + self.models[i].to_string() + "\n"
            tree_strs.append(s)
        lines.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
        lines.append("")
        body = "\n".join(lines) + "\n" + "".join(tree_strs)
        body += "end of trees\n"

        imp = self.feature_importance(
            "split", iteration=num_used // max(self.num_tree_per_iteration, 1))
        pairs = [(int(imp[i]), self.feature_names[i])
                 for i in range(len(imp)) if imp[i] > 0]
        pairs.sort(key=lambda p: -p[0])
        body += "\nfeature importances:\n"
        for v, name in pairs:
            body += f"{name}={v}\n"
        if self.config is not None:
            body += "\nparameters:\n" + self.config.to_string() + "\n"
            body += "end of parameters\n"
        elif self.loaded_parameter:
            body += "\nparameters:\n" + self.loaded_parameter + "\n"
            body += "end of parameters\n"
        return body

    def save_model_to_file(self, filename: str, start_iteration: int = 0,
                           num_iteration: int = -1) -> None:
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(start_iteration, num_iteration))

    def load_model_from_string(self, s: str, source: str = "") -> "GBDT":
        """LoadModelFromString (gbdt_model_text.cpp:339-450).

        Truncated or corrupt input fails with a ONE-LINE error naming
        the source, what is malformed and the expected shape — never a
        deep parse traceback (``source``: the file/context the text
        came from, for the message)."""
        from ..objectives import parse_objective_from_model_string
        where = source or "model text"
        lines = s.splitlines()
        first = next((ln.strip() for ln in lines if ln.strip()), "")
        if first != "tree":
            log.fatal(f"{where}: not a LightGBM model (first line "
                      f"{first[:40]!r}, expected 'tree'; model version "
                      f"{K_MODEL_VERSION})")
        kv = {}
        i = 0
        while i < len(lines):
            line = lines[i].strip()
            if line.startswith("Tree="):
                break
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
            elif line == "average_output":
                kv["average_output"] = "1"
            i += 1
        self.num_class = int(kv.get("num_class", 1))
        self.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
        self.label_idx = int(kv.get("label_index", 0))
        self.max_feature_idx = int(kv.get("max_feature_idx", 0))
        self.average_output = "average_output" in kv
        self.feature_names = kv.get("feature_names", "").split()
        self.feature_infos = kv.get("feature_infos", "").split()
        if self.config is None:
            self.config = Config()
        if "objective" in kv:
            self.objective = parse_objective_from_model_string(
                kv["objective"], self.config)
            if self.objective is not None:
                # objective usable only for convert_output after load
                self.objective.label = np.zeros(1, np.float32)
                self.objective.weights = None
                self.objective.num_data = 1
        # parse trees
        self.models = []
        self.records = []
        self._bump_model_gen()
        cur: List[str] = []
        seen_end = False
        for line in lines[i:]:
            t = line.strip()
            if t.startswith("Tree=") or t == "end of trees":
                if cur:
                    try:
                        self.models.append(
                            Tree.from_string("\n".join(cur)))
                    except Exception as e:   # noqa: BLE001 — one-line
                        log.fatal(          # diagnosis, not a traceback
                            f"{where}: malformed Tree="
                            f"{len(self.models)} block "
                            f"({type(e).__name__}: {e})")
                    cur = []
                if t == "end of trees":
                    seen_end = True
                    break
            elif t:
                cur.append(t)
        if not seen_end:
            log.fatal(f"{where}: truncated model text — no 'end of "
                      f"trees' terminator after {len(self.models)} "
                      f"tree(s) (file cut off mid-write?)")
        self.iter_ = len(self.models) // max(self.num_tree_per_iteration, 1)
        self.shrinkage_rate = 1.0  # already folded into leaf values
        self._tree_shrinkage = [m.shrinkage if m.shrinkage else 1.0
                                for m in self.models]
        return self

    def dump_model(self, start_iteration: int = 0,
                   num_iteration: int = -1) -> dict:
        """DumpModel JSON (gbdt_model_text.cpp:15-54)."""
        self._ensure_host_trees()
        num_used = self._effective_num_models()
        if num_iteration > 0:
            num_used = min((start_iteration + num_iteration)
                           * self.num_tree_per_iteration, num_used)
        start_model = start_iteration * self.num_tree_per_iteration
        return {
            "name": "tree",
            "version": K_MODEL_VERSION,
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_idx,
            "max_feature_idx": self.max_feature_idx,
            "objective": (self.objective.to_string()
                          if self.objective else "none"),
            "average_output": self.average_output,
            "feature_names": self.feature_names,
            "tree_info": [t.to_json()
                          for t in self.models[start_model:num_used]],
        }

    @property
    def current_iteration(self) -> int:
        return len(self.models) // max(self.num_tree_per_iteration, 1)

    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
