"""lightgbm_tpu: a TPU-native gradient boosting framework.

Public surface mirrors the reference python package
(reference: python-package/lightgbm/__init__.py): Dataset/Booster,
train/cv, callbacks, and sklearn-style estimators — backed by a
JAX/XLA/Pallas engine instead of the C++ core.
"""
from .basic import Booster, Dataset
from .callback import (EarlyStopException, early_stopping,
                       print_evaluation, record_evaluation,
                       reset_parameter)
from .engine import CVBooster, cv, train
from .utils.log import LightGBMError

try:
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
    _SKLEARN_EXPORTS = ["LGBMModel", "LGBMRegressor", "LGBMClassifier",
                        "LGBMRanker"]
except ImportError:          # scikit-learn not installed
    _SKLEARN_EXPORTS = []

# plotting imports matplotlib lazily inside each function, so the
# module itself always imports
from .plotting import (create_tree_digraph, plot_importance,
                       plot_metric, plot_tree)
_PLOT_EXPORTS = ["create_tree_digraph", "plot_importance",
                 "plot_metric", "plot_tree"]

__version__ = "0.3.0"

__all__ = ["Dataset", "Booster", "train", "cv", "CVBooster",
           "LightGBMError", "EarlyStopException", "print_evaluation",
           "record_evaluation", "reset_parameter",
           "early_stopping"] + _SKLEARN_EXPORTS + _PLOT_EXPORTS
