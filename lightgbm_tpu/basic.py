"""User-facing Dataset / Booster wrappers.

TPU-native counterpart of the reference python ``basic.py``
(reference: python-package/lightgbm/basic.py:626 Dataset,
basic.py:1450 Booster). The reference routes everything through the C
API (``_LIB``); here the Python objects sit directly on the in-process
engine (io.TpuDataset, models.GBDT) — same surface, no FFI hop. The
``lightgbm_tpu.capi`` module provides the C-API-shaped entry points for
code that wants them.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .io.dataset import Metadata, TpuDataset
from .metrics import create_metrics
from .objectives import create_objective
from .utils import log
from .utils.log import LightGBMError

__all__ = ["Dataset", "Booster", "LightGBMError"]


def _is_pandas_df(data) -> bool:
    try:
        import pandas as pd
        return isinstance(data, pd.DataFrame)
    except ImportError:
        return False


def _is_pandas_series(data) -> bool:
    try:
        import pandas as pd
        return isinstance(data, pd.Series)
    except ImportError:
        return False


def _is_scipy_sparse(data) -> bool:
    try:
        import scipy.sparse as sp
        return sp.issparse(data)
    except ImportError:
        return False


def _data_to_2d(data, feature_name="auto", categorical_feature="auto"):
    """Normalize input to (ndarray[N, F] float64, feature_names,
    categorical_indices). Pandas categorical/object columns are
    factorized like the reference's pandas handling
    (basic.py _data_from_pandas)."""
    cat_idx: List[int] = []
    names: Optional[List[str]] = None
    if _is_pandas_df(data):
        import pandas as pd
        df = data
        if feature_name == "auto":
            names = [str(c) for c in df.columns]
        cat_cols = [i for i, c in enumerate(df.columns)
                    if isinstance(df[c].dtype, pd.CategoricalDtype)
                    or df[c].dtype == object]
        if categorical_feature == "auto":
            cat_idx = cat_cols
        X = np.empty((len(df), df.shape[1]), np.float64)
        for i, c in enumerate(df.columns):
            col = df[c]
            if isinstance(col.dtype, pd.CategoricalDtype):
                codes = col.cat.codes.to_numpy(np.float64)
            elif col.dtype == object:
                codes = pd.Categorical(col).codes.astype(np.float64)
            else:
                X[:, i] = col.to_numpy(np.float64)
                continue
            # cat code -1 means missing -> NaN (reference
            # _data_from_pandas maps it back before binning)
            X[:, i] = np.where(codes < 0, np.nan, codes)
    elif _is_scipy_sparse(data):
        # CSR-native: scipy input stays O(nnz) (io/sparse.py); the
        # densify-vs-CSR route decision is TpuDataset's (it has the
        # config), and the predict paths densify in bounded chunks
        from .io.sparse import SparseMatrix
        X = SparseMatrix.from_scipy(data)
    else:
        X = np.asarray(data, np.float64)
        if X.ndim == 1:
            X = X.reshape(-1, 1)
    if isinstance(feature_name, (list, tuple)):
        names = [str(x) for x in feature_name]
    if isinstance(categorical_feature, (list, tuple)):
        resolved = []
        for c in categorical_feature:
            if isinstance(c, str):
                if names is None or c not in names:
                    raise LightGBMError(
                        f"categorical_feature {c!r} not found in "
                        "feature names")
                resolved.append(names.index(c))
            else:
                resolved.append(int(c))
        cat_idx = resolved
    return X, names, sorted(set(cat_idx))


def _label_to_1d(y) -> np.ndarray:
    if _is_pandas_df(y):
        if y.shape[1] != 1:
            raise LightGBMError("DataFrame for label should be 1-D")
        y = y.iloc[:, 0]
    if _is_pandas_series(y):
        y = y.to_numpy()
    return np.asarray(y, np.float32).reshape(-1)


class Dataset:
    """Dataset for training/validation (basic.py:626-1448 surface).

    Lazily constructed: binning happens on first use (``construct``),
    so ``set_*`` calls and reference linking behave like the C engine's
    deferred ``Dataset::Construct``.
    """

    def __init__(self, data, label=None, reference: "Dataset" = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, silent: bool = False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self.used_indices: Optional[np.ndarray] = None
        self._inner: Optional[TpuDataset] = None
        self._predictor = None      # init-model predictor for init_score

    # -- construction -------------------------------------------------------

    def construct(self) -> "Dataset":
        if self._inner is not None:
            return self
        cfg = Config()
        ref = self.reference
        if ref is not None:
            ref.construct()
            cfg = ref._inner.config
        if self.params:
            cfg = cfg.copy() if ref is not None else cfg
            cfg.set(self.params)

        raw_X = None
        if isinstance(self.data, str):
            from .io.loader import DatasetLoader
            loader = DatasetLoader(cfg)
            self._inner = loader.load_from_file(
                self.data, reference=ref._inner if ref else None)
            if self.label is not None:
                self._inner.metadata.label = _label_to_1d(self.label)
            if self._predictor is not None:
                raw_X, _ = loader.load_predict_matrix(
                    self.data, self._inner.num_total_features)
        else:
            X, names, cat_idx = _data_to_2d(
                self.data, self.feature_name, self.categorical_feature)
            if self.used_indices is not None:
                X = X[self.used_indices]
            meta = self._build_metadata()
            if ref is not None:
                self._inner = ref._inner.create_valid(X, meta)
            else:
                ds = TpuDataset(cfg)
                ds.construct_from_matrix(X, meta, categorical=cat_idx,
                                         feature_names=names)
                self._inner = ds
            raw_X = X
        if self._predictor is not None and raw_X is not None:
            self._apply_init_score_from_predictor(raw_X)
        if self.free_raw_data:
            self.data = None
        return self

    def _build_metadata(self) -> Metadata:
        sub = self.used_indices
        label = (None if self.label is None else _label_to_1d(self.label))
        weight = (None if self.weight is None
                  else np.asarray(self.weight, np.float32).reshape(-1))
        init = (None if self.init_score is None
                else np.asarray(self.init_score, np.float64))
        group = (None if self.group is None
                 else np.asarray(self.group, np.int64).reshape(-1))
        if sub is not None:
            if label is not None:
                label = label[sub]
            if weight is not None:
                weight = weight[sub]
            if init is not None:
                init = init.reshape(len(init), -1)[sub].reshape(-1)
            if group is not None:
                # per-query membership counts (Metadata::Init subset
                # path, metadata.cpp:97-115); group-aware folds keep
                # queries intact so nonzero counts are whole queries
                qb = np.concatenate([[0], np.cumsum(group)])
                qidx = np.searchsorted(qb, sub, side="right") - 1
                counts = np.bincount(qidx, minlength=len(group))
                group = counts[counts > 0]
        return Metadata(label=label, weight=weight, group=group,
                        init_score=init)

    def _apply_init_score_from_predictor(self, raw_X: np.ndarray):
        """Continued training: fold an init model's raw scores into this
        dataset's init_score (basic.py _set_init_score_by_predictor).
        The pre-fold init score is kept so a later predictor swap
        rebases instead of stacking."""
        if not hasattr(self, "_base_init_score"):
            self._base_init_score = self._inner.metadata.init_score
        raw = self._predictor.init_score_for(raw_X)
        base = self._base_init_score
        self._inner.metadata.init_score = (
            raw if base is None else np.asarray(base, np.float64) + raw)

    def _set_predictor(self, predictor) -> None:
        if predictor is self._predictor:
            return
        self._predictor = predictor
        if self._inner is not None and predictor is not None:
            # already constructed (e.g. second train() on the same
            # Dataset): fold now, using the retained raw data
            if self.data is None:
                raise LightGBMError(
                    "Cannot set init model on a constructed Dataset "
                    "whose raw data was freed; use free_raw_data=False")
            if isinstance(self.data, str):
                from .io.loader import DatasetLoader
                loader = DatasetLoader(self._inner.config)
                raw_X, _ = loader.load_predict_matrix(
                    self.data, self._inner.num_total_features)
            else:
                raw_X, _, _ = _data_to_2d(self.data, self.feature_name,
                                          self.categorical_feature)
                if self.used_indices is not None:
                    raw_X = raw_X[self.used_indices]
            self._apply_init_score_from_predictor(raw_X)

    # -- field access (basic.py set_field/get_field) ------------------------

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._inner is not None and label is not None:
            self._inner.metadata.label = _label_to_1d(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None and weight is not None:
            self._inner.metadata.weights = np.asarray(
                weight, np.float32).reshape(-1)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None and group is not None:
            g = np.asarray(group, np.int64).reshape(-1)
            self._inner.metadata.query_boundaries = np.concatenate(
                [[0], np.cumsum(g)]).astype(np.int64)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None and init_score is not None:
            self._inner.metadata.init_score = np.asarray(
                init_score, np.float64)
        return self

    def get_label(self):
        if self._inner is not None:
            return self._inner.metadata.label
        return None if self.label is None else _label_to_1d(self.label)

    def get_weight(self):
        if self._inner is not None:
            return self._inner.metadata.weights
        return self.weight

    def get_init_score(self):
        if self._inner is not None:
            return self._inner.metadata.init_score
        return self.init_score

    def get_group(self):
        if self._inner is not None:
            qb = self._inner.metadata.query_boundaries
            return None if qb is None else np.diff(qb)
        return self.group

    def get_field(self, field_name: str):
        getter = {"label": self.get_label, "weight": self.get_weight,
                  "init_score": self.get_init_score,
                  "group": self.get_group}.get(field_name)
        if getter is None:
            raise LightGBMError(f"Unknown field {field_name!r}")
        return getter()

    def set_field(self, field_name: str, data) -> "Dataset":
        setter = {"label": self.set_label, "weight": self.set_weight,
                  "init_score": self.set_init_score,
                  "group": self.set_group}.get(field_name)
        if setter is None:
            raise LightGBMError(f"Unknown field {field_name!r}")
        return setter(data)

    # -- shape --------------------------------------------------------------

    def num_data(self) -> int:
        self.construct()
        return self._inner.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._inner.num_total_features

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._inner.feature_names)

    # -- derived datasets ---------------------------------------------------

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        """Validation set binned with this Dataset's mappers
        (basic.py:866-900)."""
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params,
                       free_raw_data=self.free_raw_data)

    def subset(self, used_indices: Sequence[int],
               params=None) -> "Dataset":
        """Row subset sharing this Dataset's raw data and bin mappers
        (basic.py:902-926). Requires raw data (free_raw_data=False) or a
        not-yet-constructed Dataset."""
        if self.data is None:
            raise LightGBMError(
                "Cannot subset a Dataset whose raw data was freed; "
                "construct with free_raw_data=False")
        ret = Dataset(self.data, label=self.label,
                      reference=self if self._inner is not None else None,
                      weight=self.weight, group=self.group,
                      init_score=self.init_score,
                      feature_name=self.feature_name,
                      categorical_feature=self.categorical_feature,
                      params=params or self.params,
                      free_raw_data=self.free_raw_data)
        ret.used_indices = np.sort(np.asarray(used_indices, np.int64))
        ret._predictor = self._predictor
        return ret

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if reference is self.reference:
            return self
        if self._inner is not None:
            raise LightGBMError("Cannot set reference after the dataset "
                                "was constructed")
        self.reference = reference
        return self

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        self._inner.save_binary(filename)
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if categorical_feature == "auto":
            # 'auto' means "keep what the Dataset already has"
            # (reference basic.py:1040-1053)
            return self
        if self._inner is not None and \
                list(categorical_feature) != list(
                    self.categorical_feature or []):
            raise LightGBMError("Cannot change categorical_feature after "
                                "the dataset was constructed")
        self.categorical_feature = categorical_feature
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        self.feature_name = feature_name
        if self._inner is not None and isinstance(feature_name,
                                                  (list, tuple)):
            if len(feature_name) != self._inner.num_total_features:
                raise LightGBMError("Length of feature names doesn't equal "
                                    "with num_feature")
            self._inner.feature_names = [str(x) for x in feature_name]
        return self


# -- default metric resolution (src/io/config.cpp GetMetricType) ------------

_DEFAULT_METRIC = {
    "regression": "l2", "regression_l2": "l2", "mean_squared_error": "l2",
    "l2_root": "rmse", "rmse": "rmse",
    "regression_l1": "l1", "mean_absolute_error": "l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape", "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss", "softmax": "multi_logloss",
    "multiclassova": "multi_logloss", "ova": "multi_logloss",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "ndcg",
}


def _resolve_metric_names(cfg: Config) -> List[str]:
    names = [n for n in cfg.metric if n]
    if not names:
        default = _DEFAULT_METRIC.get(cfg.objective)
        return [default] if default else []
    if all(n.lower() in ("none", "null", "na", "custom") for n in names):
        return []
    return names


class Booster:
    """Booster: the trained model driver (basic.py:1450-2415 surface)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False):
        from .models.gbdt import GBDT
        self.params = dict(params) if params else {}
        self.train_set = train_set
        self.valid_sets: List[Dataset] = []
        self.name_valid_sets: List[str] = []
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_data_name = "training"
        self._gbdt: Optional[GBDT] = None
        self.pandas_categorical = None

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance, "
                                f"met {type(train_set).__name__}")
            self._init_from_train_set(train_set)
        elif model_file is not None:
            with open(model_file) as fh:
                model_str = fh.read()
            self._init_from_string(model_str)
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise TypeError("Need at least one training dataset or model "
                            "file or model string to create Booster "
                            "instance")

    # -- init ---------------------------------------------------------------

    def _init_from_train_set(self, train_set: Dataset):
        from .models.boosting import create_boosting
        cfg = Config()
        cfg.set(self.params)
        if cfg.verbosity < 1:
            from .utils.log import set_level
            set_level(max(-1, cfg.verbosity))
        train_set.params = {**self.params, **train_set.params}
        train_set.construct()
        inner = train_set._inner
        objective = create_objective(cfg.objective, cfg)
        if objective is not None:
            objective.init(inner.metadata, inner.num_data)
        self._metric_names = _resolve_metric_names(cfg)
        train_metrics = create_metrics(self._metric_names, cfg,
                                       inner.metadata, inner.num_data)
        self.config = cfg
        self._gbdt = create_boosting(cfg.boosting_type())
        self._gbdt.init(cfg, inner, objective, train_metrics)

    def _init_from_string(self, model_str: str):
        from .models.gbdt import GBDT
        self.config = None
        self._gbdt = GBDT().load_model_from_string(model_str)
        self._metric_names = []

    # -- training -----------------------------------------------------------

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if self._gbdt is None or self.train_set is None:
            raise LightGBMError("Add valid data requires a Booster with "
                                "training data")
        # late-link like basic.py:1540 (valid must share bin mappers);
        # raises if the data was already constructed with other mappers
        data.set_reference(self.train_set)
        # valid sets inherit the train set's init predictor so their
        # scores include the init model (reference set_reference chain)
        data._set_predictor(self.train_set._predictor)
        data.construct()
        metrics = create_metrics(self._metric_names, self.config,
                                 data._inner.metadata, data._inner.num_data)
        self._gbdt.add_valid_data(data._inner, metrics, name)
        self.valid_sets.append(data)
        self.name_valid_sets.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None,
               fobj=None) -> bool:
        """One boosting iteration; True when no further split was
        possible (basic.py:1693-1746)."""
        if train_set is not None and train_set is not self.train_set:
            raise LightGBMError("Replacing the train set mid-training is "
                                "not supported; create a new Booster")
        if fobj is None:
            return self._gbdt.train_one_iter()
        grad, hess = fobj(self.__inner_predict(0), self.train_set)
        return self.__boost(grad, hess)

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, np.float32)
        hess = np.asarray(hess, np.float32)
        k = self._gbdt.num_tree_per_iteration
        n = self._gbdt._n
        if grad.size != k * n:
            raise ValueError(
                f"Lengths of gradient({grad.size}) don't equal to "
                f"num_data*num_class({k * n})")
        return self._gbdt.train_one_iter(grad.reshape(k, n),
                                         hess.reshape(k, n))

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """ResetConfig subset: training-time resettable parameters
        (gbdt.cpp ResetConfig)."""
        if self.config is not None:
            self.config.set(params)
            self._gbdt.shrinkage_rate = self.config.learning_rate
            self._gbdt._setup_grower()
        self.params.update(params)
        return self

    # -- evaluation ---------------------------------------------------------

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def eval_train(self, feval=None) -> List[tuple]:
        return self.__eval(0, self._train_data_name, feval)

    def eval_valid(self, feval=None) -> List[tuple]:
        out = []
        for i, name in enumerate(self.name_valid_sets):
            out.extend(self.__eval(i + 1, name, feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None) -> List[tuple]:
        if data is self.train_set:
            return self.eval_train(feval)
        for i, vs in enumerate(self.valid_sets):
            if data is vs:
                return self.__eval(i + 1, name, feval)
        raise LightGBMError("Data should be added with add_valid first")

    def __eval(self, data_idx: int, name: str, feval=None) -> List[tuple]:
        out = [(name, mname, val, bigger)
               for mname, val, bigger in self._gbdt.get_eval_at(data_idx)]
        if feval is not None:
            ds = self.train_set if data_idx == 0 \
                else self.valid_sets[data_idx - 1]
            ret = feval(self.__inner_predict(data_idx), ds)
            if isinstance(ret, list):
                for fname, val, bigger in ret:
                    out.append((name, fname, val, bigger))
            elif ret is not None:
                fname, val, bigger = ret
                out.append((name, fname, val, bigger))
        return out

    def eval_dispatch_async(self, include_train: bool):
        """Dispatch this round's evaluations as device reductions and
        begin their host copies WITHOUT blocking; returns opaque
        handles for eval_materialize, or None when any dataset's
        metrics lack device implementations.

        The engine's training loop uses this to pipeline: iteration
        i+1's fused step overlaps the RPC that fetches iteration i's
        metric scalars, so per-iteration evaluation (early stopping)
        costs latency, not throughput."""
        idxs = ([(0, self._train_data_name)] if include_train else [])
        idxs += [(i + 1, nm) for i, nm in enumerate(self.name_valid_sets)]
        if not idxs:
            return None
        g = self._gbdt
        handles = []
        for di, name in idxs:
            metrics = (g.training_metrics if di == 0
                       else g.valid_metrics[di - 1])
            fn = g._device_eval_fn(di, metrics)
            if fn is None:
                return None
            scores = (g.train_scores() if di == 0
                      else g._valid_scores[di - 1])
            arr = fn(scores)
            try:
                arr.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
            handles.append((name, metrics, arr))
        return handles

    @staticmethod
    def eval_materialize(handles) -> List[tuple]:
        """Block on eval_dispatch_async handles -> the evaluation
        result list [(data_name, metric_name, value, bigger_better)]."""
        out = []
        for name, metrics, arr in handles:
            vals = np.asarray(arr)
            out.extend((name, m.name, float(v), m.bigger_is_better)
                       for m, v in zip(metrics, vals))
        return out

    def __inner_predict(self, data_idx: int) -> np.ndarray:
        """Raw scores for train (0) or valid set (1..); flattened
        class-major for multiclass like the reference."""
        scores = (self._gbdt.train_scores() if data_idx == 0
                  else self._gbdt._valid_scores[data_idx - 1])
        raw = np.asarray(scores, np.float64)
        return raw[0] if raw.shape[0] == 1 else raw.reshape(-1)

    # -- prediction ---------------------------------------------------------

    def predict(self, data, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, data_has_header: bool = False,
                is_reshape: bool = True, **kwargs) -> np.ndarray:
        if isinstance(data, str):
            from .io.loader import DatasetLoader
            cfg = Config()
            cfg.header = data_has_header
            loader = DatasetLoader(cfg)
            X, _ = loader.load_predict_matrix(
                data, self._gbdt.max_feature_idx + 1)
        else:
            X, _, _ = _data_to_2d(data)
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        pred_kw = {k: v for k, v in kwargs.items()
                   if k.startswith("pred_early_stop")}
        if pred_leaf:
            return self._gbdt.predict_leaf_index(X, num_iteration)
        if pred_contrib:
            return self._gbdt.predict_contrib(X, num_iteration)
        if raw_score:
            return self._gbdt.predict_raw(X, num_iteration, **pred_kw)
        return self._gbdt.predict(X, num_iteration, **pred_kw)

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit the existing model's leaf values on new data
        (basic.py Booster.refit -> GBDT::RefitTree)."""
        from .models.gbdt import GBDT
        X, _, _ = _data_to_2d(data)
        y = _label_to_1d(label)
        cfg = Config()
        params = dict(self.params)
        params.pop("refit_decay_rate", None)
        cfg.set(params)
        cfg.refit_decay_rate = decay_rate
        if not params.get("objective") and self._gbdt.objective is not None:
            cfg.objective = self._gbdt.objective.name
        model_str = self.model_to_string()
        new = GBDT()
        new.load_model_from_string(model_str)
        # categorical columns are recoverable from the model header:
        # categorical feature_infos are ':'-joined category lists,
        # numerical are '[lo:hi]' ranges (io/dataset.py feature_infos)
        cats = [i for i, info in enumerate(new.feature_infos)
                if info and info != "none" and not info.startswith("[")]
        inner = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=y), categorical=cats)
        objective = create_objective(cfg.objective, cfg)
        if objective is not None:
            objective.init(inner.metadata, inner.num_data)
        new.init_from_loaded(cfg, inner, objective, [])
        new.refit_existing(decay_rate)
        out = Booster(model_str=model_str)   # normal ctor: one source
        out._gbdt = new                      # of truth for attributes
        out.params = params
        out.config = cfg
        out.pandas_categorical = self.pandas_categorical
        return out

    # -- introspection ------------------------------------------------------

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration

    def num_trees(self) -> int:
        return len(self._gbdt.models)

    @property
    def num_devices(self) -> int:
        """Devices the training step spans (mesh size for the sharded
        tree learners, 1 for serial)."""
        return self._gbdt.num_devices

    @property
    def learner_mode(self) -> str:
        """Resolved tree learner (may be 'serial' after fallback)."""
        return self._gbdt.learner_mode

    def leaves_and_waves(self, start_group: int = 0):
        """Per-iteration leaf/wave counts (ONE stacked download) —
        the public reporting surface drivers use (engine/bench)."""
        return self._gbdt.leaves_and_waves(start_group)

    def record_comm_bytes(self, recorder, waves):
        """Attach per-iteration psum payload bytes to a RunRecorder
        (None off the data-parallel path)."""
        return self._gbdt.record_comm_bytes(recorder, waves)

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_model_per_iteration()

    def num_feature(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names)

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = 0) -> np.ndarray:
        imp = self._gbdt.feature_importance(importance_type, iteration)
        if importance_type == "split":
            return imp.astype(np.int32)
        return imp

    # -- serialization ------------------------------------------------------

    def save_model(self, filename: str, num_iteration: int = -1,
                   start_iteration: int = 0) -> "Booster":
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        self._gbdt.save_model_to_file(filename, start_iteration,
                                      num_iteration)
        return self

    def model_to_string(self, num_iteration: int = -1,
                        start_iteration: int = 0) -> str:
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        return self._gbdt.model_to_string(start_iteration, num_iteration)

    def dump_model(self, num_iteration: int = -1,
                   start_iteration: int = 0) -> dict:
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        return self._gbdt.dump_model(start_iteration, num_iteration)

    # -- pickling (reference pickles via model string, basic.py:1476) -------

    def __getstate__(self):
        state = {
            "params": self.params,
            "best_iteration": self.best_iteration,
            "best_score": self.best_score,
            "model_str": self.model_to_string(),
            "pandas_categorical": self.pandas_categorical,
        }
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self.pandas_categorical = state.get("pandas_categorical")
        self.train_set = None
        self.valid_sets = []
        self.name_valid_sets = []
        self._train_data_name = "training"
        self._init_from_string(state["model_str"])

    def model_from_string(self, model_str: str,
                          verbose: bool = True) -> "Booster":
        """Replace this booster's model with one parsed from a string
        (basic.py:2049-2068)."""
        self._init_from_string(model_str)
        return self

    def save_checkpoint(self, directory: str) -> Optional[str]:
        """Write a resumable checkpoint bundle (utils/checkpoint.py):
        the model text plus the training state a restart needs to
        continue bit-identically. Returns the path, or None on a
        failure (which warns and never raises — the engine.train
        periodic wiring calls this mid-run)."""
        return self._gbdt.write_checkpoint(directory)

    def free_dataset(self) -> "Booster":
        self.train_set = None
        self.valid_sets = []
        return self

    def free_network(self) -> "Booster":
        return self

    def _to_predictor(self) -> "_InnerPredictor":
        return _InnerPredictor(booster=self)


class _InnerPredictor:
    """Init-model predictor for continued training
    (basic.py:356-624 _InnerPredictor). Carries a trained model's raw
    predictions so they can be folded into a Dataset's init_score."""

    def __init__(self, model_file: Optional[str] = None,
                 booster: Optional[Booster] = None,
                 model_str: Optional[str] = None):
        from .models.gbdt import GBDT
        if booster is not None:
            self._gbdt = booster._gbdt
        elif model_file is not None:
            with open(model_file) as fh:
                model_str = fh.read()
            self._gbdt = GBDT().load_model_from_string(
                model_str, source=model_file)
        elif model_str is not None:
            self._gbdt = GBDT().load_model_from_string(model_str)
        else:
            raise TypeError("Need model_file, model_str or booster")

    @property
    def num_total_iteration(self) -> int:
        return self._gbdt.current_iteration

    def init_score_for(self, X) -> np.ndarray:
        """Raw predictions flattened class-major — the init_score layout
        (metadata.cpp init_score_ is [class][row])."""
        from .io.sparse import SparseMatrix
        if not isinstance(X, SparseMatrix):
            X = np.asarray(X, np.float64)
        raw = self._gbdt.predict_raw(X)
        if raw.ndim == 2:          # [N, K] -> class-major flat
            return raw.T.reshape(-1).astype(np.float64)
        return raw.astype(np.float64)
