"""Training routines: train() and cv().

TPU-native counterpart of the reference python engine
(reference: python-package/lightgbm/engine.py:19-332 train/cv,
engine.py:240-268 CVBooster). Continued training follows the reference
protocol: the init model's raw predictions are folded into the train
set's init_score (engine.py:122-135), and the returned booster holds
only the newly trained trees.
"""
from __future__ import annotations

import collections
import copy
from operator import attrgetter
from typing import Dict, List, Optional

import numpy as np

from . import callback
from .basic import Booster, Dataset, LightGBMError, _InnerPredictor

__all__ = ["train", "cv", "CVBooster"]

_NUM_BOOST_ROUND_ALIASES = [
    "num_iterations", "num_iteration", "n_iter", "num_tree", "num_trees",
    "num_round", "num_rounds", "num_boost_round", "n_estimators"]
_EARLY_STOP_ALIASES = [
    "early_stopping_round", "early_stopping_rounds", "early_stopping"]


def train(params: Dict, train_set: Dataset, num_boost_round: int = 100,
          valid_sets=None, valid_names=None, fobj=None, feval=None,
          init_model=None, feature_name="auto",
          categorical_feature="auto", early_stopping_rounds=None,
          evals_result=None, verbose_eval=True, learning_rates=None,
          keep_training_booster=False, callbacks=None) -> Booster:
    """Train one model (engine.py:19-238 semantics and defaults)."""
    params = copy.deepcopy(params) if params else {}
    for alias in _NUM_BOOST_ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
            break
    for alias in _EARLY_STOP_ALIASES:
        if alias in params and params[alias] is not None:
            early_stopping_rounds = int(params.pop(alias))
            break
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")

    if isinstance(init_model, str):
        predictor = _InnerPredictor(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor = init_model._to_predictor()
    else:
        predictor = None
    init_iteration = predictor.num_total_iteration if predictor else 0

    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    train_set.params.update(params)
    train_set._set_predictor(predictor)
    train_set.set_feature_name(feature_name)
    train_set.set_categorical_feature(categorical_feature)

    is_valid_contain_train = False
    train_data_name = "training"
    reduced_valid_sets: List[Dataset] = []
    name_valid_sets: List[str] = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                is_valid_contain_train = True
                if valid_names is not None:
                    train_data_name = valid_names[i]
                continue
            if not isinstance(valid_data, Dataset):
                raise TypeError("Training only accepts Dataset object")
            valid_data.set_reference(train_set)
            reduced_valid_sets.append(valid_data)
            if valid_names is not None and len(valid_names) > i:
                name_valid_sets.append(valid_names[i])
            else:
                name_valid_sets.append("valid_" + str(i))

    if callbacks is None:
        callbacks = set()
    else:
        for i, cb in enumerate(callbacks):
            cb.__dict__.setdefault("order", i - len(callbacks))
        callbacks = set(callbacks)
    if verbose_eval is True:
        callbacks.add(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        callbacks.add(callback.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None:
        callbacks.add(callback.early_stopping(
            early_stopping_rounds, verbose=bool(verbose_eval)))
    if learning_rates is not None:
        callbacks.add(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        callbacks.add(callback.record_evaluation(evals_result))

    # run report (obs/recorder.py): when tpu_run_report is set, a
    # RunRecorder spans the iterations via an internal after-iteration
    # callback (defined in the callback module, so the pipelined-eval
    # fast path stays eligible) and serializes the run at the end
    recorder = None
    run_report = str(params.get("tpu_run_report", "") or "")
    if run_report:
        from .obs.recorder import RunRecorder
        recorder = RunRecorder(
            path=run_report,
            watchdog_factor=float(
                params.get("tpu_watchdog_factor", 8.0) or 0.0),
            meta={"driver": "engine.train",
                  "num_boost_round": num_boost_round,
                  "init_iteration": init_iteration})
        callbacks.add(callback.record_run(recorder))

    callbacks_before_iter = sorted(
        (cb for cb in callbacks if getattr(cb, "before_iteration", False)),
        key=attrgetter("order"))
    callbacks_after_iter = sorted(
        (cb for cb in callbacks if not getattr(cb, "before_iteration",
                                               False)),
        key=attrgetter("order"))

    booster = Booster(params=params, train_set=train_set)
    if recorder is not None:
        # free-form env section of the report: the resolved mesh size
        # (the learner may have fallen back to serial on one device)
        recorder.meta["mesh_devices"] = booster.num_devices
        recorder.meta["tree_learner"] = booster.learner_mode
    if is_valid_contain_train:
        booster.set_train_data_name(train_data_name)
    for valid_set, name in zip(reduced_valid_sets, name_valid_sets):
        booster.add_valid(valid_set, name)
    booster.best_iteration = 0

    # xprof capture of the training loop (tpu_profile_dir +
    # tpu_profile_iters; obs/profiler.py — the device-level analog of
    # the utils/timing.py wall timers, readable with tensorboard/xprof)
    from .obs.profiler import ProfileWindow
    profile = ProfileWindow(
        str(params.get("tpu_profile_dir", "") or ""),
        int(params.get("tpu_profile_iters", 0) or 0))
    if recorder is not None:
        # started here (not at construction) so an exception during
        # booster/valid-set setup can't leak the log run-prefix
        recorder.start()
    try:
        evaluation_result_list = _train_loop(
            booster, params, init_iteration, num_boost_round,
            callbacks_before_iter, callbacks_after_iter, fobj, feval,
            valid_sets, is_valid_contain_train, profile,
            ckpt_dir=str(params.get("tpu_checkpoint_dir", "") or ""),
            ckpt_freq=int(params.get("tpu_checkpoint_freq", 0) or 0))
    finally:
        profile.close()
        if recorder is not None:
            # distributed runs: per-iteration leaf/wave counts and the
            # psum payload bytes (models/gbdt.py public helpers; one
            # stacked download, only paid when a report is written)
            leaves = waves = None
            try:
                if init_iteration == 0:
                    # continued training skips: the recorder's
                    # iteration keys start at init_iteration + 1 and
                    # would misalign with the group-0-based lists
                    leaves, waves = booster.leaves_and_waves()
                    if waves:
                        booster.record_comm_bytes(recorder, waves)
            except Exception:       # noqa: BLE001 — telemetry must
                pass                # never fail the training result
            try:
                from .ops import predict_cache, step_cache
                recorder.meta["step_cache"] = step_cache.stats()
                recorder.meta["predict_cache"] = predict_cache.stats()
            except Exception:       # noqa: BLE001
                pass
            recorder.finish(
                leaves_per_iteration=leaves or None,
                waves_per_iteration=waves or None,
                extra={"best_iteration": booster.best_iteration})
    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for dataset_name, eval_name, score, _ in evaluation_result_list:
        booster.best_score[dataset_name][eval_name] = score
    if not keep_training_booster:
        booster.free_dataset()
    return booster


def _train_loop(booster, params, init_iteration, num_boost_round,
                callbacks_before_iter, callbacks_after_iter, fobj,
                feval, valid_sets, is_valid_contain_train,
                profile=None, ckpt_dir: str = "", ckpt_freq: int = 0):
    evaluation_result_list: List[tuple] = []
    want_eval = valid_sets is not None or feval is not None
    # pipelined evaluation: when every metric evaluates on device
    # (Booster.eval_dispatch_async), iteration i's metric scalars are
    # fetched WHILE iteration i+1 computes, so per-round evaluation
    # (early stopping) costs RPC latency, not training throughput.
    # Custom fevals need host scores -> synchronous path. USER
    # callbacks also force the synchronous path: under pipelining an
    # after-iteration callback for iteration i runs while the booster
    # already holds iteration i+1's tree, so a user callback that
    # snapshots the model or calls eval would silently observe the
    # lookahead iteration. The built-in callbacks (print/record/early
    # stopping) only read evaluation_result_list, which IS iteration
    # i's, so they pipeline safely.
    builtin_only = all(
        getattr(cb, "__module__", None) == callback.__name__
        for cb in callbacks_after_iter)
    pipelined = want_eval and feval is None and builtin_only
    end_iteration = init_iteration + num_boost_round
    pending = None                    # (iteration, async eval handles)

    def run_after_cbs(iteration, results):
        """True = early stop (the extra lookahead iteration, if any,
        is trimmed by the caller)."""
        nonlocal evaluation_result_list
        evaluation_result_list = results
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(
                    model=booster, params=params, iteration=iteration,
                    begin_iteration=init_iteration,
                    end_iteration=end_iteration,
                    evaluation_result_list=results))
        except callback.EarlyStopException as early_stop:
            booster.best_iteration = early_stop.best_iteration + 1
            evaluation_result_list = early_stop.best_score
            return True
        return False

    for i in range(init_iteration, end_iteration):
        for cb in callbacks_before_iter:
            cb(callback.CallbackEnv(
                model=booster, params=params, iteration=i,
                begin_iteration=init_iteration,
                end_iteration=end_iteration,
                evaluation_result_list=None))

        if profile is not None:
            profile.iter_begin(i - init_iteration + 1)
        booster.update(fobj=fobj)
        if profile is not None:
            profile.iter_end(i - init_iteration + 1)
        # resumable checkpoint bundle (utils/checkpoint.py): atomic
        # write, pruned, warns-never-raises on failure. Written only
        # AFTER this iteration's evals are processed (the gbdt.train
        # flush-first rule): a bundle must never capture a tree an
        # early stop is about to roll back.
        ckpt_due = (ckpt_freq > 0 and ckpt_dir
                    and (i + 1 - init_iteration) % ckpt_freq == 0)

        handles = (booster.eval_dispatch_async(is_valid_contain_train)
                   if pipelined else None)
        if handles is None:
            pipelined = False
            results = []
            if want_eval:
                if is_valid_contain_train:
                    results.extend(booster.eval_train(feval))
                results.extend(booster.eval_valid(feval))
            if run_after_cbs(i, results):
                return evaluation_result_list
            if ckpt_due:
                booster.save_checkpoint(ckpt_dir)
            continue
        if pending is not None:
            pi, ph = pending
            if run_after_cbs(pi, booster.eval_materialize(ph)):
                # the lookahead iteration trained past the stop point
                booster.rollback_one_iter()
                return evaluation_result_list
        pending = (i, handles)
        if ckpt_due:
            # drain the one-deep lookahead so the stop decision for
            # THIS iteration lands before the bundle is written
            pi, ph = pending
            pending = None
            if run_after_cbs(pi, booster.eval_materialize(ph)):
                return evaluation_result_list
            booster.save_checkpoint(ckpt_dir)
    if pending is not None:
        pi, ph = pending
        run_after_cbs(pi, booster.eval_materialize(ph))
    return evaluation_result_list


class CVBooster:
    """Holds all fold boosters of a cv run (engine.py:240-268)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, fpreproc=None, stratified: bool = False,
                  shuffle: bool = True) -> CVBooster:
    """Fold construction (engine.py:271-324): group-aware for ranking,
    stratified for classification when requested."""
    full_data.construct()
    num_data = full_data.num_data()
    group = full_data.get_group()
    if folds is not None:
        if not hasattr(folds, "__iter__"):
            folds = folds.split(X=np.zeros(num_data),
                                y=full_data.get_label())
        else:
            # normalize: elements are either (train_idx, test_idx)
            # pairs (python convention) or bare TEST-index arrays (the
            # reference R package's folds semantics, lgb.cv.R) whose
            # train side is the complement
            all_idx = np.arange(num_data)
            norm = []
            for fd in folds:
                if (isinstance(fd, (tuple, list)) and len(fd) == 2
                        and all(hasattr(x, "__len__") for x in fd)):
                    norm.append((np.asarray(fd[0], np.int64),
                                 np.asarray(fd[1], np.int64)))
                else:
                    te = np.asarray(list(fd), np.int64)
                    norm.append((np.setdiff1d(all_idx, te), te))
            folds = norm
    elif group is not None:
        # ranking: keep queries intact per fold (GroupKFold analog)
        group = np.asarray(group, np.int64)
        flatted_group = np.repeat(np.arange(len(group)), group)
        try:
            from sklearn.model_selection import GroupKFold
            folds = GroupKFold(n_splits=nfold).split(
                X=np.zeros(num_data), groups=flatted_group)
        except ImportError:
            raise LightGBMError(
                "scikit-learn is required for group-aware cv")
    elif stratified:
        try:
            from sklearn.model_selection import StratifiedKFold
        except ImportError:
            raise LightGBMError(
                "scikit-learn is required for stratified cv")
        skf = StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                              random_state=seed if shuffle else None)
        folds = skf.split(X=np.zeros(num_data), y=full_data.get_label())
    else:
        rng = np.random.default_rng(seed)
        randidx = (rng.permutation(num_data) if shuffle
                   else np.arange(num_data))
        kstep = int(num_data / nfold)
        test_id = [randidx[i * kstep:
                           (i + 1) * kstep if i + 1 < nfold else num_data]
                   for i in range(nfold)]
        folds = ((np.setdiff1d(randidx, tid, assume_unique=True), tid)
                 for tid in test_id)

    ret = CVBooster()
    for train_idx, test_idx in folds:
        train_sub = full_data.subset(np.sort(train_idx))
        valid_sub = full_data.subset(np.sort(test_idx))
        valid_sub.reference = train_sub
        if fpreproc is not None:
            train_sub, valid_sub, tparam = fpreproc(
                train_sub, valid_sub, params.copy())
        else:
            tparam = params
        cvbooster = Booster(params=tparam, train_set=train_sub)
        cvbooster.add_valid(valid_sub, "valid")
        ret.append(cvbooster)
    return ret


def _agg_cv_result(raw_results):
    """Aggregate per-fold eval results (engine.py:327-338)."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = one_line[1]
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k],
             float(np.std(v))) for k, v in cvmap.items()]


def cv(params: Dict, train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv: bool = True, seed: int = 0, callbacks=None) -> Dict:
    """K-fold cross-validation (engine.py:341-501); returns the
    eval-history dict {metric-mean: [...], metric-stdv: [...]}."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = copy.deepcopy(params) if params else {}
    for alias in _NUM_BOOST_ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
            break
    for alias in _EARLY_STOP_ALIASES:
        if alias in params and params[alias] is not None:
            early_stopping_rounds = int(params.pop(alias))
            break
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    if metrics is not None:
        params["metric"] = metrics

    if isinstance(init_model, str):
        predictor = _InnerPredictor(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor = init_model._to_predictor()
    else:
        predictor = None

    if train_set.get_label() is None and not isinstance(train_set.data, str):
        raise LightGBMError("Labels should not be None")
    train_set.params.update(params)
    train_set._set_predictor(predictor)
    train_set.set_feature_name(feature_name)
    train_set.set_categorical_feature(categorical_feature)
    if train_set.free_raw_data and not isinstance(train_set.data, str):
        # cv needs raw rows for fold subsets
        train_set.free_raw_data = False

    if stratified and params.get("objective") not in (
            "binary", "multiclass", "multiclassova", None) \
            and train_set.get_group() is None:
        stratified = False

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, folds, nfold, params, seed,
                            fpreproc=fpreproc, stratified=stratified,
                            shuffle=shuffle)

    if callbacks is None:
        callbacks = set()
    else:
        for i, cb in enumerate(callbacks):
            cb.__dict__.setdefault("order", i - len(callbacks))
        callbacks = set(callbacks)
    if early_stopping_rounds is not None:
        callbacks.add(callback.early_stopping(
            early_stopping_rounds, verbose=False))
    if verbose_eval is True:
        callbacks.add(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval,
                                                          bool):
        callbacks.add(callback.print_evaluation(verbose_eval, show_stdv))

    callbacks_before_iter = sorted(
        (cb for cb in callbacks if getattr(cb, "before_iteration", False)),
        key=attrgetter("order"))
    callbacks_after_iter = sorted(
        (cb for cb in callbacks if not getattr(cb, "before_iteration",
                                               False)),
        key=attrgetter("order"))

    for i in range(num_boost_round):
        for cb in callbacks_before_iter:
            cb(callback.CallbackEnv(
                model=cvfolds, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        cvfolds.update(fobj=fobj)
        res = _agg_cv_result(cvfolds.eval_valid(feval))
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(
                    model=cvfolds, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=res))
        except callback.EarlyStopException as early_stop:
            cvfolds.best_iteration = early_stop.best_iteration + 1
            for k in list(results):
                results[k] = results[k][:cvfolds.best_iteration]
            break
    return dict(results)
