"""C-API-shaped entry points.

TPU-native counterpart of the reference C API (reference:
src/c_api.cpp:47-1568, include/LightGBM/c_api.h). The reference exports
a C ABI because its engine is C++; here the engine is in-process
JAX/Python, so the same surface is exposed as Python functions with the
LGBM_* names and c_api semantics: handles are opaque objects, datasets
are constructed raw-then-finished-by-first-booster, boosters train one
iteration at a time. Out-parameters become return values; everything
else (dtype tags, predict tags, field names, parameter strings) matches
c_api.h so ports of C callers (e.g. the fork's cache-admission driver,
src/test.cpp) transliterate line by line.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from .basic import _DEFAULT_METRIC, _resolve_metric_names
from .config import Config, param_dict_to_str
from .io.dataset import Metadata, TpuDataset
from .io.sparse import SparseMatrix, warn_dense_cliff
from .metrics import create_metrics
from .models.boosting import create_boosting
from .objectives import create_objective
from .utils import log
from .utils.log import LightGBMError

# dtype tags (c_api.h:20-27)
C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

# predict tags (c_api.h:29-35)
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3


def _params_to_config(parameters) -> Config:
    cfg = Config()
    if isinstance(parameters, str):
        cfg.set(Config.str2map(parameters))
    elif isinstance(parameters, dict):
        cfg.set({k: str(v) for k, v in parameters.items()})
    elif parameters:
        raise LightGBMError("parameters must be a dict or 'k=v' string")
    return cfg


class _DatasetHandle:
    """Raw matrix + metadata; binning happens when the first booster
    (or reference link) construction needs it (c_api.cpp Dataset
    creation is likewise deferred to ConstructFromSampleData)."""

    def __init__(self, X, cfg: Config,
                 reference: Optional["_DatasetHandle"] = None,
                 ring=None):
        # CSR-native input (io/sparse.py) stays sparse end to end; the
        # route decision (densify vs CSR) is TpuDataset's at construct
        self.X = (X if isinstance(X, SparseMatrix)
                  else np.asarray(X, np.float64))
        self.cfg = cfg
        self.reference = reference
        self.fields: Dict[str, np.ndarray] = {}
        self._inner: Optional[TpuDataset] = None
        # optional io/ingest.ChunkRing: a windowed retrain driver
        # (lrb.py) keeps its training chunks device-resident across
        # windows instead of re-uploading the padded chunk every time
        self.ring = ring

    def construct(self) -> TpuDataset:
        if self._inner is None:
            meta = Metadata(
                label=self.fields.get("label"),
                weight=self.fields.get("weight"),
                group=self.fields.get("group"),
                init_score=self.fields.get("init_score"))
            cats = _parse_cat_spec(self.cfg)
            if self.reference is not None:
                self._inner = self.reference.construct() \
                    .create_valid(self.X, meta)
            else:
                ds = TpuDataset(self.cfg)
                ds.construct_from_matrix(
                    self.X, meta, categorical=cats,
                    mappers=getattr(self, "premade_mappers", None),
                    ring=self.ring)
                self._inner = ds
            names = getattr(self, "feature_names", None)
            if names:
                self._inner.feature_names = list(names)
        return self._inner


def _parse_cat_spec(cfg: Config) -> List[int]:
    spec = cfg.categorical_feature
    if not spec:
        return []
    return [int(x) for x in str(spec).split(",") if x.strip()]


def _csc_to_dense(col_ptr, indices, data, num_row: int,
                  num_col: int) -> np.ndarray:
    """Explicit dense fallback for column-sparse input — the >4 GiB
    cliff guard (io/sparse.py warn_dense_cliff) fires HERE and in
    ``_csr_to_dense``, through one shared helper (the CSC path used to
    lack it)."""
    col_ptr = np.asarray(col_ptr, np.int64)
    indices = np.asarray(indices, np.int64)
    data = np.asarray(data, np.float64)
    warn_dense_cliff(int(num_row), int(num_col), int(data.size))
    X = np.zeros((int(num_row), int(num_col)), np.float64)
    for j in range(int(num_col)):
        sl = slice(int(col_ptr[j]), int(col_ptr[j + 1]))
        X[indices[sl], j] = data[sl]
    return X


def _csr_to_dense(indptr, indices, data, num_col: int) -> np.ndarray:
    """Explicit dense fallback for row-sparse input (push-rows blocks
    and callers that want the matrix); genuinely sparse datasets take
    the CSR-native route (io/sparse.py) and never come through here."""
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices, np.int64)
    data = np.asarray(data, np.float64)
    n = len(indptr) - 1
    warn_dense_cliff(n, int(num_col), int(data.size))
    X = np.zeros((n, num_col), np.float64)
    rows = np.repeat(np.arange(n), np.diff(indptr))
    X[rows, indices[:len(rows)]] = data[:len(rows)]
    return X


# ---------------------------------------------------------------------------
# Dataset API (c_api.cpp:215-505)
# ---------------------------------------------------------------------------

def _mat_to_2d(data, nrow, ncol, is_row_major) -> np.ndarray:
    X = np.asarray(data, np.float64)
    if X.ndim == 1:
        # flat buffers honor is_row_major like the C API (c_api.cpp
        # RowFunctionFromDenseMatric); 2-D numpy inputs already carry
        # their own layout
        X = X.reshape(int(nrow), int(ncol)) if is_row_major \
            else X.reshape(int(ncol), int(nrow)).T
    return X


def LGBM_DatasetCreateFromMat(data, data_type=C_API_DTYPE_FLOAT64,
                              nrow=None, ncol=None, is_row_major=1,
                              parameters="", reference=None,
                              ring=None):
    """c_api.cpp:345 LGBM_DatasetCreateFromMat. ``ring`` is a
    Python-level extension (io/ingest.ChunkRing): windowed retrain
    drivers pass their device-resident chunk ring so same-geometry
    re-ingest uploads only live rows."""
    X = _mat_to_2d(data, nrow, ncol, is_row_major)
    return _DatasetHandle(X, _params_to_config(parameters), reference,
                          ring=ring)


def LGBM_DatasetCreateFromCSR(indptr, indptr_type, indices, data,
                              data_type, nindptr, nelem, num_col,
                              parameters="", reference=None):
    """c_api.cpp:268 LGBM_DatasetCreateFromCSR — CSR-native: the input
    stays O(nnz) on the host (io/sparse.py SparseMatrix); TpuDataset
    densifies only when density exceeds ``sparse_threshold`` (the
    reference keeps CSR through sampling too, c_api.cpp:506)."""
    sm = SparseMatrix.from_csr(indptr, indices, data, int(num_col))
    return _DatasetHandle(sm, _params_to_config(parameters), reference)


def LGBM_DatasetCreateFromFile(filename: str, parameters="",
                               reference=None):
    """c_api.cpp:215."""
    from .io.loader import DatasetLoader
    cfg = _params_to_config(parameters)
    h = _DatasetHandle(np.zeros((0, 0)), cfg,
                       reference)
    loader = DatasetLoader(cfg)
    h._inner = loader.load_from_file(
        filename,
        reference=reference.construct() if reference else None)
    return h


def LGBM_DatasetSetField(handle: _DatasetHandle, field_name: str,
                         field_data, num_element=None,
                         dtype=C_API_DTYPE_FLOAT32):
    """c_api.cpp:436."""
    arr = np.asarray(field_data)
    handle.fields[field_name] = arr
    if handle._inner is not None:
        md = handle._inner.metadata
        if field_name == "label":
            md.label = arr.astype(np.float32).reshape(-1)
        elif field_name == "weight":
            md.weights = arr.astype(np.float32).reshape(-1)
        elif field_name == "init_score":
            md.init_score = arr.astype(np.float64)
        elif field_name == "group":
            g = arr.astype(np.int64).reshape(-1)
            md.query_boundaries = np.concatenate(
                [[0], np.cumsum(g)]).astype(np.int64)
        else:
            raise LightGBMError(f"Unknown field {field_name!r}")
    return 0


def LGBM_DatasetGetField(handle: _DatasetHandle, field_name: str):
    """c_api.cpp:459 — returns the array (out-params -> return)."""
    if handle._inner is not None:
        md = handle._inner.metadata
        got = {"label": md.label, "weight": md.weights,
               "init_score": md.init_score}.get(field_name)
        if got is not None:
            return got
    return handle.fields.get(field_name)


def LGBM_DatasetGetNumData(handle: _DatasetHandle) -> int:
    return (handle._inner.num_data if handle._inner is not None
            else handle.X.shape[0])


def LGBM_DatasetGetNumFeature(handle: _DatasetHandle) -> int:
    return (handle._inner.num_total_features
            if handle._inner is not None else handle.X.shape[1])


def LGBM_DatasetSaveBinary(handle: _DatasetHandle, filename: str):
    handle.construct().save_binary(filename)
    return 0


def LGBM_DatasetFree(handle: _DatasetHandle):
    handle._inner = None
    handle.X = None
    return 0


# ---------------------------------------------------------------------------
# Booster API (c_api.cpp:506-1200)
# ---------------------------------------------------------------------------

class _BoosterHandle:
    def __init__(self, gbdt, cfg: Config, train: Optional[_DatasetHandle]):
        self.gbdt = gbdt
        self.cfg = cfg
        self.train = train


def LGBM_BoosterCreate(train_data: _DatasetHandle, parameters="",
                       out=None) -> _BoosterHandle:
    """c_api.cpp:506."""
    cfg = _params_to_config(parameters)
    inner = train_data.construct()
    objective = create_objective(cfg.objective, cfg)
    if objective is not None:
        objective.init(inner.metadata, inner.num_data)
    metric_names = _resolve_metric_names(cfg)
    train_metrics = []
    if cfg.is_provide_training_metric:
        train_metrics = create_metrics(metric_names, cfg, inner.metadata,
                                       inner.num_data)
    gbdt = create_boosting(cfg.boosting_type())
    gbdt.init(cfg, inner, objective, train_metrics)
    return _BoosterHandle(gbdt, cfg, train_data)


def LGBM_BoosterCreateFromModelfile(filename: str) -> _BoosterHandle:
    """c_api.cpp:527."""
    from .models.gbdt import GBDT
    g = GBDT()
    with open(filename) as fh:
        g.load_model_from_string(fh.read())
    return _BoosterHandle(g, Config(), None)


def LGBM_BoosterLoadModelFromString(model_str: str) -> _BoosterHandle:
    from .models.gbdt import GBDT
    g = GBDT()
    g.load_model_from_string(model_str)
    return _BoosterHandle(g, Config(), None)


def LGBM_BoosterFree(handle: _BoosterHandle):
    handle.gbdt = None
    return 0


def LGBM_BoosterAddValidData(handle: _BoosterHandle,
                             valid_data: _DatasetHandle):
    """c_api.cpp:560."""
    valid_data.reference = handle.train
    inner = valid_data.construct()
    metric_names = _resolve_metric_names(handle.cfg)
    metrics = create_metrics(metric_names, handle.cfg, inner.metadata,
                             inner.num_data)
    handle.gbdt.add_valid_data(inner, metrics, "valid")
    return 0


def LGBM_BoosterUpdateOneIter(handle: _BoosterHandle):
    """c_api.cpp:605 — returns is_finished (out-param -> return)."""
    return 1 if handle.gbdt.train_one_iter() else 0


def LGBM_BoosterUpdateOneIterCustom(handle: _BoosterHandle, grad, hess):
    """c_api.cpp:621."""
    return 1 if handle.gbdt.train_one_iter(
        np.asarray(grad, np.float32), np.asarray(hess, np.float32)) else 0


def LGBM_BoosterRollbackOneIter(handle: _BoosterHandle):
    handle.gbdt.rollback_one_iter()
    return 0


def LGBM_BoosterGetCurrentIteration(handle: _BoosterHandle) -> int:
    return handle.gbdt.current_iteration


def LGBM_BoosterGetNumClasses(handle: _BoosterHandle) -> int:
    return handle.gbdt.num_class


def LGBM_BoosterGetEval(handle: _BoosterHandle, data_idx: int):
    """c_api.cpp:693 — [(name, value)] for train (0) / valid (1..)."""
    return [(name, val) for name, val, _ in
            handle.gbdt.get_eval_at(data_idx)]


def LGBM_BoosterGetEvalNames(handle: _BoosterHandle):
    return [name for name, _, _ in handle.gbdt.get_eval_at(0)]


def _predict(gbdt, X, predict_type, num_iteration):
    if predict_type == C_API_PREDICT_RAW_SCORE:
        return gbdt.predict_raw(X, num_iteration)
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        return gbdt.predict_leaf_index(X, num_iteration)
    if predict_type == C_API_PREDICT_CONTRIB:
        return gbdt.predict_contrib(X, num_iteration)
    return gbdt.predict(X, num_iteration)


def LGBM_BoosterPredictForMat(handle: _BoosterHandle, data,
                              data_type=C_API_DTYPE_FLOAT64, nrow=None,
                              ncol=None, is_row_major=1,
                              predict_type=C_API_PREDICT_NORMAL,
                              num_iteration=-1, parameter=""):
    """c_api.cpp:1014."""
    X = _mat_to_2d(data, nrow, ncol, is_row_major)
    return _predict(handle.gbdt, X, predict_type, num_iteration)


def LGBM_BoosterPredictForCSR(handle: _BoosterHandle, indptr, indptr_type,
                              indices, data, data_type, nindptr, nelem,
                              num_col, predict_type=C_API_PREDICT_NORMAL,
                              num_iteration=-1, parameter=""):
    """c_api.cpp:878 — CSR predict densifies in bounded row chunks
    inside the predict paths (models/gbdt.py), never the whole
    matrix."""
    sm = SparseMatrix.from_csr(indptr, indices, data, int(num_col))
    return _predict(handle.gbdt, sm, predict_type, num_iteration)


def LGBM_BoosterPredictForFile(handle: _BoosterHandle, data_filename,
                               data_has_header=0,
                               predict_type=C_API_PREDICT_NORMAL,
                               num_iteration=-1, parameter="",
                               result_filename="LightGBM_predict_result.txt"):
    """c_api.cpp:836."""
    from .io.loader import DatasetLoader
    cfg = _params_to_config(parameter)
    cfg.header = bool(data_has_header)
    loader = DatasetLoader(cfg)
    X, _ = loader.load_predict_matrix(
        data_filename, handle.gbdt.max_feature_idx + 1)
    out = np.asarray(_predict(handle.gbdt, X, predict_type,
                              num_iteration))
    with open(result_filename, "w") as fh:
        if out.ndim == 1:
            fh.writelines(f"{v:g}\n" for v in out)
        else:
            fh.writelines("\t".join(f"{v:g}" for v in row) + "\n"
                          for row in out)
    return 0


def LGBM_BoosterCalcNumPredict(handle: _BoosterHandle, num_row: int,
                               predict_type=C_API_PREDICT_NORMAL,
                               num_iteration=-1) -> int:
    """c_api.cpp:818."""
    g = handle.gbdt
    k = max(g.num_tree_per_iteration, 1)
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        ntree = len(g.models)
        if num_iteration > 0:
            ntree = min(ntree, num_iteration * k)
        return num_row * ntree
    if predict_type == C_API_PREDICT_CONTRIB:
        return num_row * k * (g.max_feature_idx + 2)
    return num_row * k


def LGBM_BoosterSaveModel(handle: _BoosterHandle, num_iteration=-1,
                          filename="LightGBM_model.txt",
                          start_iteration=0):
    handle.gbdt.save_model_to_file(filename, start_iteration,
                                   num_iteration)
    return 0


def LGBM_BoosterSaveModelToString(handle: _BoosterHandle,
                                  num_iteration=-1,
                                  start_iteration=0) -> str:
    return handle.gbdt.model_to_string(start_iteration, num_iteration)


def LGBM_BoosterDumpModel(handle: _BoosterHandle, num_iteration=-1,
                          start_iteration=0) -> dict:
    return handle.gbdt.dump_model(start_iteration, num_iteration)


def LGBM_BoosterFeatureImportance(handle: _BoosterHandle,
                                  num_iteration=0,
                                  importance_type=0) -> np.ndarray:
    kind = "split" if importance_type == 0 else "gain"
    return handle.gbdt.feature_importance(kind, num_iteration)


def LGBM_BoosterGetNumFeature(handle: _BoosterHandle) -> int:
    return handle.gbdt.max_feature_idx + 1


def LGBM_BoosterResetParameter(handle: _BoosterHandle, parameters):
    cfg = handle.cfg
    if isinstance(parameters, str):
        cfg.set(Config.str2map(parameters))
    else:
        cfg.set({k: str(v) for k, v in parameters.items()})
    handle.gbdt.shrinkage_rate = cfg.learning_rate
    handle.gbdt._setup_grower()
    return 0


# ---------------------------------------------------------------------------
# Error state (c_api.h LGBM_GetLastError / c_api.cpp:40-45)
# ---------------------------------------------------------------------------

_last_error: List[str] = ["Everything is fine"]


def LGBM_SetLastError(msg: str):
    _last_error[0] = str(msg)
    return 0


def LGBM_GetLastError() -> str:
    return _last_error[0]


# ---------------------------------------------------------------------------
# Remaining Dataset entry points (c_api.cpp:150-500)
# ---------------------------------------------------------------------------

def LGBM_DatasetCreateFromCSC(col_ptr, col_ptr_type, indices, data,
                              data_type, ncol_ptr, nelem, num_row,
                              parameters="", reference=None
                              ) -> _DatasetHandle:
    """c_api.cpp:390 — column-sparse input, transposed to the CSR
    representation in O(nnz) (io/sparse.py); the dense fallback is
    TpuDataset's above-threshold route."""
    sm = SparseMatrix.from_csc(col_ptr, indices, data, int(num_row),
                               int(ncol_ptr) - 1)
    return _DatasetHandle(sm, _params_to_config(parameters), reference)


def LGBM_DatasetCreateFromMats(nmat, mats, data_type, nrows, ncol,
                               is_row_major, parameters="",
                               reference=None) -> _DatasetHandle:
    """c_api.cpp:330 — several stacked row blocks."""
    blocks = [_mat_to_2d(m, nr, ncol, is_row_major)
              for m, nr in zip(mats, nrows)]
    return _DatasetHandle(np.vstack(blocks),
                          _params_to_config(parameters), reference)


def LGBM_DatasetCreateFromSampledColumn(sample_data, sample_indices,
                                        ncol, num_per_col,
                                        num_sample_row, num_total_row,
                                        parameters="") -> _DatasetHandle:
    """c_api.cpp:150 — allocate an [num_total_row, ncol] dataset whose
    bin mappers come from per-column samples; rows arrive later through
    LGBM_DatasetPushRows."""
    cfg = _params_to_config(parameters)
    h = _DatasetHandle(np.zeros((int(num_total_row), int(ncol)),
                                np.float64), cfg)
    # sampled values only seed the bin mappers; rebuild the sample
    # matrix with zeros elsewhere (zeros are the implied background,
    # dataset_loader.cpp ConstructFromSampleData)
    sm = np.zeros((int(num_sample_row), int(ncol)), np.float64)
    for j in range(int(ncol)):
        vals = np.asarray(sample_data[j][:num_per_col[j]], np.float64)
        idx = np.asarray(sample_indices[j][:num_per_col[j]], np.int64)
        sm[idx, j] = vals
    from .io.dataset import find_column_mappers
    h.premade_mappers = find_column_mappers(
        sm, cfg, _parse_cat_spec(cfg), total_rows=int(num_total_row),
        presampled=True)
    h.num_pushed = 0
    return h


def LGBM_DatasetPushRows(handle: _DatasetHandle, data, data_type,
                         nrow, ncol, start_row):
    """c_api.cpp:230 — stream a row block into a preallocated dataset."""
    X = _mat_to_2d(data, nrow, ncol, 1)
    handle.X[int(start_row):int(start_row) + int(nrow)] = X
    handle.num_pushed = max(getattr(handle, "num_pushed", 0),
                            int(start_row) + int(nrow))
    return 0


def LGBM_DatasetPushRowsByCSR(handle: _DatasetHandle, indptr,
                              indptr_type, indices, data, data_type,
                              nindptr, nelem, num_col, start_row):
    """c_api.cpp:260."""
    X = _csr_to_dense(np.asarray(indptr, np.int64),
                      np.asarray(indices, np.int64),
                      np.asarray(data, np.float64), int(num_col))
    handle.X[int(start_row):int(start_row) + X.shape[0]] = X
    handle.num_pushed = max(getattr(handle, "num_pushed", 0),
                            int(start_row) + X.shape[0])
    return 0


def LGBM_DatasetCreateByReference(reference: _DatasetHandle,
                                  num_total_row) -> _DatasetHandle:
    """c_api.cpp:215 — empty dataset binned with reference's mappers,
    filled by PushRows."""
    ncol = reference.X.shape[1]
    h = _DatasetHandle(np.zeros((int(num_total_row), ncol), np.float64),
                       reference.cfg, reference)
    h.num_pushed = 0
    return h


def LGBM_DatasetGetSubset(handle: _DatasetHandle, used_row_indices,
                          parameters="") -> _DatasetHandle:
    """c_api.cpp:430 — Dataset::CopySubset."""
    idx = np.asarray(used_row_indices, np.int64)
    sub = _DatasetHandle(handle.X[idx],
                         _params_to_config(parameters) if parameters
                         else handle.cfg, handle.reference)
    n_rows = handle.X.shape[0]
    for k, v in handle.fields.items():
        if v is None or k == "group":
            continue
        v = np.asarray(v)
        if k == "init_score" and v.size != n_rows:
            # multiclass init_score is stored flattened [K*N]
            # (column-major by class, c_api.cpp metadata layout):
            # slice per class then re-flatten
            sub.fields[k] = v.reshape(-1, n_rows)[:, idx].reshape(-1)
        else:
            sub.fields[k] = v[idx]
    grp = handle.fields.get("group")
    if grp is not None:
        # ranking data: the subset must keep whole queries (the
        # reference's CopySubset copies metadata by query); recompute
        # sizes from the selected rows and refuse a query split
        qb = np.concatenate([[0], np.cumsum(np.asarray(grp, np.int64))])
        qid = np.searchsorted(qb, idx, side="right") - 1
        take, counts = np.unique(qid, return_counts=True)
        full = qb[take + 1] - qb[take]
        if not np.array_equal(counts, full):
            raise LightGBMError(
                "DatasetGetSubset on ranking data must select whole "
                "queries")
        sub.fields["group"] = full
    return sub


def LGBM_DatasetSetFeatureNames(handle: _DatasetHandle, names):
    handle.feature_names = [str(x) for x in names]
    if handle._inner is not None:
        handle._inner.feature_names = list(handle.feature_names)
    return 0


def LGBM_DatasetGetFeatureNames(handle: _DatasetHandle) -> List[str]:
    if handle._inner is not None:
        return list(handle._inner.feature_names)
    names = getattr(handle, "feature_names", None)
    return list(names) if names else [
        f"Column_{i}" for i in range(handle.X.shape[1])]


# ---------------------------------------------------------------------------
# Remaining Booster entry points (c_api.cpp:560-1270)
# ---------------------------------------------------------------------------

def LGBM_BoosterMerge(handle: _BoosterHandle,
                      other: _BoosterHandle):
    """c_api.cpp:570 — append other's models."""
    g, o = handle.gbdt, other.gbdt
    o._ensure_host_trees()
    g._ensure_host_trees()
    g.records.extend(o.records)
    g.models.extend(o.models)
    g._tree_shrinkage.extend(o._tree_shrinkage)
    return 0


def LGBM_BoosterGetEvalCounts(handle: _BoosterHandle) -> int:
    """c_api.cpp:680 — number of configured eval metrics (no
    evaluation, no device readback)."""
    return len(_resolve_metric_names(handle.cfg))


def LGBM_BoosterGetFeatureNames(handle: _BoosterHandle) -> List[str]:
    return list(handle.gbdt.feature_names)


def LGBM_BoosterNumModelPerIteration(handle: _BoosterHandle) -> int:
    return handle.gbdt.num_model_per_iteration()


def LGBM_BoosterNumberOfTotalModel(handle: _BoosterHandle) -> int:
    return len(handle.gbdt.models)


def LGBM_BoosterGetNumPredict(handle: _BoosterHandle,
                              data_idx: int) -> int:
    """c_api.cpp:830 — size of the score vector for dataset data_idx."""
    g = handle.gbdt
    n = g._n if data_idx == 0 else g._valid_scores[data_idx - 1].shape[1]
    return int(n) * g.num_tree_per_iteration


def LGBM_BoosterGetPredict(handle: _BoosterHandle,
                           data_idx: int) -> np.ndarray:
    """c_api.cpp:840 — CONVERTED scores of train (0) / valid (1...)
    data, flattened [K*N] like the reference's row-major copy."""
    g = handle.gbdt
    scores = (g._scores[:, :g._n] if data_idx == 0
              else g._valid_scores[data_idx - 1])
    out = np.asarray(scores, np.float64)
    if g.objective is not None:
        # convert on the [K, N] matrix: multiclass softmax normalizes
        # over the CLASS axis, not the flattened vector
        out = np.asarray(g.objective.convert_output(out))
    return out.reshape(-1)


def LGBM_BoosterGetLeafValue(handle: _BoosterHandle, tree_idx: int,
                             leaf_idx: int) -> float:
    g = handle.gbdt
    g._ensure_host_trees()
    return float(g.models[int(tree_idx)].leaf_value[int(leaf_idx)])


def LGBM_BoosterSetLeafValue(handle: _BoosterHandle, tree_idx: int,
                             leaf_idx: int, val: float):
    """c_api.cpp:900 — Tree::SetLeafOutput on both the host tree and
    the device record (so device prediction agrees)."""
    import jax.numpy as jnp
    g = handle.gbdt
    g._ensure_host_trees()
    g.models[int(tree_idx)].leaf_value[int(leaf_idx)] = float(val)
    rec = g.records[int(tree_idx)]
    g.records[int(tree_idx)] = rec._replace(
        leaf_output=rec.leaf_output.at[int(leaf_idx)].set(
            jnp.float32(val)))
    g._scores_stale = True
    # in-place edit: tree identity survives, so the stacked predictor
    # must be dropped explicitly (prefix reuse cannot see the change)
    g._invalidate_stacked()
    return 0


def LGBM_BoosterShuffleModels(handle: _BoosterHandle, start: int = 0,
                              end: int = -1):
    """c_api.cpp:590 — random permutation of a tree range (whole
    iteration groups, matching the reference's model granularity)."""
    g = handle.gbdt
    g._ensure_host_trees()
    k = max(g.num_tree_per_iteration, 1)
    n_groups = len(g.models) // k
    end = n_groups if end <= 0 else min(int(end), n_groups)
    start = max(int(start), 0)
    rng = np.random.default_rng(getattr(g.config, "data_random_seed", 1))
    gperm = np.arange(n_groups)
    gperm[start:end] = rng.permutation(gperm[start:end])
    # whole iteration GROUPS move: tree t serves class t % k, so a
    # per-tree permutation would scramble multiclass class assignment
    perm = (gperm[:, None] * k + np.arange(k)[None, :]).reshape(-1)
    g.models = [g.models[i] for i in perm]
    g.records = [g.records[i] for i in perm]
    g._tree_shrinkage = [g._tree_shrinkage[i] for i in perm]
    # the reorder is an ensemble mutation: stale stacked predictors
    # would keep serving the OLD tree order
    g._bump_model_gen()
    return 0


def LGBM_BoosterRefit(handle: _BoosterHandle, leaf_preds=None):
    """c_api.cpp:600 — re-learn leaf outputs on the booster's training
    data (GBDT::RefitTree; the leaf assignment comes from the device
    replay, so the leaf_preds matrix of the C signature is accepted and
    ignored)."""
    handle.gbdt.refit_existing()
    return 0


def LGBM_BoosterResetTrainingData(handle: _BoosterHandle,
                                  train_data: _DatasetHandle):
    """c_api.cpp:580 — GBDT::ResetTrainingData: existing trees are
    re-binned against the new data's mappers and replayed into the new
    score vector, so training continues from the current model."""
    g = handle.gbdt
    inner = train_data.construct()
    objective = g.objective
    if objective is not None:
        objective.init(inner.metadata, inner.num_data)
    metrics = list(g.training_metrics)
    if g.models:
        g._ensure_host_trees()
        g.init_from_loaded(handle.cfg, inner, objective, metrics)
    else:
        g.init(handle.cfg, inner, objective, metrics)
    handle.train = train_data
    return 0


def LGBM_BoosterPredictForCSC(handle: _BoosterHandle, col_ptr,
                              col_ptr_type, indices, data, data_type,
                              ncol_ptr, nelem, num_row,
                              predict_type=C_API_PREDICT_NORMAL,
                              num_iteration=-1, parameter=""):
    """c_api.cpp:1100 — column-sparse predict via the CSR
    representation, densified in bounded row chunks."""
    sm = SparseMatrix.from_csc(col_ptr, indices, data, int(num_row),
                               int(ncol_ptr) - 1)
    return _predict(handle.gbdt, sm, predict_type, num_iteration)


# ---------------------------------------------------------------------------
# Network entry points (c_api.cpp:47-80)
# ---------------------------------------------------------------------------

def LGBM_NetworkInit(machines: str, local_listen_port: int,
                     listen_time_out: int, num_machines: int):
    """The reference boots its socket linkers here; the TPU engine's
    collectives ride the JAX runtime (ICI/DCN via XLA), whose topology
    is fixed at process start (jax.distributed.initialize) — accepted
    and logged as the documented substitution (SURVEY §2.2)."""
    if int(num_machines) > 1:
        log.info("LGBM_NetworkInit: topology comes from the JAX "
                 "runtime; machines/port arguments are not used")
    return 0


def LGBM_NetworkFree():
    from .parallel.learners import set_network_functions
    set_network_functions()             # clear injected collectives
    return 0


def LGBM_NetworkInitWithFunctions(num_machines: int, rank: int,
                                  reduce_scatter_fn=None,
                                  allgather_fn=None):
    """network.cpp:41-54 — install external collective functions.

    The reference injects C function pointers that move raw byte
    buffers; the TPU engine's collectives are XLA ops compiled into the
    training program, so the injected callables here are jax-traceable
    wrappers ``fn(value, default_collective) -> value`` invoked at every
    collective site when the distributed learners trace (histogram
    reduce-scatter = psum sites, best-split sync = all_gather site).
    They can observe, extend, or fully replace the default collective —
    the seam SURVEY §2.2 asks to keep for tests."""
    from .parallel.learners import set_network_functions
    set_network_functions(reduce_scatter_fn=reduce_scatter_fn,
                          allgather_fn=allgather_fn)
    log.info("NetworkInitWithFunctions: collective overrides installed "
             "(num_machines=%s rank=%s come from the JAX runtime)",
             num_machines, rank)
    return 0
