"""C-API-shaped entry points.

TPU-native counterpart of the reference C API (reference:
src/c_api.cpp:47-1568, include/LightGBM/c_api.h). The reference exports
a C ABI because its engine is C++; here the engine is in-process
JAX/Python, so the same surface is exposed as Python functions with the
LGBM_* names and c_api semantics: handles are opaque objects, datasets
are constructed raw-then-finished-by-first-booster, boosters train one
iteration at a time. Out-parameters become return values; everything
else (dtype tags, predict tags, field names, parameter strings) matches
c_api.h so ports of C callers (e.g. the fork's cache-admission driver,
src/test.cpp) transliterate line by line.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from .basic import _DEFAULT_METRIC, _resolve_metric_names
from .config import Config, param_dict_to_str
from .io.dataset import Metadata, TpuDataset
from .metrics import create_metrics
from .models.boosting import create_boosting
from .objectives import create_objective
from .utils import log
from .utils.log import LightGBMError

# dtype tags (c_api.h:20-27)
C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

# predict tags (c_api.h:29-35)
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3


def _params_to_config(parameters) -> Config:
    cfg = Config()
    if isinstance(parameters, str):
        cfg.set(Config.str2map(parameters))
    elif isinstance(parameters, dict):
        cfg.set({k: str(v) for k, v in parameters.items()})
    elif parameters:
        raise LightGBMError("parameters must be a dict or 'k=v' string")
    return cfg


class _DatasetHandle:
    """Raw matrix + metadata; binning happens when the first booster
    (or reference link) construction needs it (c_api.cpp Dataset
    creation is likewise deferred to ConstructFromSampleData)."""

    def __init__(self, X: np.ndarray, cfg: Config,
                 reference: Optional["_DatasetHandle"] = None):
        self.X = np.asarray(X, np.float64)
        self.cfg = cfg
        self.reference = reference
        self.fields: Dict[str, np.ndarray] = {}
        self._inner: Optional[TpuDataset] = None

    def construct(self) -> TpuDataset:
        if self._inner is None:
            meta = Metadata(
                label=self.fields.get("label"),
                weight=self.fields.get("weight"),
                group=self.fields.get("group"),
                init_score=self.fields.get("init_score"))
            cats = _parse_cat_spec(self.cfg)
            if self.reference is not None:
                self._inner = self.reference.construct() \
                    .create_valid(self.X, meta)
            else:
                ds = TpuDataset(self.cfg)
                ds.construct_from_matrix(self.X, meta, categorical=cats)
                self._inner = ds
        return self._inner


def _parse_cat_spec(cfg: Config) -> List[int]:
    spec = cfg.categorical_feature
    if not spec:
        return []
    return [int(x) for x in str(spec).split(",") if x.strip()]


def _csr_to_dense(indptr, indices, data, num_col: int) -> np.ndarray:
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices, np.int64)
    data = np.asarray(data, np.float64)
    n = len(indptr) - 1
    X = np.zeros((n, num_col), np.float64)
    rows = np.repeat(np.arange(n), np.diff(indptr))
    X[rows, indices[:len(rows)]] = data[:len(rows)]
    return X


# ---------------------------------------------------------------------------
# Dataset API (c_api.cpp:215-505)
# ---------------------------------------------------------------------------

def _mat_to_2d(data, nrow, ncol, is_row_major) -> np.ndarray:
    X = np.asarray(data, np.float64)
    if X.ndim == 1:
        # flat buffers honor is_row_major like the C API (c_api.cpp
        # RowFunctionFromDenseMatric); 2-D numpy inputs already carry
        # their own layout
        X = X.reshape(int(nrow), int(ncol)) if is_row_major \
            else X.reshape(int(ncol), int(nrow)).T
    return X


def LGBM_DatasetCreateFromMat(data, data_type=C_API_DTYPE_FLOAT64,
                              nrow=None, ncol=None, is_row_major=1,
                              parameters="", reference=None):
    """c_api.cpp:345 LGBM_DatasetCreateFromMat."""
    X = _mat_to_2d(data, nrow, ncol, is_row_major)
    return _DatasetHandle(X, _params_to_config(parameters), reference)


def LGBM_DatasetCreateFromCSR(indptr, indptr_type, indices, data,
                              data_type, nindptr, nelem, num_col,
                              parameters="", reference=None):
    """c_api.cpp:268 LGBM_DatasetCreateFromCSR (densified: the engine's
    HBM layout is dense by design, io/dataset.py)."""
    X = _csr_to_dense(indptr, indices, data, int(num_col))
    return _DatasetHandle(X, _params_to_config(parameters), reference)


def LGBM_DatasetCreateFromFile(filename: str, parameters="",
                               reference=None):
    """c_api.cpp:215."""
    from .io.loader import DatasetLoader
    cfg = _params_to_config(parameters)
    h = _DatasetHandle(np.zeros((0, 0)), cfg,
                       reference)
    loader = DatasetLoader(cfg)
    h._inner = loader.load_from_file(
        filename,
        reference=reference.construct() if reference else None)
    return h


def LGBM_DatasetSetField(handle: _DatasetHandle, field_name: str,
                         field_data, num_element=None,
                         dtype=C_API_DTYPE_FLOAT32):
    """c_api.cpp:436."""
    arr = np.asarray(field_data)
    handle.fields[field_name] = arr
    if handle._inner is not None:
        md = handle._inner.metadata
        if field_name == "label":
            md.label = arr.astype(np.float32).reshape(-1)
        elif field_name == "weight":
            md.weights = arr.astype(np.float32).reshape(-1)
        elif field_name == "init_score":
            md.init_score = arr.astype(np.float64)
        elif field_name == "group":
            g = arr.astype(np.int64).reshape(-1)
            md.query_boundaries = np.concatenate(
                [[0], np.cumsum(g)]).astype(np.int64)
        else:
            raise LightGBMError(f"Unknown field {field_name!r}")
    return 0


def LGBM_DatasetGetField(handle: _DatasetHandle, field_name: str):
    """c_api.cpp:459 — returns the array (out-params -> return)."""
    if handle._inner is not None:
        md = handle._inner.metadata
        got = {"label": md.label, "weight": md.weights,
               "init_score": md.init_score}.get(field_name)
        if got is not None:
            return got
    return handle.fields.get(field_name)


def LGBM_DatasetGetNumData(handle: _DatasetHandle) -> int:
    return (handle._inner.num_data if handle._inner is not None
            else handle.X.shape[0])


def LGBM_DatasetGetNumFeature(handle: _DatasetHandle) -> int:
    return (handle._inner.num_total_features
            if handle._inner is not None else handle.X.shape[1])


def LGBM_DatasetSaveBinary(handle: _DatasetHandle, filename: str):
    handle.construct().save_binary(filename)
    return 0


def LGBM_DatasetFree(handle: _DatasetHandle):
    handle._inner = None
    handle.X = None
    return 0


# ---------------------------------------------------------------------------
# Booster API (c_api.cpp:506-1200)
# ---------------------------------------------------------------------------

class _BoosterHandle:
    def __init__(self, gbdt, cfg: Config, train: Optional[_DatasetHandle]):
        self.gbdt = gbdt
        self.cfg = cfg
        self.train = train


def LGBM_BoosterCreate(train_data: _DatasetHandle, parameters="",
                       out=None) -> _BoosterHandle:
    """c_api.cpp:506."""
    cfg = _params_to_config(parameters)
    inner = train_data.construct()
    objective = create_objective(cfg.objective, cfg)
    if objective is not None:
        objective.init(inner.metadata, inner.num_data)
    metric_names = _resolve_metric_names(cfg)
    train_metrics = []
    if cfg.is_provide_training_metric:
        train_metrics = create_metrics(metric_names, cfg, inner.metadata,
                                       inner.num_data)
    gbdt = create_boosting(cfg.boosting_type())
    gbdt.init(cfg, inner, objective, train_metrics)
    return _BoosterHandle(gbdt, cfg, train_data)


def LGBM_BoosterCreateFromModelfile(filename: str) -> _BoosterHandle:
    """c_api.cpp:527."""
    from .models.gbdt import GBDT
    g = GBDT()
    with open(filename) as fh:
        g.load_model_from_string(fh.read())
    return _BoosterHandle(g, Config(), None)


def LGBM_BoosterLoadModelFromString(model_str: str) -> _BoosterHandle:
    from .models.gbdt import GBDT
    g = GBDT()
    g.load_model_from_string(model_str)
    return _BoosterHandle(g, Config(), None)


def LGBM_BoosterFree(handle: _BoosterHandle):
    handle.gbdt = None
    return 0


def LGBM_BoosterAddValidData(handle: _BoosterHandle,
                             valid_data: _DatasetHandle):
    """c_api.cpp:560."""
    valid_data.reference = handle.train
    inner = valid_data.construct()
    metric_names = _resolve_metric_names(handle.cfg)
    metrics = create_metrics(metric_names, handle.cfg, inner.metadata,
                             inner.num_data)
    handle.gbdt.add_valid_data(inner, metrics, "valid")
    return 0


def LGBM_BoosterUpdateOneIter(handle: _BoosterHandle):
    """c_api.cpp:605 — returns is_finished (out-param -> return)."""
    return 1 if handle.gbdt.train_one_iter() else 0


def LGBM_BoosterUpdateOneIterCustom(handle: _BoosterHandle, grad, hess):
    """c_api.cpp:621."""
    return 1 if handle.gbdt.train_one_iter(
        np.asarray(grad, np.float32), np.asarray(hess, np.float32)) else 0


def LGBM_BoosterRollbackOneIter(handle: _BoosterHandle):
    handle.gbdt.rollback_one_iter()
    return 0


def LGBM_BoosterGetCurrentIteration(handle: _BoosterHandle) -> int:
    return handle.gbdt.current_iteration


def LGBM_BoosterGetNumClasses(handle: _BoosterHandle) -> int:
    return handle.gbdt.num_class


def LGBM_BoosterGetEval(handle: _BoosterHandle, data_idx: int):
    """c_api.cpp:693 — [(name, value)] for train (0) / valid (1..)."""
    return [(name, val) for name, val, _ in
            handle.gbdt.get_eval_at(data_idx)]


def LGBM_BoosterGetEvalNames(handle: _BoosterHandle):
    return [name for name, _, _ in handle.gbdt.get_eval_at(0)]


def _predict(gbdt, X, predict_type, num_iteration):
    if predict_type == C_API_PREDICT_RAW_SCORE:
        return gbdt.predict_raw(X, num_iteration)
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        return gbdt.predict_leaf_index(X, num_iteration)
    if predict_type == C_API_PREDICT_CONTRIB:
        return gbdt.predict_contrib(X, num_iteration)
    return gbdt.predict(X, num_iteration)


def LGBM_BoosterPredictForMat(handle: _BoosterHandle, data,
                              data_type=C_API_DTYPE_FLOAT64, nrow=None,
                              ncol=None, is_row_major=1,
                              predict_type=C_API_PREDICT_NORMAL,
                              num_iteration=-1, parameter=""):
    """c_api.cpp:1014."""
    X = _mat_to_2d(data, nrow, ncol, is_row_major)
    return _predict(handle.gbdt, X, predict_type, num_iteration)


def LGBM_BoosterPredictForCSR(handle: _BoosterHandle, indptr, indptr_type,
                              indices, data, data_type, nindptr, nelem,
                              num_col, predict_type=C_API_PREDICT_NORMAL,
                              num_iteration=-1, parameter=""):
    """c_api.cpp:878."""
    X = _csr_to_dense(indptr, indices, data, int(num_col))
    return _predict(handle.gbdt, X, predict_type, num_iteration)


def LGBM_BoosterPredictForFile(handle: _BoosterHandle, data_filename,
                               data_has_header=0,
                               predict_type=C_API_PREDICT_NORMAL,
                               num_iteration=-1, parameter="",
                               result_filename="LightGBM_predict_result.txt"):
    """c_api.cpp:836."""
    from .io.loader import DatasetLoader
    cfg = _params_to_config(parameter)
    cfg.header = bool(data_has_header)
    loader = DatasetLoader(cfg)
    X, _ = loader.load_predict_matrix(
        data_filename, handle.gbdt.max_feature_idx + 1)
    out = np.asarray(_predict(handle.gbdt, X, predict_type,
                              num_iteration))
    with open(result_filename, "w") as fh:
        if out.ndim == 1:
            fh.writelines(f"{v:g}\n" for v in out)
        else:
            fh.writelines("\t".join(f"{v:g}" for v in row) + "\n"
                          for row in out)
    return 0


def LGBM_BoosterCalcNumPredict(handle: _BoosterHandle, num_row: int,
                               predict_type=C_API_PREDICT_NORMAL,
                               num_iteration=-1) -> int:
    """c_api.cpp:818."""
    g = handle.gbdt
    k = max(g.num_tree_per_iteration, 1)
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        ntree = len(g.models)
        if num_iteration > 0:
            ntree = min(ntree, num_iteration * k)
        return num_row * ntree
    if predict_type == C_API_PREDICT_CONTRIB:
        return num_row * k * (g.max_feature_idx + 2)
    return num_row * k


def LGBM_BoosterSaveModel(handle: _BoosterHandle, num_iteration=-1,
                          filename="LightGBM_model.txt",
                          start_iteration=0):
    handle.gbdt.save_model_to_file(filename, start_iteration,
                                   num_iteration)
    return 0


def LGBM_BoosterSaveModelToString(handle: _BoosterHandle,
                                  num_iteration=-1,
                                  start_iteration=0) -> str:
    return handle.gbdt.model_to_string(start_iteration, num_iteration)


def LGBM_BoosterDumpModel(handle: _BoosterHandle, num_iteration=-1,
                          start_iteration=0) -> dict:
    return handle.gbdt.dump_model(start_iteration, num_iteration)


def LGBM_BoosterFeatureImportance(handle: _BoosterHandle,
                                  num_iteration=0,
                                  importance_type=0) -> np.ndarray:
    kind = "split" if importance_type == 0 else "gain"
    return handle.gbdt.feature_importance(kind, num_iteration)


def LGBM_BoosterGetNumFeature(handle: _BoosterHandle) -> int:
    return handle.gbdt.max_feature_idx + 1


def LGBM_BoosterResetParameter(handle: _BoosterHandle, parameters):
    cfg = handle.cfg
    if isinstance(parameters, str):
        cfg.set(Config.str2map(parameters))
    else:
        cfg.set({k: str(v) for k, v in parameters.items()})
    handle.gbdt.shrinkage_rate = cfg.learning_rate
    handle.gbdt._setup_grower()
    return 0
