"""The fleet scoring daemon: a stdlib HTTP front over the coalescer.

One process, N tenants, four routes (the obs/export.py
``ThreadingHTTPServer`` pattern — stdlib only, daemon threads, bind on
127.0.0.1, port 0 = ephemeral):

    POST /v1/predict/<tenant>   {"rows": [[...], ...]}
                                -> {"predictions": [...], "version": v}
    POST /v1/tenants/<tenant>   {"model": "<model text>", "warm_rows": n}
                                -> {"tenant": t, "version": v}
    GET  /v1/tenants            registered tenants + registry stats
    GET  /healthz               liveness + queue depth + shed state
    GET  /slo                   the admission engine's budget report

Admission control runs BEFORE the queue: when ``tpu_fleet_slo_p99_ms``
is set, every registered tenant gets a
``hist:fleet/tenant_latency_s/<t>:p99 < target`` objective on a
dedicated obs/slo.py engine, and a tenant whose remaining error budget
has burned to ``tpu_fleet_shed_budget`` or below is refused with
HTTP 429 + ``Retry-After`` — shedding starts while budget remains
(before the breach), the shed tenant stops adding bad events, and its
neighbors keep serving. The state machine per tenant:

    SERVING ──(budget_remaining <= shed threshold)──► SHEDDING
    SHEDDING ──(budget recovers above threshold)────► SERVING

Recovery is possible because the shed tenant's histogram stops
accumulating slow events while shed (total grows only via the
occasional probe the operator sends), and because a model swap or
fault repair removes the latency source.

Model registration is the warm-swap path: the model is parsed, forest-
stacked and serve-bucket warmed OFF the serving path, then published
atomically — in-flight requests finish on the old version
(serve/tenants.py).
"""
from __future__ import annotations

import atexit
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..analysis import lockorder
from ..obs import registry as obs
from ..obs import slo as obs_slo
from ..obs.trace import config_get
from ..utils import log

from .coalescer import Coalescer, QueueFull
from .tenants import TenantRegistry


class ScoringDaemon:
    """Tenant registry + coalescer + HTTP front + admission control."""

    def __init__(self, port: int = 0, coalesce_us: int = 2000,
                 max_batch: int = 4096, max_queue: int = 1024,
                 warm_rows: int = 16, slo_p99_ms: float = 0.0,
                 shed_budget: float = 0.25,
                 slo_eval_gap_s: float = 0.05,
                 slo_min_events: int = 100,
                 shed_probe_every: int = 16,
                 retry_after_s: float = 0.5,
                 predict_timeout_s: float = 60.0):
        self._port = int(port)
        self.tenants = TenantRegistry(warm_rows=warm_rows)
        self.coalescer = Coalescer(
            self.tenants, max_wait_us=coalesce_us, max_batch=max_batch,
            max_queue=max_queue, latency_observer=self._observe_latency)
        self._slo_p99_ms = max(float(slo_p99_ms), 0.0)
        self._shed_budget = min(max(float(shed_budget), 0.0), 1.0)
        self._slo_eval_gap_s = max(float(slo_eval_gap_s), 0.0)
        self._slo_min_events = max(int(slo_min_events), 0)
        self._shed_probe_every = max(int(shed_probe_every), 0)
        self._retry_after_s = max(float(retry_after_s), 0.01)
        self._predict_timeout_s = float(predict_timeout_s)
        self._lock = lockorder.named_lock("serve.daemon._lock")
        # admission engine state, all guarded-by: _lock — the engine
        # is rebuilt on tenant registration (one spec per tenant) and
        # evaluated at a bounded rate on the request path (this daemon
        # may be the only evaluation clock in the process)
        self._slo_engine: Optional[obs_slo.SloEngine] = None
        self._spec_names: Dict[str, str] = {}    # tenant -> spec name
        self._shedding: Dict[str, dict] = {}     # tenant -> shed state
        self._last_eval = 0.0
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    @classmethod
    def from_config(cls, params=None, **overrides) -> "ScoringDaemon":
        """Build from the ``tpu_fleet_*`` knobs (a Config object or a
        raw params dict); explicit keyword overrides win."""
        kw = dict(
            port=int(config_get(params, "tpu_fleet_port", 0) or 0),
            coalesce_us=int(config_get(
                params, "tpu_fleet_coalesce_us", 2000)),
            max_batch=int(config_get(params, "tpu_fleet_max_batch",
                                     4096)),
            max_queue=int(config_get(params, "tpu_fleet_queue", 1024)),
            slo_p99_ms=float(config_get(params, "tpu_fleet_slo_p99_ms",
                                        0.0) or 0.0),
            shed_budget=float(config_get(
                params, "tpu_fleet_shed_budget", 0.25)),
        )
        kw.update(overrides)
        return cls(**kw)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ScoringDaemon":
        if self._server is not None:
            return self
        self.coalescer.start()
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):   # noqa: N802 — http.server
                pass                        # API; obs logging instead

            def do_GET(self):               # noqa: N802
                daemon._handle_get(self)

            def do_POST(self):              # noqa: N802
                daemon._handle_post(self)

        class Server(ThreadingHTTPServer):
            # http.server's default accept backlog is 5: a fleet of
            # clients opening one TCP connection per request overflows
            # it under burst load, and the resulting resets surface as
            # client-side retry/backoff latency spikes
            request_queue_size = 128

        try:
            self._server = Server(
                ("127.0.0.1", max(self._port, 0)), Handler)
        except OSError as e:
            # degrade, don't die: the embedding run (lrb
            # --serve-daemon) falls back to in-process scoring
            self.coalescer.stop()
            raise RuntimeError(
                f"fleet daemon could not bind port {self._port}: {e}")
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="fleet-daemon",
            daemon=True)
        self._thread.start()
        atexit.register(self.stop)
        log.info("fleet scoring daemon listening on 127.0.0.1:%d",
                 self.http_port)
        return self

    def stop(self) -> None:
        """Idempotent clean shutdown: close the listener, then drain
        the coalescer (queued requests still complete)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        srv, thr = self._server, self._thread
        self._server = None
        self._thread = None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thr is not None:
            thr.join(timeout=10.0)
        self.coalescer.stop()

    @property
    def http_port(self) -> int:
        """The bound port (resolves port=0 ephemeral binds)."""
        srv = self._server
        return int(srv.server_address[1]) if srv is not None \
            else self._port

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.http_port}"

    # -- serving primitives (also the in-process API) ------------------------

    def register_tenant(self, name: str, model_str: str,
                        warm_rows: Optional[int] = None) -> int:
        version = self.tenants.register(name, model_str,
                                        warm_rows=warm_rows)
        self._rebuild_slo()
        return version

    def predict(self, tenant: str, X, timeout_s: Optional[float] = None):
        """Admission check + coalesced predict; returns
        ``(predictions, version)``. Raises ShedError/QueueFull/KeyError
        exactly as the HTTP front maps them (429/503/404)."""
        retry_after = self.shed_check(tenant)
        if retry_after is not None:
            from .client import ShedError
            raise ShedError(tenant, retry_after)
        fut = self.coalescer.submit(tenant, X)
        return fut.result(timeout=(self._predict_timeout_s
                                   if timeout_s is None else timeout_s))

    # -- admission control ---------------------------------------------------

    def _observe_latency(self, tenant: str, latency_s: float) -> None:
        # bounded-cardinality: one series per registered tenant —
        # tenant names are operator-supplied registrations (validated
        # [a-z0-9_]), not request-derived
        obs.latency_histogram(
            "fleet/tenant_latency_s/" + tenant).observe(latency_s)

    def _rebuild_slo(self) -> None:
        if self._slo_p99_ms <= 0:
            return
        thr_s = self._slo_p99_ms / 1e3
        specs, names = [], {}
        for t in self.tenants.names():
            # create the instrument FIRST with the quantile-grade
            # latency buckets — otherwise the engine's first evaluate
            # would get-or-create it with the coarse default bounds
            # bounded-cardinality: one series per registered tenant
            obs.latency_histogram("fleet/tenant_latency_s/" + t)
            text = f"hist:fleet/tenant_latency_s/{t}:p99 < {thr_s:g}"
            spec = obs_slo.parse_specs(text)[0]
            names[t] = spec.name
            specs.append(spec)
        with self._lock:
            self._slo_engine = obs_slo.SloEngine(
                specs, min_events=self._slo_min_events)
            self._spec_names = names

    def shed_check(self, tenant: str) -> Optional[float]:
        """None = admit; a float = shed, retry after that many
        seconds. Evaluates the admission engine at a bounded rate —
        the daemon is its own SLO clock, so a tenant can be shed
        BEFORE the exporter interval would have noticed the burn."""
        with self._lock:
            engine = self._slo_engine
            spec_name = self._spec_names.get(tenant)
            if engine is None or spec_name is None:
                return None
            now = time.monotonic()
            fresh = (now - self._last_eval) >= self._slo_eval_gap_s
            if fresh:
                self._last_eval = now
        report = engine.report(fresh=fresh)
        row = next((r for r in report.get("specs", [])
                    if r["name"] == spec_name), None)
        if row is None:
            return None
        remaining = row["budget_remaining"]
        shed = (remaining <= self._shed_budget
                and not row.get("warming", False))
        with self._lock:
            state = self._shedding.get(tenant)
            if shed and state is None:
                # entering SHEDDING: snapshot the budget at first shed
                # — the drill's proof that admission acted pre-breach
                state = self._shedding[tenant] = {
                    "since": round(time.time(), 3),
                    "budget_remaining_at_shed": remaining,
                    "exhausted_at_shed": bool(row["exhausted"]),
                    "sheds": 0,
                }
                log.warning(
                    "fleet tenant %r SHED: p99 budget remaining %.3f "
                    "<= %.3f threshold (burn %.2f)", tenant, remaining,
                    self._shed_budget, row["burn_rate"])
            elif not shed and state is not None:
                del self._shedding[tenant]
                log.info("fleet tenant %r recovered: budget %.3f",
                         tenant, remaining)
            if shed:
                state["sheds"] += 1
                if (self._shed_probe_every
                        and state["sheds"] % self._shed_probe_every
                        == 0):
                    # probe trickle: admit 1 in N while shedding — a
                    # cumulative budget can only recover through new
                    # events, and a fully-shed tenant would otherwise
                    # starve its own histogram and stay shed forever
                    return None
        if not shed:
            return None
        obs.counter("fleet/shed_total").add(1)
        # bounded-cardinality: one series per registered tenant (see
        # _observe_latency)
        obs.counter("fleet/shed/" + tenant).add(1)
        return self._retry_after_s

    def slo_report(self) -> dict:
        with self._lock:
            engine = self._slo_engine
            shedding = {t: dict(s) for t, s in self._shedding.items()}
        rep = engine.report(fresh=True) if engine is not None \
            else {"specs": [], "ok": None}
        rep["shedding"] = shedding
        rep["shed_budget"] = self._shed_budget
        return rep

    def stats(self) -> dict:
        from ..ops import predict_cache
        return {
            "tenants": self.tenants.stats(),
            "queue_depth": self.coalescer.queue_depth(),
            "requests_total": obs.counter("fleet/requests_total").value,
            "shed_total": obs.counter("fleet/shed_total").value,
            "queue_rejects": obs.counter("fleet/queue_rejects").value,
            "predict_cache": predict_cache.stats(),
        }

    # -- HTTP plumbing -------------------------------------------------------

    def _send_json(self, h, code: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        h.send_response(code)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        try:
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass    # client went away; nothing to salvage

    def _read_json(self, h) -> dict:
        n = int(h.headers.get("Content-Length", 0) or 0)
        raw = h.rfile.read(n) if n else b""
        return json.loads(raw.decode()) if raw else {}

    def _handle_get(self, h) -> None:
        if h.path == "/healthz":
            with self._lock:
                shedding = sorted(self._shedding)
            self._send_json(h, 200, {
                "ok": True,
                "tenants": self.tenants.names(),
                "queue_depth": self.coalescer.queue_depth(),
                "shedding": shedding,
            })
        elif h.path == "/slo":
            self._send_json(h, 200, self.slo_report())
        elif h.path == "/v1/tenants":
            self._send_json(h, 200, self.stats())
        else:
            self._send_json(h, 404, {"error": f"no route {h.path}"})

    def _handle_post(self, h) -> None:
        try:
            if h.path.startswith("/v1/predict/"):
                self._handle_predict(h, h.path[len("/v1/predict/"):])
            elif h.path.startswith("/v1/tenants/"):
                self._handle_register(h, h.path[len("/v1/tenants/"):])
            else:
                self._send_json(h, 404, {"error": f"no route {h.path}"})
        except json.JSONDecodeError as e:
            self._send_json(h, 400, {"error": f"bad JSON body: {e}"})
        except ValueError as e:
            self._send_json(h, 400, {"error": str(e)})
        except Exception as e:          # noqa: BLE001 — the serving
            # thread answers with the real error instead of dying
            self._send_json(h, 500,
                            {"error": f"{type(e).__name__}: {e}"})

    def _handle_predict(self, h, tenant: str) -> None:
        body = self._read_json(h)
        rows = body.get("rows")
        if not isinstance(rows, list) or not rows:
            self._send_json(h, 400,
                            {"error": "want {\"rows\": [[...], ...]}"})
            return
        retry_after = self.shed_check(tenant)
        if retry_after is not None:
            self._send_json(
                h, 429,
                {"error": f"tenant {tenant!r} shed: p99 error budget "
                          f"low", "tenant": tenant},
                headers={"Retry-After": f"{retry_after:g}"})
            return
        try:
            fut = self.coalescer.submit(tenant, rows)
            preds, version = fut.result(
                timeout=self._predict_timeout_s)
        except QueueFull as e:
            self._send_json(
                h, 503, {"error": str(e)},
                headers={"Retry-After": f"{e.retry_after_s:g}"})
            return
        except KeyError:
            self._send_json(
                h, 404, {"error": f"unknown tenant {tenant!r}"})
            return
        self._send_json(h, 200, {
            "tenant": tenant,
            "version": version,
            "rows": len(rows),
            "predictions": preds.tolist(),
        })

    def _handle_register(self, h, tenant: str) -> None:
        body = self._read_json(h)
        model_str = body.get("model")
        if not isinstance(model_str, str) or not model_str:
            self._send_json(h, 400,
                            {"error": "want {\"model\": \"<text>\"}"})
            return
        warm = body.get("warm_rows")
        version = self.register_tenant(
            tenant, model_str,
            warm_rows=None if warm is None else int(warm))
        self._send_json(h, 200, {"tenant": tenant, "version": version})
