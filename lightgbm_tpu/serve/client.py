"""The fleet daemon's wire client: stdlib urllib + the one retry
policy.

Scoring requests are idempotent (pure reads against a published model
version), so every transient socket failure — the daemon dropping a
connection mid model-swap ("Remote end closed connection", "Connection
reset"), a not-yet-rebound listener ("Connection refused"), an overdue
response ("Read timed out" / ``socket.timeout``) — is absorbed by
utils/retry.py's bounded backoff, with the attempts/retries/giveups
visible in the ``retry/*`` counters. Admission refusals are NOT
transient: a 429 means the daemon is protecting that tenant's error
budget, and hammering through it would defeat the point — the client
surfaces ``ShedError`` (with the server's ``Retry-After``) instead of
retrying. A 503 (bounded queue full) IS retried: backpressure asks
for exactly that.

Floats survive the JSON wire bit-exactly: Python serializes float64
with shortest-round-trip repr, so the parity tests can assert
coalesced-over-HTTP == direct in-process predict to the last bit.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, Optional

import numpy as np

from ..utils import retry


class ShedError(RuntimeError):
    """HTTP 429: the tenant's error budget is burning; the daemon
    refused the request pre-breach. Not retried — honor
    ``retry_after_s``."""

    def __init__(self, tenant: str, retry_after_s: float = 1.0):
        super().__init__(
            f"tenant {tenant!r} shed by admission control "
            f"(retry after {retry_after_s:g}s)")
        self.tenant = str(tenant)
        self.retry_after_s = float(retry_after_s)


def _classify(exc: BaseException) -> bool:
    """The client's transient test: retry.is_transient plus the HTTP
    status semantics of the daemon (503 = backpressure, retry; 429 =
    admission, do NOT; 4xx = caller bug, fail fast)."""
    if isinstance(exc, ShedError):
        return False
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in (502, 503)
    if isinstance(exc, urllib.error.URLError):
        r = exc.reason
        if isinstance(r, BaseException) and retry.is_transient(r):
            return True
    return retry.is_transient(exc)


class FleetClient:
    """Talk to one ScoringDaemon (``base_url`` from
    ``ScoringDaemon.url`` or an operator-configured endpoint)."""

    def __init__(self, base_url: str, timeout_s: float = 30.0,
                 policy: Optional[retry.RetryPolicy] = None):
        self.base_url = str(base_url).rstrip("/")
        self.timeout_s = float(timeout_s)
        self.policy = policy or retry.DEFAULT_POLICY

    # -- wire primitives -----------------------------------------------------

    def _request(self, method: str, path: str,
                 payload: Optional[dict] = None, what: str = "fleet",
                 retried: bool = True) -> dict:
        url = self.base_url + path
        data = (json.dumps(payload).encode()
                if payload is not None else None)

        def once() -> dict:
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                body = e.read().decode(errors="replace")
                if e.code == 429:
                    ra = float(e.headers.get("Retry-After", 1.0) or 1.0)
                    tenant = path.rsplit("/", 1)[-1]
                    raise ShedError(tenant, ra) from None
                try:
                    detail = json.loads(body).get("error", body)
                except (ValueError, AttributeError):
                    detail = body
                # re-raise carrying the body; _classify keeps 502/503
                # retryable off the original exception's status code
                e.msg = f"{e.msg}: {detail}"
                raise

        if not retried:
            return once()
        return retry.call(once, what=what, policy=self.policy,
                          classify=_classify)

    # -- API -----------------------------------------------------------------

    def predict(self, tenant: str, X) -> np.ndarray:
        return self.predict_versioned(tenant, X)[0]

    def predict_versioned(self, tenant: str, X):
        """-> (predictions ndarray, served model version). Retries
        transient failures (idempotent); raises ShedError on 429."""
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        out = self._request(
            "POST", f"/v1/predict/{tenant}",
            {"rows": X.tolist()}, what="fleet/predict")
        return (np.asarray(out["predictions"], dtype=np.float64),
                int(out["version"]))

    def register(self, tenant: str, model_str: str,
                 warm_rows: Optional[int] = None) -> int:
        """Publish a model version for ``tenant`` (warm atomic swap on
        the daemon side); idempotent enough to retry — re-registering
        the same text just bumps the version again."""
        payload: Dict = {"model": str(model_str)}
        if warm_rows is not None:
            payload["warm_rows"] = int(warm_rows)
        out = self._request("POST", f"/v1/tenants/{tenant}", payload,
                            what="fleet/register")
        return int(out["version"])

    def tenants(self) -> dict:
        return self._request("GET", "/v1/tenants", what="fleet/tenants")

    def health(self) -> dict:
        return self._request("GET", "/healthz", what="fleet/health",
                             retried=False)

    def slo(self) -> dict:
        return self._request("GET", "/slo", what="fleet/slo")
