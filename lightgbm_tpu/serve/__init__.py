"""Fleet serving: the networked multi-tenant scoring front.

The paper's LRB workload is a *serving* system — every cache-admission
decision is a predict call against the freshest sliding-window model —
and the ROADMAP's north star is heavy traffic from millions of users.
This package is the network front that turns concurrent traffic into
throughput:

- ``tenants.py``  — per-tenant boosters with versioned warm atomic
  swap (``prepare_serving`` + publish-on-complete); N same-geometry
  tenants share ONE compiled program through the process-wide predict
  registry (ops/predict_cache.py), and the registry's hit counters
  prove the cross-tenant reuse.
- ``coalescer.py`` — the perf core: concurrent single/small-batch
  requests queue into a bounded buffer; a dispatcher thread drains
  them into one pow2-bucketed device batch per tick and slices the
  results back per request — bit-identical to direct predict, but K
  concurrent clients touch ~log distinct compiled programs instead of
  paying K dispatches.
- ``daemon.py``   — the stdlib ``http.server`` scoring endpoint (the
  proven obs/export.py pattern) with SLO-driven admission control:
  when a tenant's p99 error budget burns low, that tenant is shed
  (429 + ``Retry-After``) BEFORE the breach while its neighbors keep
  serving.
- ``client.py``   — the stdlib urllib client; idempotent scoring
  requests retry transient socket failures under the one bounded
  backoff policy (utils/retry.py).

Everything here is stdlib + numpy + the existing obs/ops plumbing —
importing this package never touches jax (model loads do, lazily,
exactly as direct capi serving would).
"""
from .client import FleetClient, ShedError
from .coalescer import Coalescer, QueueFull
from .daemon import ScoringDaemon
from .tenants import TenantRegistry

__all__ = [
    "Coalescer", "FleetClient", "QueueFull", "ScoringDaemon",
    "ShedError", "TenantRegistry",
]
