"""Multi-tenant model management with versioned warm atomic swap.

One serving process hosts N tenants (the LRB fleet shape: many
same-geometry sliding-window models, one per traffic slice). Each
tenant is a (booster handle, version) pair published atomically under
one lock:

- ``register`` loads the model text, runs ``GBDT.prepare_serving``
  (full forest stack + serve-bucket warmup) OFF the serving path, and
  only then publishes the new handle — in-flight requests finish on
  the old model, the first request after publish runs on an
  already-warm program (the lrb.py ``_publish`` discipline, now per
  tenant).
- same-geometry tenants share compiled programs automatically: the
  stacked predictor's dispatch goes through the process-wide
  geometry-keyed predict registry (ops/predict_cache.py), so the
  SECOND tenant's ``prepare_serving`` is a registry HIT — no re-trace,
  no recompile, and the hit counters make the cross-tenant reuse
  assertable (tests/test_fleet.py).

Tenant names are restricted to ``[a-z0-9_]`` so the per-tenant metric
families (``fleet/tenant_latency_s/<t>``) stay legal Prometheus series
names.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..analysis import lockorder
from ..obs import registry as obs
from ..utils import log

# serve-bucket floor (ops/predict_cache.SERVE_MIN_BUCKET): warming one
# floor-width batch compiles the program every 1..16-row request rides
_DEFAULT_WARM_ROWS = 16

_NAME_RE = re.compile(r"^[a-z0-9_]{1,64}$")


class _Tenant:
    __slots__ = ("name", "handle", "version")

    def __init__(self, name: str, handle, version: int):
        self.name = name
        self.handle = handle
        self.version = version


class TenantRegistry:
    """name -> (booster handle, version), swap-safe."""

    def __init__(self, warm_rows: int = _DEFAULT_WARM_ROWS):
        self.warm_rows = int(warm_rows)
        self._lock = lockorder.named_lock("serve.tenants._lock")
        self._tenants: Dict[str, _Tenant] = {}   # guarded-by: _lock

    @staticmethod
    def validate_name(name: str) -> str:
        name = str(name)
        if not _NAME_RE.match(name):
            raise ValueError(
                f"tenant name {name!r} invalid: want 1-64 chars of "
                f"[a-z0-9_] (it names metric series)")
        return name

    def register(self, name: str, model_str: str,
                 warm_rows: Optional[int] = None) -> int:
        """Load + warm a model for ``name`` and publish it atomically;
        returns the published version (1 on first registration). The
        expensive half (model parse, forest stack, serve-bucket warm
        compile/registry hit) runs OUTSIDE the lock — readers keep
        serving the old version until the single-assignment publish."""
        name = self.validate_name(name)
        from .. import capi
        handle = capi.LGBM_BoosterLoadModelFromString(str(model_str))
        wr = self.warm_rows if warm_rows is None else int(warm_rows)
        handle.gbdt.prepare_serving(warm_rows=max(wr, 0))
        with self._lock:
            old = self._tenants.get(name)
            version = (old.version + 1) if old is not None else 1
            self._tenants[name] = _Tenant(name, handle, version)
            active = len(self._tenants)
        if old is not None:
            obs.counter("fleet/model_swaps").add(1)
        obs.gauge("fleet/tenants_active").set(float(active))
        log.info("fleet tenant %r: published version %d (warm_rows=%d)",
                 name, version, wr)
        return version

    def get(self, name: str) -> Tuple[object, int]:
        """Snapshot (handle, version) for ``name``; raises KeyError for
        an unknown tenant. The returned pair stays consistent even if a
        swap publishes right after — that is the whole contract."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                raise KeyError(name)
            return t.handle, t.version

    def drop(self, name: str) -> bool:
        with self._lock:
            gone = self._tenants.pop(name, None) is not None
            active = len(self._tenants)
        if gone:
            obs.gauge("fleet/tenants_active").set(float(active))
        return gone

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def stats(self) -> Dict:
        with self._lock:
            tenants = {n: {"version": t.version}
                       for n, t in self._tenants.items()}
        return {
            "tenants": tenants,
            "active": len(tenants),
            "model_swaps": obs.counter("fleet/model_swaps").value,
        }
