"""The cross-request coalescer: many small requests, one device batch.

This is the perf core of the serving fleet. N concurrent clients each
sending 1..few-row predict requests would naively pay N dispatches (N
host->device transfers, N kernel launches, N result fetches) per
round. Here they queue into one bounded buffer instead, and a single
dispatcher thread drains the buffer once per *tick*:

    submit(tenant, X) ──┐
    submit(tenant, X) ──┤  bounded queue      dispatcher tick:
    submit(tenant, X) ──┼──────────────────►  linger <= max_wait
         ...            │  (<= max_queue      drain <= max_batch rows
    submit(tenant, X) ──┘   requests)         group by tenant
                                              concat -> ONE predict
                                              slice -> resolve futures

The concatenated batch rides the usual serving path — pow2 serve
buckets (ops/predict_cache.serve_bucket_rows) and the geometry-keyed
predict registry — so a burst of 1-row requests from K clients costs
one padded program execution instead of K. Bit-exactness is free:
rows are independent in every predict kernel (per-row one-hot, per-row
leaf match), so concat + slice returns exactly the bytes each request
would have gotten alone (tests/test_fleet.py asserts this for
binary/multiclass/1-row/odd batch shapes).

Backpressure is explicit: a full queue refuses the submission
(``QueueFull`` -> HTTP 503 + Retry-After at the daemon) rather than
growing without bound. The tick knobs (``tpu_fleet_coalesce_us``,
``tpu_fleet_max_batch``, ``tpu_fleet_queue``) trade p50 latency for
batch width.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

import numpy as np

from ..analysis import lockorder
from ..obs import registry as obs
from ..obs import reqlog
from ..utils import faults

from .tenants import TenantRegistry


# coalesced-batch-width histogram buckets: powers of two, matching the
# serve-bucket ladder the batches actually dispatch on (the default
# seconds-grade buckets would overflow at 60 "rows")
ROW_BUCKETS = tuple(float(1 << k) for k in range(15))   # 1 .. 16384


class QueueFull(RuntimeError):
    """The bounded admission queue refused a submission; retry after
    ``retry_after_s`` (the daemon surfaces this as HTTP 503)."""

    def __init__(self, depth: int, retry_after_s: float = 0.05):
        super().__init__(
            f"coalescer queue full ({depth} requests queued)")
        self.retry_after_s = float(retry_after_s)


class _Slot:
    __slots__ = ("tenant", "X", "rows", "future", "t_enqueue")

    def __init__(self, tenant: str, X: np.ndarray):
        self.tenant = tenant
        self.X = X
        self.rows = int(X.shape[0])
        self.future: "Future" = Future()
        self.t_enqueue = time.perf_counter()


def _default_predict(handle, X: np.ndarray) -> np.ndarray:
    # the same call a direct (uncoalesced) client would make — parity
    # by construction, not by reimplementation
    from .. import capi
    return capi.LGBM_BoosterPredictForMat(
        handle, X, predict_type=capi.C_API_PREDICT_NORMAL)


class Coalescer:
    """Bounded request buffer + dispatcher thread (one per daemon)."""

    def __init__(self, tenants: TenantRegistry,
                 max_wait_us: int = 2000, max_batch: int = 4096,
                 max_queue: int = 1024,
                 predict_fn: Optional[Callable] = None,
                 latency_observer: Optional[Callable] = None):
        self._tenants = tenants
        self._wait_s = max(int(max_wait_us), 0) / 1e6
        self._max_batch = max(int(max_batch), 1)
        self._max_queue = max(int(max_queue), 1)
        self._predict = predict_fn or _default_predict
        # daemon hook: per-request (tenant, latency_s) into the
        # admission controller's per-tenant histograms
        self._observe_latency = latency_observer
        self._cond = threading.Condition(
            lockorder.named_lock("serve.coalescer._cond"))
        self._q: "deque[_Slot]" = deque()     # guarded-by: _cond
        self._stop = False                    # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None

    # -- client side ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="fleet-coalescer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Drain-and-exit: queued requests still dispatch; new submits
        are refused."""
        t = self._thread
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=30.0)
        self._thread = None

    def submit(self, tenant: str, X) -> "Future":
        """Queue one request; the returned future resolves to
        ``(predictions, model_version)``. Raises QueueFull when the
        bounded buffer is at capacity and RuntimeError after stop()."""
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        slot = _Slot(str(tenant), X)
        with self._cond:
            if self._stop:
                raise RuntimeError("coalescer is stopped")
            if len(self._q) >= self._max_queue:
                obs.counter("fleet/queue_rejects").add(1)
                raise QueueFull(len(self._q))
            self._q.append(slot)
            depth = len(self._q)
            self._cond.notify_all()
        obs.counter("fleet/requests_total").add(1)
        obs.gauge("fleet/queue_depth").set(float(depth))
        return slot.future

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- dispatcher side -----------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if not self._q and self._stop:
                    return
                # linger: the first request of the tick is already
                # here; give the rest of the burst max_wait to join
                # the same device batch (skip straight to drain once
                # a full batch is queued)
                if self._wait_s > 0:
                    deadline = time.perf_counter() + self._wait_s
                    while not self._stop:
                        if (sum(s.rows for s in self._q)
                                >= self._max_batch):
                            break
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        self._cond.wait(left)
                batch = self._drain_locked()
                depth = len(self._q)
            obs.gauge("fleet/queue_depth").set(float(depth))
            self._dispatch_batch(batch)

    def _drain_locked(self) -> List[_Slot]:
        """Pop FIFO slots up to max_batch rows (always at least one —
        a single oversized request must still serve); the remainder
        stays queued for the next tick."""
        batch: List[_Slot] = []
        rows = 0
        while self._q:
            if batch and rows + self._q[0].rows > self._max_batch:
                break
            # unguarded-ok: caller holds _cond (_loop's with block)
            s = self._q.popleft()
            batch.append(s)
            rows += s.rows
        return batch

    def _dispatch_batch(self, batch: List[_Slot]) -> None:
        # group by tenant, order preserved: one concatenated predict
        # per tenant per tick (same-geometry tenants still share the
        # compiled program underneath via the predict registry)
        groups: "Dict[str, List[_Slot]]" = {}
        for s in batch:
            groups.setdefault(s.tenant, []).append(s)
        for tenant, slots in groups.items():
            self._dispatch_tenant(tenant, slots)

    def _dispatch_tenant(self, tenant: str, slots: List[_Slot]) -> None:
        try:
            handle, version = self._tenants.get(tenant)
        except KeyError as e:
            for s in slots:
                s.future.set_exception(e)
            return
        rows = sum(s.rows for s in slots)
        X = (slots[0].X if len(slots) == 1
             else np.concatenate([s.X for s in slots], axis=0))
        rid = reqlog.next_request_id()
        t0 = time.perf_counter()
        try:
            if faults.active():
                # fleet.predict / fleet.predict.<tenant>: the latency/
                # failure seam for the shed drills (utils/faults.py)
                faults.check("fleet.predict", context=tenant)
                faults.check("fleet.predict." + tenant, context=tenant)
            with reqlog.request(rid) as ctx:
                preds = self._predict(handle, X)
        except BaseException as e:        # noqa: BLE001 — each waiting
            # request gets the real error; the dispatcher must survive
            for s in slots:
                if not s.future.set_running_or_notify_cancel():
                    continue
                s.future.set_exception(e)
            return
        done = time.perf_counter()
        off = 0
        for s in slots:
            part = preds[off:off + s.rows]
            off += s.rows
            if s.future.set_running_or_notify_cancel():
                s.future.set_result((part, version))
            lat = done - s.t_enqueue
            if self._observe_latency is not None:
                self._observe_latency(tenant, lat)
        obs.histogram("fleet/coalesced_batch_rows",
                      ROW_BUCKETS).observe(float(rows))
        obs.counter("fleet/coalesced_requests").add(len(slots))
        reqlog.record(
            "request", req_id=rid, path="fleet/serve", tenant=tenant,
            rows=rows, requests=len(slots), bucket=ctx.bucket,
            model_version=version,
            latency_ms=round((done - t0) * 1e3, 3))
