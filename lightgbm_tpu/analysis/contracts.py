"""Contract linters: knob, metric-name and artifact-write discipline.

Three repo-wide contracts that used to be enforced by review only:

**Knobs** — every ``tpu_*`` knob used anywhere (attribute read,
``params.get("tpu_x")``, dict key) must be

- *declared*: a ``Config`` dataclass field (``config.py``);
- *documented*: present in ``docs/Parameters.md`` (the generated
  table — drift means someone edited by hand or forgot to regen);
- *validated*: int/float knobs must be referenced by
  ``Config.check_param_conflict`` (the repo's validation seam) —
  free-domain knobs are baselined with a justification;
- *classified* w.r.t. ``utils/checkpoint.py VOLATILE_KNOBS``: every
  VOLATILE entry must name a live Config field, and a knob whose
  reads are confined to telemetry/tooling modules must be VOLATILE —
  otherwise changing a port or a path silently invalidates every old
  checkpoint's config fingerprint.

**Metrics** — every obs metric name (``obs.counter("...")`` etc.)
must match the naming scheme ``group/name[/sub]`` (lowercase,
``[a-z0-9_]``). A NON-constant name is a label-cardinality hazard
(every distinct string becomes a new time series) and must carry a
``# bounded-cardinality: <reason>`` annotation.

**Artifacts** — run artifacts written by obs/, utils/ and tools/ must
route through ``utils/fileio.atomic_write`` (the one tmp+rename
implementation): a bare ``open(path, "w")`` there can leave a torn
file for a concurrent reader. Append-mode streams (JSONL time series)
are the designed exception; ``fileio.py`` itself is the
implementation. Waive a deliberate site with ``# atomic-ok: reason``.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, SourceFile, call_name, dotted, \
    enclosing_stmt

CHECKER = "contracts"

_KNOB_RE = re.compile(r"^tpu_[a-z0-9_]+$")
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(/[a-z0-9_]+)*$")
_BOUNDED_RE = re.compile(r"bounded-cardinality:\s*(\S.*)")
_ATOMIC_OK_RE = re.compile(r"atomic-ok:\s*(\S.*)")
_DOC_KNOB_RE = re.compile(r"\|\s*`(tpu_[a-z0-9_]+)`")

METRIC_FACTORY_NAMES = {"counter", "gauge", "timer", "histogram",
                        "latency_histogram"}
# knob-string consumers: a "tpu_x" literal inside these calls is a read
KNOB_STRING_CALLS = {"get", "getattr", "config_get", "pop",
                     "setdefault"}
# modules whose knob reads cannot alter training math: a knob read
# ONLY from here belongs in VOLATILE_KNOBS (fingerprint stability)
TELEMETRY_PREFIXES = ("lightgbm_tpu/obs/", "tools/")
TELEMETRY_FILES = ("bench.py", "lightgbm_tpu/utils/timing.py",
                   "lightgbm_tpu/utils/log.py")
# artifact-write scope of the atomic-write rule
ATOMIC_SCOPE_PREFIXES = ("lightgbm_tpu/obs/", "lightgbm_tpu/utils/",
                         "tools/")
ATOMIC_IMPL = "lightgbm_tpu/utils/fileio.py"


@dataclass
class RepoInfo:
    """Facts about the repo's contract surfaces, parsed (never
    imported) from their single-source-of-truth files."""
    config_fields: Set[str] = field(default_factory=set)
    validated_knobs: Set[str] = field(default_factory=set)
    volatile_knobs: Set[str] = field(default_factory=set)
    documented_knobs: Set[str] = field(default_factory=set)
    # pre-rename knobs accepted with a deprecation warning
    # (config.py DEPRECATED_ALIASES keys): legitimately used without
    # being dataclass fields
    deprecated_aliases: Set[str] = field(default_factory=set)


def build_repo_info(sources: List[SourceFile],
                    root: str) -> RepoInfo:
    info = RepoInfo()
    for sf in sources:
        if sf.rel == "lightgbm_tpu/config.py":
            _parse_config(sf, info)
        elif sf.rel == "lightgbm_tpu/utils/checkpoint.py":
            _parse_volatile(sf, info)
    params_md = os.path.join(root, "docs", "Parameters.md")
    if os.path.exists(params_md):
        with open(params_md, encoding="utf-8") as fh:
            info.documented_knobs = set(_DOC_KNOB_RE.findall(fh.read()))
    return info


def _parse_config(sf: SourceFile, info: RepoInfo) -> None:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    info.config_fields.add(stmt.target.id)
                if isinstance(stmt, ast.FunctionDef) and \
                        stmt.name == "check_param_conflict":
                    for n in ast.walk(stmt):
                        if isinstance(n, ast.Attribute) and \
                                _KNOB_RE.match(n.attr):
                            info.validated_knobs.add(n.attr)
                        elif isinstance(n, ast.Constant) and \
                                isinstance(n.value, str) and \
                                _KNOB_RE.match(n.value):
                            info.validated_knobs.add(n.value)
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "DEPRECATED_ALIASES"
                for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    info.deprecated_aliases.add(k.value)


def _parse_volatile(sf: SourceFile, info: RepoInfo) -> None:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "VOLATILE_KNOBS"
                for t in node.targets):
            for n in ast.walk(node.value):
                if isinstance(n, ast.Constant) and \
                        isinstance(n.value, str):
                    info.volatile_knobs.add(n.value)


# ---------------------------------------------------------------------------
# Knob linter
# ---------------------------------------------------------------------------

def _knob_uses(sf: SourceFile) -> List[Tuple[str, int]]:
    """(knob, line) for every tpu_* use in one file: attribute
    reads/writes (``cfg.tpu_x`` — never the func of a call, so
    ``autotune.tpu_compiler_params()`` is not a knob), knob-string
    arguments of get/getattr/config_get, dict-literal keys,
    subscripts and comparisons."""
    uses: List[Tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Attribute) and _KNOB_RE.match(node.attr):
            parent = sf.parent(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                continue                # a tpu_*-named function, not a knob
            uses.append((node.attr, node.lineno))
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and _KNOB_RE.match(node.value):
            parent = sf.parent(node)
            if isinstance(parent, ast.Call):
                fname = call_name(parent).rsplit(".", 1)[-1]
                if fname in KNOB_STRING_CALLS and \
                        node in parent.args:
                    uses.append((node.value, node.lineno))
            elif isinstance(parent, ast.Dict):
                if node in parent.keys:
                    uses.append((node.value, node.lineno))
            elif isinstance(parent, (ast.Subscript, ast.Compare)):
                uses.append((node.value, node.lineno))
    return uses


def check_knobs(sources: List[SourceFile], info: RepoInfo
                ) -> List[Finding]:
    out: List[Finding] = []
    reads_by_knob: Dict[str, Set[str]] = {}
    first_use: Dict[str, Tuple[str, int]] = {}
    for sf in sources:
        for knob, line in _knob_uses(sf):
            reads_by_knob.setdefault(knob, set()).add(sf.rel)
            first_use.setdefault(knob, (sf.rel, line))
            if knob not in info.config_fields and \
                    knob not in info.deprecated_aliases:
                out.append(Finding(
                    CHECKER, "undeclared-knob", sf.rel, line,
                    f"{knob!r} is used here but is not a Config "
                    "dataclass field — declare (and validate) it in "
                    "config.py", f"{knob}"))
    for knob in sorted(k for k in info.config_fields
                       if _KNOB_RE.match(k)):
        if knob not in info.documented_knobs:
            out.append(Finding(
                CHECKER, "undocumented-knob",
                "lightgbm_tpu/config.py", 1,
                f"{knob!r} is declared but missing from "
                "docs/Parameters.md — regen with "
                "'python docs/generate_params.py'", f"{knob}"))
    # VOLATILE classification
    for name in sorted(info.volatile_knobs):
        if name not in info.config_fields:
            out.append(Finding(
                CHECKER, "stale-volatile-entry",
                "lightgbm_tpu/utils/checkpoint.py", 1,
                f"VOLATILE_KNOBS entry {name!r} is not a Config "
                "field — a renamed/removed knob left the "
                "fingerprint exclusion behind", f"{name}"))
    for knob, where in sorted(reads_by_knob.items()):
        if knob not in info.config_fields or knob in info.volatile_knobs:
            continue
        semantic = [w for w in where
                    if not (w.startswith(TELEMETRY_PREFIXES)
                            or w in TELEMETRY_FILES
                            or w == "lightgbm_tpu/config.py")]
        if not semantic:
            rel, line = first_use[knob]
            out.append(Finding(
                CHECKER, "unclassified-telemetry-knob", rel, line,
                f"{knob!r} is read only from telemetry/tooling "
                f"({', '.join(sorted(where))}) but is NOT in "
                "VOLATILE_KNOBS — changing it would invalidate every "
                "old checkpoint's config fingerprint", f"{knob}"))
    return out


def check_knob_validation(sources: List[SourceFile], info: RepoInfo
                          ) -> List[Finding]:
    """Int/float tpu_* fields must be touched by check_param_conflict
    (bools are validated by parsing; strings case-by-case)."""
    out: List[Finding] = []
    for sf in sources:
        if sf.rel != "lightgbm_tpu/config.py":
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == "Config"):
                continue
            for stmt in node.body:
                if not (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    continue
                knob = stmt.target.id
                if not _KNOB_RE.match(knob):
                    continue
                ann = dotted(stmt.annotation)
                if ann not in ("int", "float"):
                    continue
                if knob in info.validated_knobs:
                    continue
                out.append(Finding(
                    CHECKER, "unvalidated-knob", sf.rel, stmt.lineno,
                    f"{knob!r} ({ann}) is never referenced by "
                    "Config.check_param_conflict — a bad value flows "
                    "straight to the consumer; add a clamp/warning "
                    "(or baseline with why the full domain is valid)",
                    f"{knob}"))
    return out


# ---------------------------------------------------------------------------
# Metric-name linter
# ---------------------------------------------------------------------------

def check_metrics(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if sf.rel == "lightgbm_tpu/obs/registry.py":
            continue                    # the factory itself
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fname = call_name(node).rsplit(".", 1)[-1]
            if fname not in METRIC_FACTORY_NAMES:
                continue
            base = call_name(node)
            if "." in base and not _looks_like_obs(base):
                continue                # e.g. collections.Counter-ish
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                if not _METRIC_NAME_RE.match(arg.value):
                    out.append(Finding(
                        CHECKER, "metric-name", sf.rel, node.lineno,
                        f"metric name {arg.value!r} does not match "
                        "the scheme group/name ([a-z0-9_] segments "
                        "joined by '/')", f"{arg.value}"))
            else:
                covered = (_BOUNDED_RE.search(sf.comment_near(node))
                           or _BOUNDED_RE.search(sf.comment_near(
                               enclosing_stmt(sf, node))))
                if not covered:
                    # a function-level annotation (above its def)
                    # covers every dynamic name inside that function
                    for fn in sf.enclosing_functions(node):
                        if _BOUNDED_RE.search(sf.comment_near(fn)):
                            covered = True
                            break
                if covered:
                    continue
                expr = ast.unparse(arg)
                out.append(Finding(
                    CHECKER, "metric-cardinality", sf.rel, node.lineno,
                    f"metric name is dynamic ({expr[:48]}) — every "
                    "distinct string becomes a new time series; "
                    "annotate the bounded label set with "
                    "'# bounded-cardinality: reason' or use a "
                    "constant name",
                    f"{sf.qualname(enclosing_stmt(sf, node))}:"
                    f"{expr[:48]}"))
    return out


def _looks_like_obs(base: str) -> bool:
    head = base.split(".", 1)[0]
    return head in ("obs", "_obs", "registry", "self") or \
        "registry" in base or "obs" in head


# ---------------------------------------------------------------------------
# Artifact-write linter
# ---------------------------------------------------------------------------

def check_artifacts(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        if not sf.rel.startswith(ATOMIC_SCOPE_PREFIXES):
            continue
        if sf.rel == ATOMIC_IMPL:
            continue                    # the tmp+rename implementation
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) != "open":
                continue
            mode = _open_mode(node)
            if mode is None or "w" not in mode:
                continue
            if _ATOMIC_OK_RE.search(sf.comment_near(node)) or \
                    _ATOMIC_OK_RE.search(sf.comment_near(
                        enclosing_stmt(sf, node))):
                continue
            out.append(Finding(
                CHECKER, "non-atomic-write", sf.rel, node.lineno,
                f"bare open(..., {mode!r}) in the artifact scope — a "
                "concurrent reader can observe a torn file; route "
                "through utils/fileio.atomic_write (or waive with "
                "'# atomic-ok: reason')",
                f"{sf.qualname(enclosing_stmt(sf, node))}:{mode}"))
    return out


def _open_mode(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def check(sources: List[SourceFile], info: RepoInfo) -> List[Finding]:
    return (check_knobs(sources, info)
            + check_knob_validation(sources, info)
            + check_metrics(sources)
            + check_artifacts(sources))
