"""Runtime lock-order detector: "no deadlock yet" becomes a checked
property.

The static half of this package proves WRITE discipline (every write
under its lock); deadlock is an ORDER property — thread 1 takes A then
B while thread 2 takes B then A — that only shows up when real threads
interleave. The repo already has the interleavings: the serving
predict-during-retrain hammer, the obs concurrent-scrape hammer and
the lrb pipeline drills. This module records the lock **acquisition
graph** while those run and fails on cycles.

Design (production pays nothing):

- ``named_lock(name)`` / ``named_rlock(name)`` are the factories the
  repo's long-lived locks are created through. With detection OFF
  (the default) they return a plain ``threading.Lock``/``RLock`` —
  zero wrapper, zero per-acquire cost.
- With detection ON (``detecting()`` context manager, or the
  ``LGBM_TPU_LOCK_ORDER=1`` env var at import), they return a
  ``_TrackedLock`` proxy that delegates to a real lock and tells the
  monitor about acquire/release. Module-level locks created at import
  time are swapped in-place for the detection window via a patch
  table (``GLOBAL_LOCKS``) — the proxy wraps the ORIGINAL lock
  object, so mutual exclusion is untouched; only visibility changes.
- The monitor keeps, per thread, the set of currently-held named
  locks; acquiring ``b`` while holding ``a`` adds the edge ``a -> b``
  (with one sample code location per new edge). Reentrant RLock
  acquires don't re-push. ``cycles()`` runs a DFS over the name
  graph; the hammer tests assert it returns nothing.

Lock names are CLASSES of locks (every ``GBDT._stacked_lock`` shares
one node): a cycle between name classes is exactly the two-booster /
two-subsystem deadlock shape the fleet-serving roadmap items will
breed. A same-name edge (two INSTANCES of one class held together)
shows up as a self-cycle — if a legitimate nesting of that shape ever
appears, it must be split into two named classes, which is the
documentation the next reader needs anyway.
"""
from __future__ import annotations

import os
import threading
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

__all__ = ["named_lock", "named_rlock", "detecting", "enabled",
           "monitor", "Monitor", "LockOrderError", "GLOBAL_LOCKS"]


class LockOrderError(AssertionError):
    """A cycle in the lock-acquisition graph."""


# locks created at import time, swapped for the detection window:
# (module dotted path, attribute path, lock-class name). A dotted
# attribute path reaches INSTANCE locks of import-time singletons
# (the default metrics registry) — the proxy wraps the ORIGINAL lock
# object, so children holding raw references stay mutually exclusive
# with the patched accessor (their acquisitions are just not seen).
GLOBAL_LOCKS: Tuple[Tuple[str, str, str], ...] = (
    ("lightgbm_tpu.ops.step_cache", "_lock", "step_cache._lock"),
    ("lightgbm_tpu.ops.predict_cache", "_lock", "predict_cache._lock"),
    ("lightgbm_tpu.utils.log", "_lock", "log._lock"),
    ("lightgbm_tpu.utils.faults", "_lock", "faults._lock"),
    ("lightgbm_tpu.obs.registry", "_default._lock",
     "obs.registry._lock"),
    ("lightgbm_tpu.obs.export", "_global_lock", "export._global_lock"),
    ("lightgbm_tpu.obs.flight", "_global_lock", "flight._global_lock"),
    ("lightgbm_tpu.obs.reqlog", "_id_lock", "reqlog._id_lock"),
    ("lightgbm_tpu.obs.reqlog", "_global_lock", "reqlog._global_lock"),
    ("lightgbm_tpu.obs.slo", "_global_lock", "slo._global_lock"),
)


class Monitor:
    """The acquisition-graph recorder. All internal state is guarded
    by a RAW lock (never a tracked one — the monitor must not observe
    itself)."""

    def __init__(self):
        # REENTRANT: a signal handler (obs/flight's SIGTERM hook) can
        # fire while the interrupted thread is inside on_acquired
        # holding this lock, and the handler's own flight-lock
        # acquisition re-enters the monitor — a plain Lock would
        # self-deadlock the process instead of letting it dump and
        # die (the PR-12 trigger-lock lesson). Worst case under
        # reentrancy is a torn edge COUNT, never a hang.
        self._mu = threading.RLock()
        # (from_name, to_name) -> [count, sample "file:line (thread)"]
        self._edges: Dict[Tuple[str, str], list] = {}
        self._names: Dict[str, int] = {}      # name -> acquire count
        self._tls = threading.local()

    # -- hooks ---------------------------------------------------------------

    def _held(self) -> Dict[int, Tuple[str, int]]:
        """This thread's held locks: id(lock) -> (name, depth)."""
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = {}
        return held

    def on_acquired(self, lock_id: int, name: str) -> None:
        held = self._held()
        if lock_id in held:             # reentrant RLock acquire
            n, depth = held[lock_id]
            held[lock_id] = (n, depth + 1)
            return
        new_edges = []
        for other_id, (other_name, _) in held.items():
            if other_id != lock_id:
                new_edges.append((other_name, name))
        held[lock_id] = (name, 1)
        with self._mu:
            self._names[name] = self._names.get(name, 0) + 1
            fresh = [e for e in new_edges if e not in self._edges]
            for e in new_edges:
                rec = self._edges.get(e)
                if rec is None:
                    self._edges[e] = [1, ""]
                else:
                    rec[0] += 1
        if fresh:
            # one sample location per NEW edge (stack walk is pricey;
            # existing edges only bump a counter)
            where = _call_site()
            with self._mu:
                for e in fresh:
                    if self._edges[e][1] == "":
                        self._edges[e][1] = where

    def on_release(self, lock_id: int) -> None:
        held = self._held()
        rec = held.get(lock_id)
        if rec is None:                 # released by a non-tracked path
            return
        name, depth = rec
        if depth > 1:
            held[lock_id] = (name, depth - 1)
        else:
            del held[lock_id]

    # -- readout -------------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], Tuple[int, str]]:
        with self._mu:
            return {e: (c, w) for e, (c, w) in self._edges.items()}

    def lock_names(self) -> List[str]:
        with self._mu:
            return sorted(self._names)

    def cycles(self) -> List[List[str]]:
        """Distinct elementary cycles in the name graph (DFS; each
        cycle reported once, rotated to its smallest node)."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges():
            adj.setdefault(a, []).append(b)
        seen_cycles = set()
        out: List[List[str]] = []

        def dfs(node: str, path: List[str], on_path: set):
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    base = cyc[:-1]
                    rot = min(range(len(base)),
                              key=lambda i: base[i])
                    canon = tuple(base[rot:] + base[:rot])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon) + [canon[0]])
                else:
                    path.append(nxt)
                    on_path.add(nxt)
                    dfs(nxt, path, on_path)
                    on_path.discard(nxt)
                    path.pop()

        for start in sorted(adj):
            dfs(start, [start], {start})
        return out

    def assert_acyclic(self) -> None:
        cycles = self.cycles()
        if cycles:
            edges = self.edges()
            lines = []
            for cyc in cycles:
                lines.append(" -> ".join(cyc))
                for a, b in zip(cyc, cyc[1:]):
                    c, w = edges.get((a, b), (0, "?"))
                    lines.append(f"    {a} -> {b}  (seen {c}x, "
                                 f"first at {w})")
            raise LockOrderError(
                "lock-acquisition cycle(s) detected — two threads "
                "taking these locks in opposite orders can deadlock:\n"
                + "\n".join(lines))

    def graph(self) -> dict:
        """JSON-able acquisition graph (for artifacts/debugging)."""
        return {
            "schema": "lightgbm-tpu/lock-order v1",
            "locks": self.lock_names(),
            "edges": [{"from": a, "to": b, "count": c, "where": w}
                      for (a, b), (c, w) in sorted(self.edges().items())],
            "cycles": self.cycles(),
        }


def _call_site() -> str:
    tname = threading.current_thread().name
    for frame in reversed(traceback.extract_stack(limit=12)[:-3]):
        if os.sep + "analysis" + os.sep not in frame.filename and \
                "threading" not in frame.filename:
            return (f"{os.path.basename(frame.filename)}:"
                    f"{frame.lineno} ({tname})")
    return f"? ({tname})"


class _TrackedLock:
    """Proxy delegating to a real Lock/RLock, reporting to the
    monitor. Wrapping an EXISTING lock object (the patch-table path)
    preserves mutual exclusion with any raw references — only the
    proxy's own acquisitions become visible."""

    __slots__ = ("_inner", "_name")

    def __init__(self, name: str, inner):
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            mon = _monitor
            if mon is not None:
                mon.on_acquired(id(self._inner), self._name)
        return got

    def release(self):
        mon = _monitor
        if mon is not None:
            mon.on_release(id(self._inner))
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):                 # pragma: no cover - debug aid
        return f"<_TrackedLock {self._name} {self._inner!r}>"


_monitor: Optional[Monitor] = None
_env_armed = os.environ.get("LGBM_TPU_LOCK_ORDER", "") not in ("", "0")
_enabled = _env_armed
if _env_armed:                          # opt-in from the environment
    _monitor = Monitor()

    def _report_at_exit():              # pragma: no cover - env mode
        import atexit

        @atexit.register
        def _dump():
            cycles = _monitor.cycles()
            if cycles:
                import sys
                print("[lock-order] CYCLES detected:\n"
                      + "\n".join(" -> ".join(c) for c in cycles),
                      file=sys.stderr)

    _report_at_exit()


def enabled() -> bool:
    return _enabled


def monitor() -> Optional[Monitor]:
    return _monitor


def named_lock(name: str):
    """A process lock belonging to the named lock CLASS. Plain
    ``threading.Lock`` unless detection is enabled — production pays
    nothing."""
    if not _enabled:
        return threading.Lock()
    if _env_armed:
        # env-armed mode has no detecting() entry point to apply the
        # patch table; piggyback on lock creation (rare — one per
        # booster/driver) to pick up module locks as they import.
        # Idempotent: already-wrapped and not-yet-imported are skipped
        _patch_globals()
    return _TrackedLock(name, threading.Lock())


def named_rlock(name: str):
    """Reentrant variant of ``named_lock`` (reentrant acquires are
    tracked once, not per depth)."""
    if not _enabled:
        return threading.RLock()
    if _env_armed:
        _patch_globals()
    return _TrackedLock(name, threading.RLock())


def _patch_globals() -> List[Tuple[object, str, object]]:
    """Swap the import-time module locks for tracked proxies (wrapping
    the ORIGINAL lock object). Returns restore records. Modules not
    yet imported are skipped — detection never forces an import."""
    import sys
    restore = []
    for mod_name, attr_path, lock_name in GLOBAL_LOCKS:
        holder = sys.modules.get(mod_name)
        if holder is None:
            continue
        *chain, attr = attr_path.split(".")
        for part in chain:
            holder = getattr(holder, part, None)
            if holder is None:
                break
        if holder is None:
            continue
        cur = getattr(holder, attr, None)
        if cur is None or isinstance(cur, _TrackedLock):
            continue
        setattr(holder, attr, _TrackedLock(lock_name, cur))
        restore.append((holder, attr, cur))
    return restore


@contextmanager
def detecting(patch_globals: bool = True):
    """Enable lock-order detection for a code block (the hammer-test
    seam). Locks created inside via the factories are tracked; known
    module-level locks are swapped for the window. Yields the
    ``Monitor``; the caller asserts ``monitor.assert_acyclic()`` (or
    inspects ``graph()``) after the block."""
    global _monitor, _enabled
    prev_mon, prev_en = _monitor, _enabled
    mon = Monitor()
    _monitor, _enabled = mon, True
    restore = _patch_globals() if patch_globals else []
    try:
        yield mon
    finally:
        for mod, attr, orig in restore:
            setattr(mod, attr, orig)
        _monitor, _enabled = prev_mon, prev_en
