"""lock-discipline checker: annotated shared state is written under
its lock.

The repo carries 20+ ``threading.Lock``/``RLock`` instances across
serving, ingest and obs whose discipline — which attribute is guarded
by which lock — was enforced purely by convention and hammer tests.
This checker makes the convention machine-checked:

**Declaring**: annotate the attribute's initialization with a trailing
comment naming the lock (an attribute on the same object for instance
state, a module global for module state)::

    self._pending = None          # guarded-by: _join_lock
    _steps = OrderedDict()        # guarded-by: _lock

A lock HELPER is declared with call syntax and matches a ``with`` on
that call::

    self._stacked_cache = None    # guarded-by: _stacked_guard()

**Checking**: every write to an annotated attribute anywhere in the
same class (any method) or module must be lexically inside a matching
``with`` block. Writes are assignments, item/attr stores through the
attribute, ``del``, augmented assignment, and calls of known mutator
methods (``append``/``update``/``pop``/``clear``/...). Reads are NOT
checked — the convention proves write discipline (readers that need a
consistent snapshot take the lock by code review, as documented at
each declaration).

**Exemptions** (each is a happens-before argument, not a hole):

- writes inside ``__init__`` / module top level — publication of the
  owning object happens-before any other thread can hold a reference;
- functions annotated ``# guarded-by: <lock>`` on their ``def`` line
  declare "called with <lock> held" — their bodies count as guarded,
  and every intra-class/module CALL SITE of such a function is
  checked to be inside the ``with`` instead;
- a single write site can be waived with ``# unguarded-ok: <reason>``.

Like jit-capture, this checker's baseline must stay empty: exemptions
live next to the code.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, SourceFile, dotted, enclosing_stmt

CHECKER = "lock_discipline"

_DECL_RE = re.compile(r"guarded-by:\s*([A-Za-z_][\w.]*(?:\(\))?)")
_WAIVE_RE = re.compile(r"unguarded-ok:\s*(\S.*)")

MUTATOR_METHODS = {
    "append", "appendleft", "add", "clear", "pop", "popitem",
    "popleft", "update", "move_to_end", "setdefault", "extend",
    "extendleft", "remove", "insert", "discard", "sort", "reverse",
}


@dataclass(frozen=True)
class _Decl:
    scope: str          # class name for self.X, "<module>" for globals
    attr: str
    lock: str           # "_join_lock" or "_stacked_guard()"


def _scope_name(sf: SourceFile, node: ast.AST) -> str:
    cls = sf.enclosing_class(node)
    return cls.name if cls is not None else "<module>"


def _collect_decls(sf: SourceFile) -> Dict[Tuple[str, str], _Decl]:
    decls: Dict[Tuple[str, str], _Decl] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        # the annotation may trail the assignment's first line OR sit
        # on its own comment line directly above (long declarations)
        m = _DECL_RE.search(sf.comment_near(node))
        if m is None:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            attr = _target_attr(t)
            if attr is None:
                continue
            scope = _scope_name(sf, node)
            decls[(scope, attr)] = _Decl(scope, attr, m.group(1))
    return decls


def _target_attr(t: ast.AST) -> Optional[str]:
    """'_pending' for ``self._pending``; '_steps' for module ``_steps``."""
    if isinstance(t, ast.Attribute) and \
            isinstance(t.value, ast.Name) and t.value.id == "self":
        return t.attr
    if isinstance(t, ast.Name):
        return t.id
    return None


def _held_locks(sf: SourceFile, node: ast.AST) -> Set[str]:
    """Lock specs lexically held at ``node``: from enclosing ``with``
    items plus any guarded-by annotation on enclosing ``def`` lines
    (the called-with-lock-held convention)."""
    held: Set[str] = set()
    for a in sf.ancestors(node):
        if isinstance(a, (ast.With, ast.AsyncWith)):
            for item in a.items:
                spec = _lock_spec(item.context_expr)
                if spec:
                    held.add(spec)
        elif isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m = _DECL_RE.search(sf.comment_near(a))
            if m is not None:
                held.add(m.group(1))
    return held


def _lock_spec(expr: ast.AST) -> str:
    """Canonical spec of a with-item: ``self._join_lock`` ->
    '_join_lock'; ``self._stacked_guard()`` -> '_stacked_guard()';
    module ``_lock`` -> '_lock'."""
    if isinstance(expr, ast.Call) and not expr.args \
            and not expr.keywords:
        inner = _lock_spec(expr.func)
        return f"{inner}()" if inner else ""
    d = dotted(expr)
    if d.startswith("self."):
        d = d[len("self."):]
    return d


def _rebinds_global(sf: SourceFile, node: ast.AST, name: str) -> bool:
    """True when a plain ``name = ...`` at ``node`` rebinds the module
    global: at module top level, or inside a function that declares
    ``global name``."""
    fns = sf.enclosing_functions(node)
    if not fns:
        return True
    for fn in fns:
        for n in ast.walk(fn):
            if isinstance(n, ast.Global) and name in n.names:
                return True
    return False


def _is_init_exempt(sf: SourceFile, node: ast.AST) -> bool:
    fns = sf.enclosing_functions(node)
    if not fns:
        return True                     # module top level
    # the attribute owner's constructor: no other thread can hold a
    # reference yet (publication happens-before thread start)
    return getattr(fns[0], "name", "") == "__init__"


def check(sources: List[SourceFile]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        decls = _collect_decls(sf)
        if not decls:
            continue
        guarded_fns = _guarded_functions(sf)
        for node in ast.walk(sf.tree):
            for attr, is_self, write_kind in _writes(node):
                # self.X binds to the enclosing class's declaration;
                # a bare name is a module global wherever it is
                # written from
                scope = (_scope_name(sf, node) if is_self
                         else "<module>")
                decl = decls.get((scope, attr))
                if decl is None:
                    continue
                if not is_self and write_kind == "write" and \
                        not _rebinds_global(sf, node, attr):
                    # a plain rebinding of a bare name inside a
                    # function WITHOUT `global` is a new local (it
                    # can never touch the module global) — only
                    # item/mutator writes reach the global unadorned
                    continue
                line = getattr(node, "lineno", 0)
                comment = sf.comment_near(node)
                if _DECL_RE.search(comment):
                    continue            # the declaration site itself
                if _WAIVE_RE.search(comment):
                    continue
                if _is_init_exempt(sf, node):
                    continue
                if decl.lock in _held_locks(sf, node):
                    continue
                qual = sf.qualname(node if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    else enclosing_stmt(sf, node))
                out.append(Finding(
                    CHECKER, "unguarded-write", sf.rel, line,
                    f"{write_kind} of {scope}.{attr} outside "
                    f"'with {decl.lock}' (declared guarded-by at its "
                    "init; waive a deliberate site with "
                    "'# unguarded-ok: reason')",
                    f"{qual}:{attr}"))
        # call sites of guarded functions must hold the lock
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_simple(node)
            lock = guarded_fns.get((_scope_name(sf, node), callee))
            if lock is None:
                continue
            if lock in _held_locks(sf, node):
                continue
            if _WAIVE_RE.search(sf.comments.get(node.lineno, "")):
                continue
            out.append(Finding(
                CHECKER, "unguarded-call", sf.rel, node.lineno,
                f"call of {callee}() outside 'with {lock}' — the "
                "callee is annotated guarded-by (its body assumes "
                "the lock is held)",
                f"{sf.qualname(enclosing_stmt(sf, node))}:{callee}"))
    return out


def _guarded_functions(sf: SourceFile) -> Dict[Tuple[str, str], str]:
    """(scope, fn name) -> lock spec, for defs annotated guarded-by."""
    out: Dict[Tuple[str, str], str] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            m = _DECL_RE.search(sf.comment_near(node))
            if m is not None:
                out[(_scope_name(sf, node), node.name)] = m.group(1)
    return out


def _callee_simple(call: ast.Call) -> str:
    d = dotted(call.func)
    if d.startswith("self."):
        d = d[len("self."):]
    return d


def _writes(node: ast.AST):
    """Yield (attr, kind) for write-shaped uses in ``node`` (one
    statement-level AST node at a time via the caller's walk)."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield from _target_writes(t)
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        yield from _target_writes(node.target)
    elif isinstance(node, ast.AugAssign):
        yield from _target_writes(node.target)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            yield from _target_writes(t)
    elif isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATOR_METHODS:
            attr = _target_attr(node.func.value)
            if attr is not None:
                yield (attr, _is_self_ref(node.func.value),
                       f"mutating call (.{node.func.attr})")


def _is_self_ref(t: ast.AST) -> bool:
    return isinstance(t, ast.Attribute)


def _target_writes(t: ast.AST):
    attr = _target_attr(t)
    if attr is not None:
        yield attr, _is_self_ref(t), "write"
        return
    # item/attr store THROUGH the annotated name: self._pending["k"]=v
    if isinstance(t, (ast.Subscript, ast.Attribute)):
        inner = _target_attr(t.value)
        if inner is not None:
            yield inner, _is_self_ref(t.value), "item write"
    if isinstance(t, (ast.Tuple, ast.List)):
        for elt in t.elts:
            yield from _target_writes(elt)
