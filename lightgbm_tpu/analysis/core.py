"""Shared infrastructure for the repo's static-analysis checkers.

Everything here is standard library only (``ast``, ``symtable``,
``tokenize``) so the analysis can run in CI without importing the
package under analysis — no jax, no device, no side effects. A
``SourceFile`` is parsed once and shared by every checker; findings
carry a line for humans and a line-independent ``key`` for the
baseline file (keys must survive unrelated edits, so they hash the
enclosing symbol, not the line number).
"""
from __future__ import annotations

import ast
import io
import json
import os
import symtable
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

# checkers whose baselines must stay EMPTY: the two bug classes with
# repo history behind them (PR 5 closure recapture, PR 7 captured
# device arrays; the serving-lock races of PR 7/8). A deliberate
# exemption for these goes INLINE next to the code as an annotated
# waiver with a reason — never silently into the baseline file.
NO_BASELINE_CHECKERS = ("jit_capture", "lock_discipline")

BASELINE_VERSION = 1


class UsageError(Exception):
    """Driver-level misuse (bad baseline file, bad arguments) —
    ``tools/run_analysis.py`` maps this to exit code 2."""


@dataclass(frozen=True)
class Finding:
    checker: str        # e.g. "jit_capture"
    rule: str           # e.g. "nonstatic-capture"
    path: str           # repo-relative posix path
    line: int           # 1-based, for humans (not part of the key)
    message: str
    detail: str         # line-stable discriminator (symbol, name, ...)

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.rule}:{self.path}:{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}/{self.rule}] "
                f"{self.message}")

    def to_json(self) -> dict:
        return {"checker": self.checker, "rule": self.rule,
                "path": self.path, "line": self.line,
                "message": self.message, "detail": self.detail,
                "key": self.key}


# ---------------------------------------------------------------------------
# Baseline file
# ---------------------------------------------------------------------------

@dataclass
class Baseline:
    """Checked-in exemption list (``tools/analysis_baseline.json``).

    Every entry carries a one-line justification; entries that no
    longer match any live finding are reported as STALE (so the file
    can only shrink toward zero, never rot). Entries for the
    NO_BASELINE_CHECKERS are refused at load."""

    path: str = ""
    entries: Dict[str, str] = field(default_factory=dict)   # key -> why

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            raise UsageError(f"unreadable baseline {path}: {e}")
        if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
            raise UsageError(
                f"baseline {path}: expected a dict with version="
                f"{BASELINE_VERSION}, got {type(doc).__name__} "
                f"version={doc.get('version') if isinstance(doc, dict) else '?'}")
        entries: Dict[str, str] = {}
        for i, e in enumerate(doc.get("entries", [])):
            if (not isinstance(e, dict) or not isinstance(e.get("key"), str)
                    or not isinstance(e.get("justification"), str)
                    or not e.get("justification").strip()):
                raise UsageError(
                    f"baseline {path}: entry {i} needs string 'key' and a "
                    "non-empty 'justification'")
            checker = e["key"].split(":", 1)[0]
            if checker in NO_BASELINE_CHECKERS:
                raise UsageError(
                    f"baseline {path}: entry {i} ({e['key']}) — "
                    f"{checker} findings cannot be baselined; fix the "
                    "code or add an inline annotated waiver with a reason")
            if e["key"] in entries:
                raise UsageError(
                    f"baseline {path}: duplicate key {e['key']}")
            entries[e["key"]] = e["justification"]
        return cls(path=path, entries=entries)

    def apply(self, findings: List[Finding]
              ) -> Tuple[List[Finding], int, List[str]]:
        """(kept findings, suppressed count, stale baseline keys)."""
        used = set()
        kept = []
        for f in findings:
            if f.key in self.entries:
                used.add(f.key)
            else:
                kept.append(f)
        stale = [k for k in self.entries if k not in used]
        return kept, len(used), stale

    def dump(self, findings: List[Finding]) -> dict:
        """Document for --update-baseline (justifications to fill in;
        NO_BASELINE_CHECKERS findings are never written)."""
        entries = []
        for f in sorted(findings, key=lambda f: f.key):
            if f.checker in NO_BASELINE_CHECKERS:
                continue
            entries.append({"key": f.key,
                            "justification": self.entries.get(
                                f.key, "TODO: justify or fix"),
                            "note": f.message})
        return {"version": BASELINE_VERSION, "entries": entries}


# ---------------------------------------------------------------------------
# Parsed source files
# ---------------------------------------------------------------------------

class SourceFile:
    """One parsed module: AST with parent links, per-line comments,
    lazily-built symtable. Checkers share one instance per file."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._parent = parent  # type: ignore[attr-defined]
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:      # pragma: no cover - parse ok above
            pass
        self._symtable: Optional[symtable.SymbolTable] = None

    # -- navigation ---------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_parent", None)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_functions(self, node: ast.AST
                            ) -> List[ast.AST]:
        """Innermost-first chain of enclosing FunctionDef/Lambda."""
        return [a for a in self.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))]

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted path of enclosing defs/classes — the line-stable
        symbol findings key on."""
        parts = []
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                parts.append(a.name)
        name = getattr(node, "name", None)
        if isinstance(name, str):
            parts.insert(0, name)
        return ".".join(reversed(parts)) or "<module>"

    # -- comments / waivers -------------------------------------------------

    def comment_near(self, node: ast.AST) -> str:
        """Trailing comment on the node's first line plus any
        comment-only lines directly above it — where annotation
        waivers live."""
        line = getattr(node, "lineno", 0)
        parts = []
        above = line - 1
        while above in self.comments and \
                self.lines[above - 1].lstrip().startswith("#"):
            parts.append(self.comments[above])
            above -= 1
        parts.reverse()
        if line in self.comments:
            parts.append(self.comments[line])
        # strip the leading hashes so an annotation spanning several
        # comment lines parses as one text (ok(a, b,\n#  c) — ...)
        return " ".join(p.lstrip("#").strip() for p in parts)

    # -- symtable -----------------------------------------------------------

    def function_table(self, node: ast.AST
                       ) -> Optional[symtable.SymbolTable]:
        """The symtable block for a FunctionDef/Lambda node (matched
        by name + line)."""
        if self._symtable is None:
            self._symtable = symtable.symtable(self.text, self.rel,
                                               "exec")
        want_line = getattr(node, "lineno", None)
        want_name = getattr(node, "name", "lambda")

        def walk(tab: symtable.SymbolTable):
            for child in tab.get_children():
                if (child.get_lineno() == want_line
                        and child.get_name() == want_name):
                    return child
                found = walk(child)
                if found is not None:
                    return found
            return None

        return walk(self._symtable)

    def free_names(self, node: ast.AST) -> List[str]:
        """Free variables of a function node (captured from enclosing
        function scopes; module globals and builtins are NOT free)."""
        tab = self.function_table(node)
        if tab is None:                  # pragma: no cover - defensive
            return []
        if isinstance(tab, symtable.Function):
            return sorted(tab.get_frees())
        return []


def iter_sources(root: str) -> List[SourceFile]:
    """The analysis scan set: the package, tools/ and bench.py.
    Tests and fixtures are deliberately excluded — synthetic
    rule-violation fixtures live there."""
    paths: List[str] = []
    pkg = os.path.join(root, "lightgbm_tpu")
    for base, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                paths.append(os.path.join(base, f))
    tools_dir = os.path.join(root, "tools")
    if os.path.isdir(tools_dir):
        for f in sorted(os.listdir(tools_dir)):
            if f.endswith(".py"):
                paths.append(os.path.join(tools_dir, f))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    out = []
    for p in paths:
        with open(p, encoding="utf-8") as fh:
            text = fh.read()
        out.append(SourceFile(p, os.path.relpath(p, root), text))
    return out


# ---------------------------------------------------------------------------
# Small AST predicates shared by checkers
# ---------------------------------------------------------------------------

def call_name(call: ast.Call) -> str:
    """Dotted name of a call target: ``jax.jit`` for
    ``jax.jit(f)``, ``get_step`` for ``get_step(...)``."""
    return dotted(call.func)


def dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def enclosing_stmt(sf: "SourceFile", node: ast.AST) -> ast.AST:
    """The statement-level ancestor of ``node`` (direct child of the
    enclosing def/class/module) — what findings key their qualname
    on, shared so sibling checkers emit identical keys."""
    cur = node
    for a in sf.ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Module)):
            return cur
        cur = a
    return cur
