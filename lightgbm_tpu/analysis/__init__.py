"""Repo-native static analysis (stdlib-only, no jax import).

Purpose-built checkers for THIS codebase's invariants, not a general
linter:

- ``jit_capture``   — functions handed to ``jax.jit`` or registered in
  the process-wide step/predict registries must close only over
  provably-static kinds (the PR-5 closure-recapture and PR-7
  captured-device-array bug classes, caught at analysis time).
- ``lock_discipline`` — ``# guarded-by: <lock>`` annotated attributes
  must only be written lexically inside a matching ``with`` block.
- ``contracts``     — ``tpu_*`` knob declaration/validation/docs/
  VOLATILE_KNOBS classification, obs metric naming + bounded label
  cardinality, atomic artifact writes in obs/utils/tools.
- ``lockorder``     — the one DYNAMIC companion: an opt-in
  instrumentation wrapper over the repo's named locks that records
  the acquisition-order graph during the thread-hammer tests and
  fails on cycles.

Driver: ``python tools/run_analysis.py`` (baseline file, ``--json``,
exit 0/1/2). This package deliberately imports nothing heavy at
module scope — ``lockorder`` is imported by production modules at
lock-creation time and must stay effectively free.
"""
