"""jit-capture checker: compiled code must not close over arrays.

The two nastiest historical bugs in this repo were closure-capture
bugs in jitted/cached code paths:

- **PR 5 (closure recapture)**: the fused training step lived in a
  per-booster closure; after the process-wide registry landed, any
  regression that silently re-captured per-booster state (labels,
  score buffers) would either bake one booster's arrays into a SHARED
  compiled program or put every booster back on its own compile. The
  only guard was a runtime conftest hit-rate assertion.
- **PR 7 (captured device arrays)**: a predict-registry wrapper closed
  over the first model's device stacks — a registry hit from a
  retrained same-geometry model would have served the FIRST model's
  arrays. Caught by a parity suite, after the fact.

This checker moves both to analysis time. Any function that is

- passed to ``jax.jit`` (call, ``@jax.jit``, ``@partial(jax.jit,..)``),
- returned by a builder registered in ``step_cache.get_step`` /
  ``predict_cache.get`` / ``StackedModel._dispatch``,

must close only over an allowlist of **static kinds**:

- module globals and builtins (not per-instance state);
- values provably scalar/hashable-static: constants, ``int()/float()/
  bool()/str()/len()/tuple()/...`` results, boolean expressions,
  arithmetic over statics, ``Config`` scalar fields (``cfg.lambda_l1``
  — the "config scalars" contract of ``gradient_builder``);
- parameters of enclosing functions whose annotation is a static type
  (``int``, ``float``, ``bool``, ``str``, ``tuple``, ``Optional`` of
  those).

Anything else — ``self``/attribute reads, results of arbitrary calls
(``jnp.asarray(...)``, ``self._device_arrays(...)``), unannotated or
``Callable`` parameters, nested closures — is flagged: those are
exactly the kinds that can bind arrays or per-booster state.

Deliberate captures (a per-instance jit whose closed-over tables ARE
the kernel constants) are waived INLINE, next to the code, with a
reason::

    # jit-capture: ok(nan_bin, cats) — per-binner jit, tables are
    # per-dataset constants
    return jax.jit(chunk)

``ok(*)`` waives every capture of a plain ``jax.jit`` site; registry
registrations accept only NAMED waivers (a shared program must
enumerate what it closes over). The checker's baseline must stay
empty — exemptions live next to the code they excuse.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, call_name, dotted

CHECKER = "jit_capture"

# call targets that register a builder whose RESULT is cached
# process-wide (named waivers only — these programs outlive a booster)
REGISTRY_CALLS = {"step_cache.get_step", "predict_cache.get"}
REGISTRY_CALL_SUFFIXES = ("._dispatch",)
# builder-returned calls that are themselves audited jit factories:
# a builder returning one of these delegates its capture contract to
# the factory's own jit site (checked at that site)
AUDITED_BUILDER_FACTORIES = {"step_cache.build_train_step",
                             "build_train_step"}

STATIC_CALL_NAMES = {
    "int", "float", "bool", "str", "len", "min", "max", "round",
    "abs", "tuple", "sorted", "range", "frozenset", "repr", "hash",
}
STATIC_METHOD_NAMES = {"bit_length"}
STATIC_ANNOTATION_NAMES = {"int", "float", "bool", "str", "tuple",
                           "Tuple", "frozenset", "FrozenSet"}

_WAIVER_RE = re.compile(
    r"jit-capture:\s*ok\(([^)]*)\)\s*[-—:]*\s*(\S.*)?")


class _Waivers:
    def __init__(self, names: Set[str], wildcard: bool):
        self.names = names
        self.wildcard = wildcard

    def covers(self, name: str, allow_wildcard: bool) -> bool:
        return name in self.names or (self.wildcard and allow_wildcard)


def _parse_waivers(*comments: str) -> Optional[_Waivers]:
    names: Set[str] = set()
    wildcard = False
    seen = False
    for c in comments:
        for m in _WAIVER_RE.finditer(c or ""):
            if not (m.group(2) or "").strip():
                continue        # a waiver without a reason is no waiver
            seen = True
            for tok in m.group(1).split(","):
                tok = tok.strip()
                if tok == "*":
                    wildcard = True
                elif tok:
                    names.add(tok)
    return _Waivers(names, wildcard) if seen else None


# ---------------------------------------------------------------------------
# Static-kind inference
# ---------------------------------------------------------------------------

class _Kinds:
    """Conservative static-expression classifier over one file."""

    def __init__(self, sf: SourceFile, config_fields: Set[str]):
        self.sf = sf
        self.config_fields = config_fields

    # -- scope bindings -----------------------------------------------------

    def _bindings(self, fn: ast.AST, name: str) -> List[ast.AST]:
        """Binding sites of ``name`` local to function ``fn`` (not
        descending into nested functions): parameter nodes, assignment
        value expressions, or the binding statement itself."""
        out: List[ast.AST] = []
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                if a.arg == name:
                    out.append(a)

        def visit(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)) \
                            and child.name == name:
                        out.append(child)
                    continue            # new scope: don't descend
                if isinstance(child, ast.Assign):
                    for t in child.targets:
                        self._match_target(t, name, child.value, out)
                elif isinstance(child, ast.AnnAssign) and child.value:
                    self._match_target(child.target, name, child.value,
                                       out)
                elif isinstance(child, ast.AugAssign):
                    self._match_target(child.target, name, child, out)
                elif isinstance(child, (ast.For, ast.AsyncFor)):
                    self._match_target(child.target, name, child, out)
                elif isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        if item.optional_vars is not None:
                            self._match_target(item.optional_vars,
                                               name, child, out)
                elif isinstance(child, ast.NamedExpr):
                    self._match_target(child.target, name, child.value,
                                       out)
                elif isinstance(child, (ast.Import, ast.ImportFrom)):
                    for alias in child.names:
                        bound = (alias.asname
                                 or alias.name.split(".")[0])
                        if bound == name:
                            out.append(child)
                visit(child)

        body = getattr(fn, "body", None)
        if isinstance(body, list):
            for stmt in body:
                visit_root = ast.Module(body=[stmt], type_ignores=[])
                visit(visit_root)
        return out

    @staticmethod
    def _match_target(target: ast.AST, name: str, value: ast.AST,
                      out: List[ast.AST]) -> None:
        if isinstance(target, ast.Name) and target.id == name:
            out.append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for i, elt in enumerate(target.elts):
                if isinstance(elt, ast.Name) and elt.id == name:
                    # tuple unpack: classify the matching element when
                    # the value is a literal tuple, else the whole RHS
                    if isinstance(value, (ast.Tuple, ast.List)) \
                            and len(value.elts) == len(target.elts):
                        out.append(value.elts[i])
                    else:
                        out.append(value)
                elif isinstance(elt, (ast.Tuple, ast.List)):
                    _Kinds._match_target(elt, name, value, out)

    # -- classification -----------------------------------------------------

    def classify_free(self, name: str, scopes: Sequence[ast.AST],
                      _depth: int = 0) -> Tuple[bool, str]:
        """(is_static, why-not) for a name captured from the given
        innermost-first chain of enclosing function scopes."""
        for fn in scopes:
            sites = self._bindings(fn, name)
            if not sites:
                continue
            idx = list(scopes).index(fn)
            for site in sites:
                if isinstance(site, ast.arg):
                    ok, why = self._param_static(site)
                elif isinstance(site, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef)):
                    ok, why = False, "a nested closure (may capture " \
                                     "arrays transitively)"
                elif isinstance(site, (ast.Import, ast.ImportFrom)):
                    ok, why = True, ""
                elif isinstance(site, (ast.For, ast.AsyncFor, ast.With,
                                       ast.AsyncWith, ast.AugAssign)):
                    ok, why = False, "bound by a loop/with/augmented " \
                                     "assignment"
                else:
                    ok, why = self.expr_static(site, scopes[idx:],
                                               _depth + 1)
                if not ok:
                    return False, why
            return True, ""
        return False, "no static binding found in enclosing scopes"

    def _param_static(self, a: ast.arg) -> Tuple[bool, str]:
        if a.annotation is not None and \
                self._ann_static(a.annotation):
            return True, ""
        ann = ast.unparse(a.annotation) if a.annotation else "unannotated"
        return False, (f"an enclosing-scope parameter ({ann}) — only "
                       "int/float/bool/str/tuple-annotated parameters "
                       "are provably static")

    def _ann_static(self, ann: ast.AST) -> bool:
        if isinstance(ann, ast.Name):
            return ann.id in STATIC_ANNOTATION_NAMES
        if isinstance(ann, ast.Attribute):
            return ann.attr in STATIC_ANNOTATION_NAMES
        if isinstance(ann, ast.Subscript):
            base = dotted(ann.value)
            tail = base.rsplit(".", 1)[-1]
            if tail == "Optional":
                return self._ann_static(ann.slice)
            return tail in STATIC_ANNOTATION_NAMES
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                return self._ann_static(
                    ast.parse(ann.value, mode="eval").body)
            except SyntaxError:
                return False
        return False

    def expr_static(self, e: ast.AST, scopes: Sequence[ast.AST],
                    _depth: int = 0) -> Tuple[bool, str]:
        """Is the value of expression ``e`` a static kind?"""
        if _depth > 12:
            return False, "expression too deep to classify"
        if isinstance(e, ast.Constant):
            return True, ""
        if isinstance(e, ast.Name):
            # local/enclosing binding, else a module global (process-
            # wide, not per-booster — allowed)
            for fn in scopes:
                if self._bindings(fn, e.id):
                    return self.classify_free(e.id, scopes, _depth)
            return True, ""
        if isinstance(e, ast.Attribute):
            if e.attr in self.config_fields:
                return True, ""     # Config scalar — the contract kind
            return False, (f"an attribute read ({ast.unparse(e)}) — "
                           "can bind arrays or per-instance state")
        if isinstance(e, ast.Call):
            fname = call_name(e)
            if fname.rsplit(".", 1)[-1] in STATIC_CALL_NAMES and \
                    "." not in fname:
                return True, ""
            if isinstance(e.func, ast.Attribute) and \
                    e.func.attr in STATIC_METHOD_NAMES:
                return True, ""
            return False, (f"the result of a call ({fname or '?'}(...))"
                           " — not provably static")
        if isinstance(e, ast.UnaryOp):
            if isinstance(e.op, ast.Not):
                return True, ""     # bool result
            return self.expr_static(e.operand, scopes, _depth + 1)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In,
                                   ast.NotIn)) for op in e.ops):
                return True, ""     # identity/membership: bool result
            for sub in [e.left] + list(e.comparators):
                ok, why = self.expr_static(sub, scopes, _depth + 1)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(e, ast.BoolOp):
            for sub in e.values:
                ok, why = self.expr_static(sub, scopes, _depth + 1)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(e, ast.BinOp):
            for sub in (e.left, e.right):
                ok, why = self.expr_static(sub, scopes, _depth + 1)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(e, ast.IfExp):
            for sub in (e.body, e.orelse):
                ok, why = self.expr_static(sub, scopes, _depth + 1)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            for sub in e.elts:
                ok, why = self.expr_static(sub, scopes, _depth + 1)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(e, ast.JoinedStr):
            return True, ""
        if isinstance(e, ast.Subscript):
            return self.expr_static(e.value, scopes, _depth + 1)
        if isinstance(e, ast.Starred):
            return self.expr_static(e.value, scopes, _depth + 1)
        return False, (f"a {type(e).__name__} expression — not "
                       "provably static")


# ---------------------------------------------------------------------------
# Site discovery
# ---------------------------------------------------------------------------

def _is_jit_call(call: ast.Call) -> bool:
    name = call_name(call)
    return name == "jit" or name.endswith(".jit")


def _is_partial_jit(call: ast.Call) -> bool:
    name = call_name(call)
    if not (name == "partial" or name.endswith(".partial")):
        return False
    return bool(call.args) and isinstance(call.args[0],
                                          (ast.Attribute, ast.Name)) \
        and _is_jit_name(call.args[0])


def _is_jit_name(node: ast.AST) -> bool:
    d = dotted(node)
    return d == "jit" or d.endswith(".jit")


def _registry_call(call: ast.Call) -> bool:
    name = call_name(call)
    if name in REGISTRY_CALLS:
        return True
    return any(name.endswith(sfx) for sfx in REGISTRY_CALL_SUFFIXES)


def _call_arg(call: ast.Call, idx: int, *kw_names: str
              ) -> Optional[ast.AST]:
    """Positional-or-keyword argument lookup — `get(key, builder=b)`
    and `jax.jit(fun=f)` must not silently bypass the audit."""
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg in kw_names:
            return kw.value
    return None


def _local_defs(sf: SourceFile, at: ast.AST, name: str
                ) -> List[ast.FunctionDef]:
    """Resolve ``name`` to FunctionDefs in the scopes enclosing ``at``
    (innermost scope wins). A name conditionally bound to several defs
    (if/else branches, two same-named builders in one method) returns
    ALL defs preceding the use — every one of them can be the runtime
    binding, so every one is audited."""
    for scope in sf.enclosing_functions(at) + [sf.tree]:
        cands: List[ast.FunctionDef] = []
        for node in ast.walk(scope):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node.name == name:
                # must belong to THIS scope, not a deeper function
                encl = sf.enclosing_functions(node)
                if (encl and encl[0] is scope) or (scope is sf.tree
                                                   and not encl):
                    cands.append(node)
        if cands:
            use_line = getattr(at, "lineno", 1 << 30)
            before = [c for c in cands if c.lineno <= use_line]
            return sorted(before or cands, key=lambda c: c.lineno)
    return []


def _key_covered_names(sf: SourceFile, call: ast.Call) -> Set[str]:
    """Names that are part of a registry call's KEY expression: a
    capture that is literally in the key cannot go stale across a
    registry hit — a different value is a different key, hence a
    different compiled program."""
    key = _call_arg(call, 0, "key")
    if key is None:
        return set()
    exprs: List[ast.AST] = []
    if isinstance(key, ast.Name):
        kinds = _Kinds(sf, set())
        for fn in sf.enclosing_functions(call):
            exprs.extend(kinds._bindings(fn, key.id))
            if exprs:
                break
    else:
        exprs.append(key)
    names: Set[str] = set()
    for e in exprs:
        if isinstance(e, ast.AST):
            for n in ast.walk(e):
                if isinstance(n, ast.Name):
                    names.add(n.id)
    return names


# ---------------------------------------------------------------------------
# Checker entry
# ---------------------------------------------------------------------------

def check(sources: List[SourceFile],
          config_fields: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    for sf in sources:
        kinds = _Kinds(sf, config_fields)
        seen_fns: Set[int] = set()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jit_call(node):
                target = _call_arg(node, 0, "fun")
                if target is not None:
                    _check_jit_target(sf, kinds, node, target,
                                      seen_fns, out)
            elif _registry_call(node):
                builder = _call_arg(node, 1, "builder")
                if builder is not None:
                    _check_registered_builder(sf, kinds, node,
                                              builder, seen_fns, out)
                elif _call_arg(node, 0, "key") is not None:
                    # a registration whose builder we cannot even
                    # locate must not pass silently
                    out.append(Finding(
                        CHECKER, "unresolvable-builder", sf.rel,
                        node.lineno,
                        f"{call_name(node)} call has no locatable "
                        "builder argument (positional #2 or "
                        "builder=) — the registered program cannot "
                        "be audited",
                        f"{sf.qualname(node)}:{call_name(node)}"))
        # decorated defs: @jax.jit / @partial(jax.jit, ...)
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_name(dec) or (
                            isinstance(dec, ast.Call)
                            and (_is_jit_call(dec)
                                 or _is_partial_jit(dec))):
                        _check_function(sf, kinds, node, node,
                                        seen_fns, out,
                                        registry=False)
    return out


def _check_jit_target(sf: SourceFile, kinds: _Kinds, call: ast.Call,
                      target: ast.AST, seen: Set[int],
                      out: List[Finding]) -> None:
    if isinstance(target, ast.Lambda):
        _check_function(sf, kinds, target, call, seen, out,
                        registry=False)
        return
    if isinstance(target, ast.Name):
        fns = _local_defs(sf, call, target.id)
        if fns:
            for fn in fns:
                _check_function(sf, kinds, fn, call, seen, out,
                                registry=False)
            return
        # a module-level def jitted by name has no frees — find it
        waivers = _parse_waivers(sf.comment_near(call))
        if waivers is not None and waivers.covers(target.id, True):
            return
        out.append(Finding(
            CHECKER, "unresolvable", sf.rel, call.lineno,
            f"jax.jit target {target.id!r} does not resolve to a "
            "local function — captures cannot be audited; waive with "
            f"'# jit-capture: ok({target.id}) — reason' if its "
            "capture discipline is established elsewhere",
            f"{sf.qualname(call)}:{target.id}"))
        return
    # jit of an arbitrary expression (e.g. jax.jit(_shard_map(...)))
    waivers = _parse_waivers(sf.comment_near(call))
    if waivers is not None and waivers.wildcard:
        return
    expr = ast.unparse(target)
    out.append(Finding(
        CHECKER, "unresolvable", sf.rel, call.lineno,
        f"jax.jit of a non-name expression ({expr[:48]}) — captures "
        "cannot be audited; waive with '# jit-capture: ok(*) — reason'",
        f"{sf.qualname(call)}:{expr[:48]}"))


def _check_registered_builder(sf: SourceFile, kinds: _Kinds,
                              call: ast.Call, builder: ast.AST,
                              seen: Set[int],
                              out: List[Finding]) -> None:
    reg = call_name(call)
    key_names = _key_covered_names(sf, call)
    if isinstance(builder, ast.Lambda):
        _check_function(sf, kinds, builder, call, seen, out,
                        registry=True, key_names=key_names)
        return
    if not isinstance(builder, ast.Name):
        out.append(Finding(
            CHECKER, "unresolvable-builder", sf.rel, call.lineno,
            f"{reg} builder is not a simple local function — the "
            "registered program's captures cannot be audited",
            f"{sf.qualname(call)}:{ast.unparse(builder)[:48]}"))
        return
    fns = _local_defs(sf, call, builder.id)
    if not fns:
        waivers = _parse_waivers(sf.comment_near(call))
        if waivers is not None and waivers.covers(builder.id, False):
            return
        out.append(Finding(
            CHECKER, "unresolvable-builder", sf.rel, call.lineno,
            f"{reg} builder {builder.id!r} does not resolve to a "
            "local function; waive with '# jit-capture: "
            f"ok({builder.id}) — reason' (named waivers only for "
            "registry registrations)",
            f"{sf.qualname(call)}:{builder.id}"))
        return
    # the REGISTERED value is what the builder returns: audit every
    # returned local function; returns of audited factories delegate
    for fn in fns:
        for ret in ast.walk(fn):
            if not isinstance(ret, ast.Return) or ret.value is None:
                continue
            if sf.enclosing_functions(ret)[0] is not fn:
                continue                # a nested function's return
            v = ret.value
            if isinstance(v, ast.Name):
                inners = _local_defs(sf, ret, v.id)
                if inners:
                    for inner in inners:
                        _check_function(sf, kinds, inner, call, seen,
                                        out, registry=True,
                                        key_names=key_names)
                    continue
            if isinstance(v, ast.Call) and \
                    call_name(v) in AUDITED_BUILDER_FACTORIES:
                continue                # audited at the factory's site
            if isinstance(v, ast.Call) and _is_jit_call(v) and v.args:
                # ``return jax.jit(step)`` — the registered program is
                # the jitted local function, audited REGISTRY-strict
                tgt = v.args[0]
                inners = (_local_defs(sf, ret, tgt.id)
                          if isinstance(tgt, ast.Name) else
                          [tgt] if isinstance(tgt, ast.Lambda) else [])
                if inners:
                    for inner in inners:
                        seen.discard(id(inner))   # registry-strict wins
                        _check_function(sf, kinds, inner, call, seen,
                                        out, registry=True,
                                        key_names=key_names)
                    continue
            if isinstance(v, ast.Lambda):
                _check_function(sf, kinds, v, call, seen, out,
                                registry=True, key_names=key_names)
                continue
            out.append(Finding(
                CHECKER, "unresolvable-builder", sf.rel, ret.lineno,
                f"builder {fn.name!r} (registered via {reg}) returns "
                f"{ast.unparse(v)[:48]!r} — not a local function or "
                "an audited factory; the registered program's "
                "captures cannot be audited",
                f"{sf.qualname(fn)}:{ast.unparse(v)[:48]}"))


def _check_function(sf: SourceFile, kinds: _Kinds, fn: ast.AST,
                    site: ast.AST, seen: Set[int],
                    out: List[Finding], registry: bool,
                    key_names: frozenset = frozenset()) -> None:
    if id(fn) in seen:
        return
    seen.add(id(fn))
    frees = sf.free_names(fn)
    if not frees:
        return
    waivers = _parse_waivers(sf.comment_near(fn),
                             sf.comment_near(site))
    scopes = sf.enclosing_functions(fn)
    qual = sf.qualname(fn)
    kind_word = "registered in the process-wide registry" if registry \
        else "jitted"
    for name in frees:
        if name in key_names:
            continue        # literally part of the registry key:
            #                 a different value is a different program
        if waivers is not None and \
                waivers.covers(name, allow_wildcard=not registry):
            continue
        ok, why = kinds.classify_free(name, scopes)
        if ok:
            continue
        hint = "named waivers only — this program outlives the " \
               "booster that built it" if registry else \
               f"'# jit-capture: ok({name}) — reason' waives it"
        out.append(Finding(
            CHECKER, "nonstatic-capture", sf.rel,
            getattr(fn, "lineno", site.lineno),
            f"{qual} is {kind_word} but closes over {name!r}: {why}; "
            f"pass it as a traced argument ({hint})",
            f"{qual}:{name}"))
