"""Training callbacks.

TPU-native counterpart of the reference python callback protocol
(reference: python-package/lightgbm/callback.py:1-222). Callbacks are
callables invoked once per boosting iteration with a ``CallbackEnv``;
ones with ``before_iteration = True`` run before the boosting update.
"""
from __future__ import annotations

import collections
from operator import gt, lt

from .utils.log import LightGBMError


class EarlyStopException(Exception):
    """Raised by callbacks to end training early (callback.py:11-22)."""

    def __init__(self, best_iteration, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


# env passed to every callback (callback.py:26-33)
CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv=True):
    """(callback.py:36-46)."""
    if len(value) == 4:
        return "%s's %s: %g" % (value[0], value[1], value[2])
    if len(value) == 5:
        if show_stdv:
            return "%s's %s: %g + %g" % (value[0], value[1], value[2],
                                         value[4])
        return "%s's %s: %g" % (value[0], value[1], value[2])
    raise ValueError("Wrong metric value")


def print_evaluation(period=1, show_stdv=True):
    """Print evaluation results every ``period`` iterations
    (callback.py:49-77)."""
    def _callback(env):
        if (period > 0 and env.evaluation_result_list
                and (env.iteration + 1) % period == 0):
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            print("[%d]\t%s" % (env.iteration + 1, result))
    _callback.order = 10
    return _callback


def record_evaluation(eval_result):
    """Record evaluation history into ``eval_result`` dict
    (callback.py:80-110)."""
    if not isinstance(eval_result, dict):
        raise TypeError("Eval_result should be a dictionary")
    eval_result.clear()

    def _init(env):
        for data_name, eval_name, _, _ in map(
                lambda x: x[:4], env.evaluation_result_list):
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env):
        if not eval_result:
            _init(env)
        for data_name, eval_name, result, _ in map(
                lambda x: x[:4], env.evaluation_result_list):
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    return _callback


def record_run(recorder):
    """Feed per-iteration spans + eval results into a RunRecorder
    (obs/recorder.py) — the engine.train telemetry seam, installed
    automatically when ``tpu_run_report`` is set.

    Defined in this module so the pipelined-eval fast path (engine.py
    builtin_only) stays eligible. Under pipelining, after-iteration
    callbacks for iteration i run one boosting update late, so the
    recorded span for i includes iteration i+1's dispatch — wall
    times are pipeline-accurate, not update-exact (the CLI driver,
    models/gbdt.py train, records update-exact spans)."""
    def _callback(env):
        recorder.tick(env.iteration + 1,
                      [x[:4] for x in (env.evaluation_result_list or [])])
    _callback.order = 25
    return _callback


def reset_parameter(**kwargs):
    """Reset parameters after the first iteration (callback.py:113-155).

    kwargs values are either a list of length num_boost_round or a
    callable(iteration) -> value. Only ``learning_rate`` and other
    booster-resettable parameters are supported.
    """
    def _callback(env):
        new_parameters = {}
        for key, value in kwargs.items():
            if key in ("num_class", "num_classes", "boosting", "boost",
                       "boosting_type", "metric", "metrics", "metric_types"):
                raise LightGBMError(f"Cannot reset {key} during training")
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to "
                        "'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are "
                                 "supported as a mapping from boosting round "
                                 "index to new parameter value.")
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds, verbose=True):
    """Early stopping on validation metrics (callback.py:158-222).

    Checks every metric on every validation set; stops when none has
    improved in ``stopping_rounds`` iterations. The training data's
    own metrics are ignored.
    """
    best_score = []
    best_iter = []
    best_score_list = []
    cmp_op = []

    def _init(env):
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            print("Training until validation scores don't improve for "
                  f"{stopping_rounds} rounds.")
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:          # bigger is better
                best_score.append(float("-inf"))
                cmp_op.append(gt)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lt)

    def _callback(env):
        if not cmp_op:
            _init(env)
        for i, eval_ret in enumerate(env.evaluation_result_list):
            score = eval_ret[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            # train-set metrics never trigger the stop (callback.py:206);
            # the train data name is user-settable (set_train_data_name)
            train_name = getattr(env.model, "_train_data_name", "training")
            if eval_ret[0] == train_name:
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    print("Early stopping, best iteration is:\n[%d]\t%s" % (
                        best_iter[i] + 1, "\t".join(
                            _format_eval_result(x)
                            for x in best_score_list[i])))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    print("Did not meet early stopping. Best iteration is:"
                          "\n[%d]\t%s" % (best_iter[i] + 1, "\t".join(
                              _format_eval_result(x)
                              for x in best_score_list[i])))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
