"""scikit-learn estimator API.

TPU-native counterpart of the reference sklearn wrapper
(reference: python-package/lightgbm/sklearn.py:128 LGBMModel,
sklearn.py:588 LGBMRegressor, :620 LGBMClassifier, :756 LGBMRanker).
Custom objectives follow the same (y_true, y_pred) -> (grad, hess)
convention via ``_ObjectiveFunctionWrapper`` and custom metrics the
(y_true, y_pred) -> (name, value, is_higher_better) convention.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
from sklearn.preprocessing import LabelEncoder

from .basic import Booster, Dataset, LightGBMError
from .engine import train

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style fobj(y_true, y_pred[, group]) to the engine's
    fobj(preds, dataset) (sklearn.py:33-94)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset: Dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_group())
        else:
            raise TypeError(
                "Self-defined objective should have 2 or 3 arguments, "
                f"got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt sklearn-style feval (sklearn.py:96-126)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset: Dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(
            "Self-defined eval function should have 2, 3 or 4 arguments, "
            f"got {argc}")


class LGBMModel(BaseEstimator):
    """Base sklearn estimator (sklearn.py:128-586)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=100,
                 subsample_for_bin=200000, objective=None, class_weight=None,
                 min_split_gain=0.0, min_child_weight=1e-3,
                 min_child_samples=20, subsample=1.0, subsample_freq=0,
                 colsample_bytree=1.0, reg_alpha=0.0, reg_lambda=0.0,
                 random_state=None, n_jobs=-1, silent=True,
                 importance_type="split", **kwargs):
        self.boosting_type = boosting_type
        self.objective = objective
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self.class_weight = class_weight
        self._Booster: Optional[Booster] = None
        self._evals_result = None
        self._best_score = None
        self._best_iteration = None
        self._other_params: Dict[str, Any] = {}
        self._objective = objective
        self._n_features = None
        self._classes = None
        self._n_classes = None
        self.set_params(**kwargs)

    def get_params(self, deep=True):
        params = super().get_params(deep=deep)
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            self._other_params[key] = value
        return self

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("class_weight", None)
        params.pop("n_estimators", None)
        params["objective"] = self._objective
        if callable(self._objective):
            params["objective"] = "None"
        elif self._objective is None:
            params["objective"] = "regression"
        alias = {
            "boosting_type": "boosting", "min_split_gain":
            "min_gain_to_split", "min_child_weight":
            "min_sum_hessian_in_leaf", "min_child_samples":
            "min_data_in_leaf", "subsample": "bagging_fraction",
            "subsample_freq": "bagging_freq", "colsample_bytree":
            "feature_fraction", "reg_alpha": "lambda_l1",
            "reg_lambda": "lambda_l2", "random_state": "seed",
            "subsample_for_bin": "bin_construct_sample_cnt",
            "n_jobs": "num_threads",
        }
        for k, v in alias.items():
            if k in params:
                val = params.pop(k)
                if val is not None:
                    params[v] = val
        if params.get("seed") is None:
            params.pop("seed", None)
        params.pop("num_threads", None)
        return params

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto",
            callbacks=None):
        """Fit the model (sklearn.py:334-502)."""
        params = self._process_params()
        fobj = None
        if callable(self._objective):
            fobj = _ObjectiveFunctionWrapper(self._objective)
            params["objective"] = "None"
        feval = None
        if callable(eval_metric):
            feval = _EvalFunctionWrapper(eval_metric)
            eval_metric = None
        if isinstance(eval_metric, str):
            eval_metric = [eval_metric]
        if eval_metric:
            params["metric"] = eval_metric

        y_orig = y
        y = np.asarray(_ravel(y))
        if self.class_weight is not None and sample_weight is None:
            sample_weight = _class_weight_to_sample_weight(
                self.class_weight, y)
        train_set = Dataset(X, label=y, weight=sample_weight,
                            group=group, init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params, free_raw_data=False)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and (vy is y or vy is y_orig):
                    valid_sets.append(train_set)
                else:
                    vw = _get_i(eval_sample_weight, i)
                    vg = _get_i(eval_group, i)
                    vi = _get_i(eval_init_score, i)
                    valid_sets.append(Dataset(
                        vx, label=_ravel(vy), weight=vw, group=vg,
                        init_score=vi, reference=train_set,
                        free_raw_data=False))
                valid_names.append(
                    eval_names[i] if eval_names and len(eval_names) > i
                    else f"valid_{i}")

        evals_result: Dict = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._n_features = (X.shape[1] if hasattr(X, "shape")
                            else len(X[0]))
        self._evals_result = evals_result or None
        self._best_iteration = (self._Booster.best_iteration
                                if self._Booster.best_iteration > 0
                                else None)
        self._best_score = self._Booster.best_score
        return self

    def predict(self, X, raw_score=False, num_iteration=-1,
                pred_leaf=False, pred_contrib=False, **kwargs):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit first")
        nf = X.shape[1] if hasattr(X, "shape") else len(X[0])
        if self._n_features is not None and nf != self._n_features:
            raise ValueError(
                "Number of features of the model must match the input. "
                f"Model n_features_ is {self._n_features} and input "
                f"n_features is {nf}")
        return self._Booster.predict(
            X, raw_score=raw_score, num_iteration=num_iteration,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib, **kwargs)

    @property
    def n_features_(self) -> int:
        if self._n_features is None:
            raise LightGBMError("No n_features found. Need to call fit "
                                "beforehand.")
        return self._n_features

    @property
    def best_score_(self):
        return self._best_score

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def objective_(self):
        return self._objective if self._objective is not None \
            else "regression"

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit "
                                "beforehand.")
        return self._Booster

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("No feature_importances found. Need to "
                                "call fit beforehand.")
        return self._Booster.feature_importance(
            importance_type=self.importance_type)


class LGBMRegressor(LGBMModel, RegressorMixin):
    """LightGBM regressor (sklearn.py:588-618)."""

    def fit(self, X, y, sample_weight=None, init_score=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_metric=None,
            early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto",
            callbacks=None):
        if self._objective is None:
            self._objective = "regression"
        super().fit(X, y, sample_weight=sample_weight,
                    init_score=init_score, eval_set=eval_set,
                    eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score,
                    eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks)
        return self


class LGBMClassifier(LGBMModel, ClassifierMixin):
    """LightGBM classifier (sklearn.py:620-754)."""

    def fit(self, X, y, sample_weight=None, init_score=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None,
            eval_metric=None, early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto",
            callbacks=None):
        self._le = LabelEncoder().fit(_ravel(y))
        encoded = self._le.transform(_ravel(y))
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            if self._objective is None or self._objective in (
                    "binary",):
                self._objective = "multiclass"
            self._other_params["num_class"] = self._n_classes
        else:
            if self._objective is None:
                self._objective = "binary"
        eval_set_enc = None
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            eval_set_enc = [(vx, self._le.transform(_ravel(vy)))
                            for vx, vy in eval_set]
        super().fit(X, encoded, sample_weight=sample_weight,
                    init_score=init_score, eval_set=eval_set_enc,
                    eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score,
                    eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks)
        return self

    def predict(self, X, raw_score=False, num_iteration=-1,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration,
                                    pred_leaf, pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:           # binary probabilities
            idx = (result >= 0.5).astype(np.int64)
        else:
            idx = np.argmax(result, axis=1)
        return self._le.inverse_transform(idx)

    def predict_proba(self, X, raw_score=False, num_iteration=-1,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        result = super().predict(X, raw_score, num_iteration, pred_leaf,
                                 pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            return np.vstack((1. - result, result)).transpose()
        return result

    @property
    def classes_(self):
        if self._classes is None:
            raise LightGBMError("No classes found. Need to call fit "
                                "beforehand.")
        return self._classes

    @property
    def n_classes_(self) -> int:
        if self._n_classes is None:
            raise LightGBMError("No classes found. Need to call fit "
                                "beforehand.")
        return self._n_classes


class LGBMRanker(LGBMModel):
    """LightGBM ranker (sklearn.py:756-821)."""

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), early_stopping_rounds=None,
            verbose=True, feature_name="auto",
            categorical_feature="auto", callbacks=None):
        if self._objective is None:
            self._objective = "lambdarank"
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set "
                             "is not None")
        self._other_params["eval_at"] = list(eval_at)
        super().fit(X, y, sample_weight=sample_weight,
                    init_score=init_score, group=group,
                    eval_set=eval_set, eval_names=eval_names,
                    eval_sample_weight=eval_sample_weight,
                    eval_init_score=eval_init_score,
                    eval_group=eval_group, eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    verbose=verbose, feature_name=feature_name,
                    categorical_feature=categorical_feature,
                    callbacks=callbacks)
        return self


def _ravel(y):
    if hasattr(y, "to_numpy"):
        y = y.to_numpy()
    return np.asarray(y).ravel()


def _get_i(seq, i):
    if seq is None:
        return None
    return seq[i] if len(seq) > i else None


def _class_weight_to_sample_weight(class_weight, y: np.ndarray):
    if class_weight == "balanced":
        classes, counts = np.unique(y, return_counts=True)
        weight_map = {c: len(y) / (len(classes) * cnt)
                      for c, cnt in zip(classes, counts)}
    elif isinstance(class_weight, dict):
        weight_map = class_weight
    else:
        raise ValueError(f"Unsupported class_weight {class_weight!r}")
    return np.asarray([weight_map.get(v, 1.0) for v in y], np.float32)
