"""CLI entry point: ``python -m lightgbm_tpu config=train.conf [k=v ...]``.

Counterpart of the reference executable main (reference: src/main.cpp).
"""
from .application import main

if __name__ == "__main__":
    main()
