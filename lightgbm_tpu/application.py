"""Command-line application: train / predict.

TPU-native counterpart of the reference Application
(reference: src/application/application.cpp:29-281 and src/main.cpp).
Parameters come from ``key=value`` argv tokens plus an optional
``config=<file>`` of ``key = value`` lines (comments with '#'), exactly
like Application::LoadParameters (application.cpp:64-108). Tasks:

- train (+ refit): load data + valids, build objective/metrics, run the
  GBDT::Train driver (models/gbdt.py:train), save output_model
- predict: load input_model, parse the data file, write output_result
- convert_model: emit the model as standalone if-else C++ code
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List

import numpy as np

from .config import Config
from .io.loader import DatasetLoader
from .metrics import create_metrics
from .models.boosting import create_boosting
from .models.gbdt import GBDT
from .objectives import create_objective
from .utils import log


def parse_config_file(path: str) -> Dict[str, str]:
    """Config-file 'key = value' lines (application.cpp:76-99)."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out.setdefault(k.strip(), v.strip())
    return out


def load_parameters(argv: List[str]) -> Config:
    """argv 'k=v' tokens override config-file values
    (application.cpp:64-108: cmd wins over file)."""
    cmd: Dict[str, str] = {}
    for tok in argv:
        if "=" in tok:
            k, v = tok.split("=", 1)
            cmd[Config.key_alias_transform(k)] = v.strip()
        elif tok:
            log.warning("Unknown parameter %s", tok)
    params = dict(cmd)
    if "config" in cmd:
        for k, v in parse_config_file(cmd["config"]).items():
            params.setdefault(Config.key_alias_transform(k), v)
    cfg = Config()
    cfg.set(params)
    # -1 = fatal-only, 0 = warnings, 1 = info, 2+ = debug (log.h:22)
    log.set_level(max(-1, min(cfg.verbosity, 2)))
    return cfg


def _rel_to_config(cfg: Config, path: str) -> str:
    """Data paths in a config file resolve relative to that file
    (matching how the reference examples are invoked from their dir)."""
    if path and not os.path.isabs(path) and not os.path.exists(path) \
            and cfg.config:
        cand = os.path.join(os.path.dirname(os.path.abspath(cfg.config)),
                            path)
        if os.path.exists(cand):
            return cand
    return path


class Application:
    """Application (application.cpp:29-63)."""

    def __init__(self, argv: List[str]):
        self.config = load_parameters(argv)
        if self.config.task in ("train", "refit") and not self.config.data:
            log.fatal("No training/prediction data, application quit")

    def run(self) -> None:
        task = self.config.task
        if task == "train":
            self.train()
        elif task == "predict":
            self.predict()
        elif task == "convert_model":
            self.convert_model()
        elif task == "refit":
            self.refit()
        else:
            log.fatal(f"Unknown task: {task}")

    # -- train (application.cpp:110-232 LoadData + Train) -------------------

    def train(self) -> None:
        cfg = self.config
        loader = DatasetLoader(cfg)
        train_path = _rel_to_config(cfg, cfg.data)
        train_data = loader.load_from_file(train_path)

        objective = create_objective(cfg.objective, cfg)
        if objective is not None:
            objective.init(train_data.metadata, train_data.num_data)
        from .basic import _resolve_metric_names
        metric_names = _resolve_metric_names(cfg)
        train_metrics = []
        if cfg.is_provide_training_metric:
            train_metrics = create_metrics(
                metric_names, cfg, train_data.metadata, train_data.num_data)

        booster = create_boosting(cfg.boosting_type())
        if cfg.input_model:
            in_path = _rel_to_config(cfg, cfg.input_model)
            with open(in_path) as fh:
                booster.load_model_from_string(fh.read(), source=in_path)
            booster.init_from_loaded(cfg, train_data, objective,
                                     train_metrics)
        else:
            booster.init(cfg, train_data, objective, train_metrics)

        for i, vpath in enumerate(cfg.valid):
            vdata = loader.load_from_file(_rel_to_config(cfg, vpath),
                                          reference=train_data)
            vmetrics = create_metrics(metric_names, cfg, vdata.metadata,
                                      vdata.num_data)
            booster.add_valid_data(vdata, vmetrics,
                                   os.path.basename(vpath))
        # tpu_resume_from: continue a killed run from its checkpoint
        # bundle/dir, bit-identically (utils/checkpoint.py)
        booster.train(cfg.snapshot_freq, cfg.output_model,
                      resume_from=cfg.tpu_resume_from)

    def refit(self) -> None:
        """Task refit: re-learn input_model's leaf values on `data`
        (application.cpp task=refit -> GBDT::RefitTree)."""
        cfg = self.config
        model_path = _rel_to_config(cfg, cfg.input_model)
        if not model_path or not os.path.isfile(model_path):
            log.fatal("refit requires input_model")
        loader = DatasetLoader(cfg)
        train_data = loader.load_from_file(_rel_to_config(cfg, cfg.data))
        objective = create_objective(cfg.objective, cfg)
        if objective is not None:
            objective.init(train_data.metadata, train_data.num_data)
        booster = create_boosting(cfg.boosting_type())
        with open(model_path) as fh:
            booster.load_model_from_string(fh.read(), source=model_path)
        booster.init_from_loaded(cfg, train_data, objective, [])
        booster.refit_existing()
        booster.save_model_to_file(cfg.output_model)
        log.info("Refit model saved to %s", cfg.output_model)

    # -- predict (application.cpp:234-249) ----------------------------------

    def predict(self) -> None:
        cfg = self.config
        model_path = _rel_to_config(cfg, cfg.input_model)
        if not model_path or not os.path.isfile(model_path):
            log.fatal(f"Model file {cfg.input_model!r} not found; set "
                      "input_model for the predict task")
        booster = GBDT()
        with open(model_path) as fh:
            booster.load_model_from_string(fh.read(), source=model_path)
        loader = DatasetLoader(cfg)
        data_path = _rel_to_config(cfg, cfg.data)
        X, _ = loader.load_predict_matrix(data_path,
                                          booster.max_feature_idx + 1)
        n_iter = cfg.num_iteration_predict
        if cfg.predict_leaf_index:
            out = booster.predict_leaf_index(X, n_iter)
        elif cfg.predict_contrib:
            out = booster.predict_contrib(X, n_iter)
        elif cfg.predict_raw_score:
            out = booster.predict_raw(
                X, n_iter, pred_early_stop=cfg.pred_early_stop,
                pred_early_stop_freq=cfg.pred_early_stop_freq,
                pred_early_stop_margin=cfg.pred_early_stop_margin)
        else:
            out = booster.predict(
                X, n_iter, pred_early_stop=cfg.pred_early_stop,
                pred_early_stop_freq=cfg.pred_early_stop_freq,
                pred_early_stop_margin=cfg.pred_early_stop_margin)
        out = np.asarray(out)
        out_path = cfg.output_result or "LightGBM_predict_result.txt"
        with open(out_path, "w") as fh:
            if out.ndim == 1:
                for v in out:
                    fh.write(f"{v:g}\n")
            else:
                for row in out:
                    fh.write("\t".join(f"{v:g}" for v in row) + "\n")
        log.info("Finished prediction; results saved to %s", out_path)

    # -- convert_model (if-else codegen) -------------------------------------

    def convert_model(self) -> None:
        cfg = self.config
        model_path = _rel_to_config(cfg, cfg.input_model)
        if not model_path or not os.path.isfile(model_path):
            log.fatal("convert_model requires input_model")
        from .models.codegen import model_to_if_else
        booster = GBDT()
        with open(model_path) as fh:
            booster.load_model_from_string(fh.read(), source=model_path)
        code = model_to_if_else(booster)
        with open(cfg.convert_model, "w") as fh:
            fh.write(code)
        log.info("Converted model saved to %s", cfg.convert_model)


def main(argv: List[str] = None) -> None:
    if argv is None:
        argv = sys.argv[1:]
    Application(argv).run()
