"""Parameter/config system.

TPU-native counterpart of the reference config machinery
(reference: include/LightGBM/config.h:27, src/io/config.cpp:153,
src/io/config_auto.cpp:4). One dataclass holds every documented parameter;
aliases are resolved before parsing; cross-parameter conflicts are checked
like Config::CheckParamConflict (src/io/config.cpp:202).

Parameters flow through the same four surfaces as the reference: CLI
``key=value`` argv, config files, param strings, and Python dicts.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .utils import log

# ---------------------------------------------------------------------------
# Alias table (reference: src/io/config_auto.cpp:4-156). alias -> canonical.
# ---------------------------------------------------------------------------
ALIAS_TABLE: Dict[str, str] = {
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective", "app": "objective", "application": "objective",
    "boosting_type": "boosting", "boost": "boosting",
    "train": "data", "train_data": "data", "train_data_file": "data",
    "data_filename": "data",
    "test": "valid", "valid_data": "valid", "valid_data_file": "valid",
    "test_data": "valid", "test_data_file": "valid", "valid_filenames": "valid",
    "num_iteration": "num_iterations", "n_iter": "num_iterations",
    "num_tree": "num_iterations", "num_trees": "num_iterations",
    "num_round": "num_iterations", "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations", "n_estimators": "num_iterations",
    "shrinkage_rate": "learning_rate", "eta": "learning_rate",
    "num_leaf": "num_leaves", "max_leaves": "num_leaves", "max_leaf": "num_leaves",
    "tree": "tree_learner", "tree_type": "tree_learner",
    "tree_learner_type": "tree_learner",
    "num_thread": "num_threads", "nthread": "num_threads",
    "nthreads": "num_threads", "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed", "random_state": "seed",
    "min_data_per_leaf": "min_data_in_leaf", "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction", "subsample": "bagging_fraction",
    "bagging": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction", "colsample_bytree": "feature_fraction",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "max_tree_output": "max_delta_step", "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2", "lambda": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints", "monotone_constraint": "monotone_constraints",
    "feature_contrib": "feature_contri", "fc": "feature_contri",
    "fp": "feature_contri", "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename",
    "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename",
    "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "hist_pool_size": "histogram_pool_size",
    "data_seed": "data_random_seed",
    "model_output": "output_model", "model_out": "output_model",
    "save_period": "snapshot_freq",
    "model_input": "input_model", "model_in": "input_model",
    "predict_result": "output_result", "prediction_result": "output_result",
    "predict_name": "output_result", "prediction_name": "output_result",
    "pred_name": "output_result", "name_pred": "output_result",
    "init_score_filename": "initscore_filename",
    "init_score_file": "initscore_filename", "init_score": "initscore_filename",
    "input_init_score": "initscore_filename",
    "valid_data_init_scores": "valid_data_initscores",
    "valid_init_score_file": "valid_data_initscores",
    "valid_init_score": "valid_data_initscores",
    "is_pre_partition": "pre_partition",
    "is_enable_bundle": "enable_bundle", "bundle": "enable_bundle",
    "is_sparse": "is_enable_sparse", "enable_sparse": "is_enable_sparse",
    "sparse": "is_enable_sparse",
    "two_round_loading": "two_round", "use_two_round_loading": "two_round",
    "is_save_binary": "save_binary", "is_save_binary_file": "save_binary",
    "load_from_binary_file": "enable_load_from_binary_file",
    "binary_load": "enable_load_from_binary_file",
    "load_binary": "enable_load_from_binary_file",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column", "group_id": "group_column",
    "query_column": "group_column", "query": "group_column",
    "query_id": "group_column",
    "ignore_feature": "ignore_column", "blacklist": "ignore_column",
    "cat_feature": "categorical_feature",
    "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "is_predict_raw_score": "predict_raw_score",
    "predict_rawscore": "predict_raw_score", "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index",
    "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib", "contrib": "predict_contrib",
    "convert_model_file": "convert_model",
    "num_classes": "num_class",
    "unbalance": "is_unbalance", "unbalanced_sets": "is_unbalance",
    "metrics": "metric", "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at", "ndcg_at": "eval_at",
    "map_eval_at": "eval_at", "map_at": "eval_at",
    "num_machine": "num_machines",
    "local_port": "local_listen_port", "port": "local_listen_port",
    "machine_list_file": "machine_list_filename",
    "machine_list": "machine_list_filename", "mlist": "machine_list_filename",
    "workers": "machines", "nodes": "machines",
}


def _compile_cache_from_cpu_knob(v: Any) -> int:
    """Value remap of the pre-rename tpu_compile_cache_cpu: its 1 (CPU
    opt-in) is tpu_compile_cache=1; its 0 meant "CPU off, TPU still
    on" — which is the new knob's -1 auto, NOT its 0 (that would turn
    the cache off on TPU/GPU too)."""
    try:
        return 1 if int(float(v)) == 1 else -1
    except (TypeError, ValueError):
        return -1


# renamed knobs accepted with a deprecation warning. Unlike
# ALIAS_TABLE these remap the VALUE too, so they are resolved in
# Config.set() (on the normalized pre-alias key), not in
# key_alias_transform — an alias-table entry would silently pass the
# old value through with changed semantics.
DEPRECATED_ALIASES = {
    "tpu_compile_cache_cpu": ("tpu_compile_cache",
                              _compile_cache_from_cpu_knob),
}


@dataclass
class Config:
    """All parameters with reference defaults (include/LightGBM/config.h)."""

    # --- core ---
    config: str = ""
    task: str = "train"                    # train, predict, convert_model, refit
    objective: str = "regression"
    boosting: str = "gbdt"                 # gbdt, rf, dart, goss
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"           # serial, feature, data, voting
    num_threads: int = 0
    device_type: str = "cpu"               # cpu, gpu, tpu
    seed: int = 0

    # --- learning control ---
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    early_stopping_round: int = 0
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    verbosity: int = 1

    # --- IO / dataset ---
    max_bin: int = 255
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    histogram_pool_size: float = -1.0
    data_random_seed: int = 1
    output_model: str = "LightGBM_model.txt"
    snapshot_freq: int = -1
    input_model: str = ""
    output_result: str = "LightGBM_predict_result.txt"
    initscore_filename: str = ""
    valid_data_initscores: List[str] = field(default_factory=list)
    pre_partition: bool = False
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    use_missing: bool = True
    zero_as_missing: bool = False
    two_round: bool = False
    save_binary: bool = False
    enable_load_from_binary_file: bool = True
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: str = ""

    # --- predict ---
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    num_iteration_predict: int = -1
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0

    # --- convert model ---
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # --- objective ---
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    max_position: int = 20
    label_gain: List[float] = field(default_factory=list)

    # --- metric ---
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])

    # --- network ---
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # --- device (gpu params kept for config compatibility; tpu_* are ours) ---
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    # TPU-native additions: histogram accumulation dtype and device batch
    # size. tpu_use_dp=true accumulates histograms at f32 grade via the
    # bf16 hi/lo decomposition (wave cap 25); false = single bf16
    # (2^-9 relative rounding on grad/hess, wave cap 32).
    tpu_use_dp: bool = True
    tpu_hist_chunk: int = 0          # rows per Pallas grid step; 0 = auto
    tpu_donate_buffers: bool = True
    # leaves split per device step (ops/wave_grower.py): one wave
    # histogram pass serves this many leaves at once. 1 = exact
    # reference leaf-wise order; 0 = auto: 24 with tpu_use_dp (hi/lo
    # channel budget, kept a multiple of 8 for sublane alignment) or 32
    # without — values above the active cap are clamped with a warning.
    # NOTE: with W > 1 the grown tree can differ from the reference's
    # strict leaf-wise order when the leaf budget binds mid-wave; set
    # tpu_wave_size=1 for exact reference parity.
    tpu_wave_size: int = 0
    # int8 gradient quantization (analog of modern LightGBM's quantized
    # training): g/h are stochastically rounded to integers in
    # [-127, 127] per tree and histograms accumulate exactly in int32
    # int8 MXU products — 2x the bf16 rate and a 42-leaf wave
    # (3 channels). Costs ~1e-3 AUC-grade noise on the split gains;
    # serial tree_learner without EFB bundles only.
    tpu_quantized_hist: bool = False
    # count-proxy histograms (int8 quantized mode only): drop the count
    # channel from the MXU histogram dot so 2 channels x W <= 128 lanes
    # buys 64-leaf waves — fewer full-data passes per tree (~20% faster
    # at HIGGS scale). Per-bin counts become conservative LOWER BOUNDS
    # (max(|sum g_q|, sum h_q)/127) consumed only by the
    # min_data_in_leaf candidate gate, which can then over-prune but
    # never admits a split the exact gate would reject; per-leaf counts
    # (leaf_count / internal_count in the model file) stay exact via
    # partition-mask counting. -1 = auto (on when tpu_quantized_hist
    # and the fused kernel is eligible: serial/data learner, no EFB
    # bundles, no forced splits, no categoricals); 0 = off; 1 = on.
    tpu_count_proxy: int = -1
    # quantized histogram reduction (data-parallel learner only): psum
    # the int32 quantized histogram representation across the mesh and
    # dequantize AFTER the collective, instead of psumming dequantized
    # f32 sums — the communication-compression analog of LightGBM's
    # quantized distributed training. Exact integer addition on the
    # wire (no f32 rounding across shards) and, with the count-proxy
    # tier, a 2-channel payload (33% less ICI traffic than the
    # 3-channel f32 histogram). Valid because the quantization scales
    # are GLOBAL (pmax over shards), so dequantization commutes with
    # the sum. -1 = auto (on when tpu_quantized_hist is active under
    # tree_learner=data and the global row count stays inside the
    # int32 sum bound; the int-vs-f32 wire choice is autotuned on real
    # meshes, ops/autotune.py); 0 = off (f32 psum); 1 = force.
    tpu_quantized_psum: int = -1
    # packed psum wire width (parallel/learners.py): with the
    # quantized psum active the collective payload is integer-valued,
    # so it can cross the DCN as int16 (or int8) whenever the
    # 127 * num_rows_global wrap bound proves the narrow sum cannot
    # overflow — the narrowing cast, integer psum and widening cast
    # are all exact, so the result is BIT-identical to the int32 wire.
    # The same knob gates the delta-encoded (code, feat, row)
    # coordinate transport of the sparse tier (io/sparse.py). -1 =
    # auto (narrowest provably-safe width); 0 = legacy int32/f32 wire;
    # 1 = force-narrow where safe (falls back with a warning where the
    # wrap bound refuses).
    tpu_psum_wire: int = -1
    # overlap-structured histogram collective (parallel/learners.py):
    # split the [wave, feature, bin, channel] histogram psum into
    # independent double-buffered slot collectives along the feature
    # axis so XLA can overlap one slot's DCN reduction with local
    # compute instead of stalling the step on a single monolithic
    # psum. psum is elementwise across shards, so the slot split is
    # BIT-identical to the fused collective (for f32 AND integer
    # wires). -1 = auto (async slots on data-parallel meshes; the
    # async-vs-sync arm is autotuned per (mesh, payload) key on real
    # TPUs, ops/autotune.py tune_hist_psum_async); 0 = sync (one
    # psum); 1 = force async slots.
    tpu_async_psum: int = -1
    # background checkpoint writer (utils/checkpoint.py): the
    # collective score gather stays on the training path, but rank-0's
    # bundle serialization + atomic file writes move to a bounded-queue
    # writer thread, hiding checkpoint I/O behind subsequent
    # iterations. Commit-point ordering is preserved (scores sidecar
    # first, bundle second, both atomic_write), checkpoint/
    # write_failures semantics are unchanged, and the queue is drained
    # at train end and before any resume read. -1 = auto (on when
    # checkpointing is active); 0 = synchronous writes; 1 = force.
    tpu_ckpt_async: int = -1
    # 4-bit packed HBM bins (the reference's Dense4bitsBin as a COMPUTE
    # tier, dense_nbits_bin.hpp): when max_bin <= 16 and either the
    # count-proxy int8 path or the hi/lo exact tier (tpu_use_dp) is
    # active, two features share one byte in HBM and the Pallas
    # kernels unpack nibbles in VMEM — half the bin-matrix HBM, double
    # the rows/chip. -1 = auto (on when eligible); 0 = off.
    tpu_packed_bins: int = -1
    # exact-tier (tpu_use_dp, non-quantized) histogram channel layout
    # (ops/hist_wave.py): "hilo5" = the 5-channel bf16 hi/lo rows
    # (wave cap 24); "hilo4" = 4 channels plus a second count dot
    # (cap 32 — 25% fewer full-data passes per tree); "hilo3" = the
    # fused hess/count plane (cap 40; constant-unit-hessian objectives
    # without row weights only — requesting it elsewhere falls back to
    # hilo4 with a warning). All three reconstruct identical f32-grade
    # sums. "" = auto: timed once per (features, bins, device) on a
    # real TPU (ops/autotune.py tune_exact_tier), widest feasible wave
    # off-TPU (the XLA path is layout-free, so only the wave cap — the
    # pass count — matters there).
    tpu_exact_tier: str = ""
    # Pallas kernel autotuning (ops/autotune.py): "on" times a small
    # VMEM-feasible set of tile configurations on the first encounter
    # of a (kernel, features, bins, dtype-tier, device-kind) shape and
    # persists the winner to the on-disk tuning cache; "off" pins the
    # measured per-tier defaults; "exhaustive" sweeps the full
    # candidate grid (slower first run, same cache afterwards). Tuning
    # only ever runs on a real TPU backend.
    tpu_autotune: str = "on"
    # tuning-cache file path; empty = <shared cache dir>/tuning_vN.json
    # (io/dataset.py default_cache_dir, LGBM_TPU_CACHE_DIR overridable).
    # The file is versioned JSON: a version mismatch re-tunes instead
    # of trusting stale entries (the dataset binary-token discipline).
    tpu_tuning_cache: str = ""
    # write an xprof/tensorboard device trace of the training loop here
    # (obs/profiler.py ProfileWindow brackets the loop with
    # jax.profiler.start/stop_trace; phase names appear as
    # TraceAnnotation spans inside the capture)
    tpu_profile_dir: str = ""
    # iterations to trace when tpu_profile_dir is set: 0 = the whole
    # boosting loop; N > 0 traces exactly N iterations starting at
    # iteration 2 (skipping the compile-dominated first iteration), so
    # the capture shows steady-state device work
    tpu_profile_iters: int = 0
    # run-report artifact path (obs/recorder.py): every training run
    # writes a versioned JSON (or, with a .jsonl suffix, JSONL) report
    # with per-iteration wall times / leaves / HBM / transfer-byte
    # deltas, the phase table, and the transfer counters — perf work
    # diffs these artifacts instead of log tails. Empty = no report.
    tpu_run_report: str = ""
    # slow-iteration watchdog (obs/recorder.py): warn with the current
    # phase table when an iteration exceeds this factor x the trailing
    # median iteration time (last 64, armed after 8). 0 disables.
    tpu_watchdog_factor: float = 8.0
    # iterations between host checks for the "no more splits" stop
    # (gbdt.cpp:393-409); device→host reads are high-latency, so the stop
    # is detected periodically instead of every iteration
    tpu_stop_check_interval: int = 8
    # iterations between forced dispatch-queue drains (a scalar
    # device→host readback). Async dispatch otherwise lets hundreds of
    # queued iterations pile up, which measurably degrades sustained
    # throughput on RPC-tunneled backends (~2.4x over 500 iterations);
    # a bounded queue keeps throughput flat at the short-chain rate.
    # 0 disables (queue unbounded).
    tpu_dispatch_sync_interval: int = 32
    # streamed TPU-side ingest (io/ingest.py): value->bin mapping runs
    # on device as a jitted chunked kernel, raw row chunks stream
    # host->device double-buffered, and the feature-major bin matrix is
    # assembled directly on device — the full host bin matrix,
    # transpose and bulk upload disappear. Bit-exact against the host
    # binner. -1 = auto (on when running on a real TPU); 0 = off
    # (host binner); 1 = force on any backend (parity tests). Datasets
    # where EFB actually bundles take the host path regardless, so the
    # bundling decision and bundled matrix stay bit-identical.
    tpu_ingest: int = -1
    # rows per ingest pipeline chunk; 0 = auto (a power of two sized so
    # one chunk carries ~64 MB of raw values).
    tpu_ingest_chunk_rows: int = 0
    # out-of-core disk->device ingest (io/loader.py): the two-round
    # loader's round-2 row blocks feed the streamed device binner
    # (io/ingest.py IngestStream) directly, so the [F, N] device bin
    # matrix assembles without ever materializing the full host value
    # matrix — peak host RSS is bounded by the block size, not N.
    # Bit-exact against the in-memory loader (same mappers, same
    # value->bin kernel). -1 = auto (stream whenever the two-round
    # loader runs and the device binner is available); 0 = off (the
    # two-round loader materializes host bins, the pre-OOC behavior);
    # 1 = force the two-round streaming route for file loads even when
    # ``two_round`` is unset (parity tests, RSS-bounded ingest of
    # bigger-than-RAM files).
    tpu_out_of_core: int = -1
    # rows per out-of-core round-2 block (the loader's disk-read
    # granularity; the device binner re-chunks to its own pipeline
    # width downstream); 0 = auto (256k rows).
    tpu_ooc_block_rows: int = 0
    # hashed GOSS sampling (models/boosting.py): the top-gradient +
    # uniform-rest draw uses the shard-invariant lowbias32 hash of the
    # GLOBAL row index and a per-tree salt (the PR-4 bagging scheme)
    # instead of a positional PRNG, so the sampled mask is identical
    # under any row sharding/padding AND the sampler rides the fused
    # step as traced arrays — GOSS boosters become step-cache eligible
    # (windows 2+ retrain at 0.00 s compile). -1 = auto (hashed);
    # 0 = legacy positional PRNG sampler (the parity/repro oracle —
    # per-booster jit, step-cache ineligible); 1 = hashed.
    tpu_goss_hash: int = -1
    # process-wide compiled-step registry (ops/step_cache.py): the fused
    # training step becomes a pure function of an explicit geometry key
    # and the jitted callable is shared across boosters — a per-window
    # retrain loop (lrb.py) or a test suite compiles each distinct
    # geometry ONCE instead of once per booster. A registry hit is
    # bit-exact by construction (the key covers everything that shapes
    # the trace; data flows through traced arguments). -1 = auto (on);
    # 0 = off (per-booster closures, the pre-cache behavior); 1 = on.
    tpu_step_cache: int = -1
    # shape bucketing for the shared step (ops/step_cache.py): rows pad
    # up to this policy's width with a validity mask zeroing the pad
    # rows, the histogram bin axis pads to the next power of two and
    # the feature axis to a multiple of 8 (trivial-column exclusion and
    # observed bin counts make BOTH data-dependent), so boosters whose
    # data shapes land in the same buckets share ONE compiled step.
    # -1 = auto (rows: next power of two, min 256); 0 = exact shapes
    # everywhere (shared only between identically-shaped boosters);
    # N > 0 = rows round up to a multiple of N. Pad rows carry exact
    # +0.0 grad/hess and a zero bagging mask, pad bins/features are
    # masked per-feature via the traced metadata — histograms, root
    # aggregates, the stochastic-rounding stream and renew percentiles
    # are bit-identical to the exact-shape run.
    tpu_row_bucket: int = -1
    # process-wide geometry-keyed predict registry
    # (ops/predict_cache.py): the stacked predictor's dispatch becomes
    # a pure function of an explicit geometry key (table offsets,
    # padded split/leaf axes, classes, tree-chunk/step counts, row
    # bucket, device kind) held in a bounded LRU — a retrained model
    # with the same geometry (the sliding-window workload) hits a warm
    # compiled program instead of re-tracing, and the hit/miss/stack
    # counters make the reuse observable. -1 = auto (on); 0 = off
    # (per-model dispatch closures, no counters — jax's own trace
    # cache still dedupes identical shapes); 1 = on.
    tpu_predict_cache: int = -1
    # serving-batch shape buckets (ops/predict_cache.py
    # serve_bucket_rows): online predict batches pad up to this
    # policy's width so a live request stream (1..4096-row batches)
    # touches a handful of compiled programs instead of one per
    # distinct batch size. Bit-exact: rows are independent in every
    # predict kernel and pad rows are sliced off. -1 = auto (next
    # power of two, floor 16; pow2/16 steps above 16k); 0 = exact
    # shapes (one trace per batch size); N > 0 = round up to a
    # multiple of N.
    tpu_serve_bucket: int = -1
    # persistent XLA compile cache, backend-aware (ops/autotune.py
    # ensure_compile_cache): -1 = auto — wired on TPU and GPU (where
    # the expensive Mosaic/Triton compiles live and deserialization is
    # sound), off on CPU because this image's jax 0.4.x CPU backend
    # flakily segfaults while DESERIALIZING warm entries (~1/3 of warm
    # runs). 1 = on everywhere, with the CPU side gated on jax >= 0.5
    # (where the deserializer is fixed; older jax warns and stays
    # off). 0 = off on every backend. An explicit
    # jax_compilation_cache_dir always wins. Replaces the CPU-only
    # tpu_compile_cache_cpu (accepted as a warned alias: its 1 maps to
    # 1, its 0 to the -1 auto default).
    tpu_compile_cache: int = -1
    # cross-thread span trace (obs/trace.py): write a Chrome
    # trace-event / Perfetto-loadable JSON here showing ingest worker
    # chunks, training iterations, step-cache compiles/hits, watchdog
    # firings and (lrb.py) per-window derive/train/evaluate spans with
    # correct pid/tid across threads. Flushed at run finish, after
    # every lrb window, and at interpreter exit. Empty = off.
    tpu_trace: str = ""
    # span-trace ring capacity in EVENTS (obs/trace.py): the buffer
    # keeps the most recent N events, so a million-iteration serving
    # loop traces its tail instead of growing without bound (dropped
    # count recorded in the file's metadata). Floor 1024.
    tpu_trace_buffer: int = 65536
    # live metrics export (obs/export.py): base path for periodic
    # registry snapshots — "<base>.prom" (Prometheus text exposition,
    # atomically replaced) and "<base>.jsonl" (append-only time
    # series) are written every tpu_metrics_interval_s DURING the run,
    # so a live loop can be watched without waiting for the run
    # report. A .prom/.jsonl suffix on the value is stripped.
    # Empty = off (unless tpu_metrics_port opens the HTTP endpoint).
    tpu_metrics_export: str = ""
    # seconds between exporter snapshots (obs/export.py); also the
    # flush cadence of the JSONL time series. Non-positive values fall
    # back to the 5.0 default; the exporter floors tiny values at 0.01.
    tpu_metrics_interval_s: float = 5.0
    # serve live metrics over HTTP (obs/export.py): a stdlib
    # http.server on 127.0.0.1:<port> answering GET /metrics
    # (Prometheus text), /metrics.json (raw snapshot), /healthz
    # (liveness + last-snapshot age + SLO budget state, JSON) and /slo
    # (the full error-budget report) while the process runs — point a
    # scraper or a fleet health check at a live training/serving loop.
    # 0 = off.
    tpu_metrics_port: int = 0
    # request-scoped wide-event log (obs/reqlog.py): JSONL path for
    # one structured record per serving request batch and per lrb
    # window — request id, latency, rows, serve bucket, the serving
    # model's window, degraded/staleness state. The in-memory ring
    # feeding the flight recorder is always on; this knob adds the
    # on-disk file. Empty = no file.
    tpu_reqlog: str = ""
    # fraction of request records written to the tpu_reqlog file,
    # decided DETERMINISTICALLY per request id (lowbias32 hash — the
    # same ids are sampled at the same rate on every run). Window and
    # degraded-window records are never sampled out. Clamped to [0, 1].
    tpu_reqlog_sample: float = 1.0
    # SLO specs (obs/slo.py), ";"-separated, evaluated every exporter
    # interval: e.g. "predict_p99_ms<50;staleness_windows<=2;
    # degraded_window_rate<0.05" (also hist:/gauge:/ratio: forms).
    # Compliance, remaining error budget and burn rate become slo/*
    # gauges, the /healthz + /slo endpoint bodies, and — on budget
    # exhaustion — a flight-recorder trigger. Empty = no SLOs.
    tpu_slo: str = ""
    # fleet scoring daemon (serve/daemon.py): TCP port for the
    # multi-tenant HTTP scoring endpoint on 127.0.0.1. 0 = ephemeral
    # (the OS picks; ScoringDaemon.http_port reports the bound port —
    # the tests' and lrb --serve-daemon's mode). The daemon only
    # starts when explicitly constructed (bench --fleet, lrb
    # --serve-daemon, or embedding code); this knob never opens a
    # socket by itself.
    tpu_fleet_port: int = 0
    # cross-request coalescer max wait in MICROSECONDS
    # (serve/coalescer.py): after the first request of a tick arrives,
    # the dispatcher lingers up to this long for more requests to
    # merge into the same pow2-bucketed device batch. Higher = bigger
    # batches (throughput), at up to this much added p50 latency.
    # Clamped to [0, 1e6]; 0 = dispatch immediately (coalescing only
    # what is already queued).
    tpu_fleet_coalesce_us: int = 2000
    # max coalesced rows dispatched per tenant per tick
    # (serve/coalescer.py). Requests beyond the cap stay queued for
    # the next tick, bounding both device-batch width and the
    # head-of-line latency a huge batch inflicts on neighbors.
    # Floor 1.
    tpu_fleet_max_batch: int = 4096
    # bounded coalescer admission queue, in REQUESTS
    # (serve/coalescer.py): submissions beyond this depth are refused
    # (HTTP 503 + Retry-After) instead of growing an unbounded buffer
    # — backpressure reaches the client as a retryable signal.
    # Floor 1.
    tpu_fleet_queue: int = 1024
    # per-tenant serving SLO: p99 latency target in MILLISECONDS for
    # the admission controller (serve/daemon.py). For every registered
    # tenant the daemon arms a "hist:fleet/tenant_latency_s/<t>:p99 <
    # target" spec on an obs/slo.py engine and sheds that tenant's
    # load when its error budget burns low (see
    # tpu_fleet_shed_budget). 0 = no admission SLO (never shed).
    # Negative values clamp to 0.
    tpu_fleet_slo_p99_ms: float = 0.0
    # admission-control shed threshold (serve/daemon.py): when a
    # tenant's remaining p99 error budget (obs/slo.py
    # budget_remaining, 1.0 = untouched, <= 0 = breached) falls to or
    # below this fraction, the daemon 429s that tenant's requests
    # BEFORE the budget exhausts, while other tenants keep serving.
    # Clamped to [0, 1]; 0 = shed only at breach.
    tpu_fleet_shed_budget: float = 0.25
    # flight recorder ring capacity (obs/flight.py): recent spans, log
    # lines and reqlog records kept in memory and dumped as ONE
    # postmortem JSON bundle when the watchdog fires, a fault
    # injects, an lrb window degrades, an SLO budget exhausts, or the
    # process gets SIGTERM/an uncaught exception. Bundles land next to
    # the run's artifacts (run report/reqlog/export/trace path), else
    # the system temp dir; run reports cross-link them as
    # meta.flight_dumps. 0 disables the black box.
    tpu_flight_buffer: int = 256
    # explicit flight-dump directory (obs/flight.py), overriding the
    # artifact-path default. Multi-process drivers
    # (parallel/elastic.py) point EVERY rank at one shared directory
    # so the incident sweep (obs/incident.py) can gather all ranks'
    # postmortem bundles into a single incident document. Empty = the
    # first configured artifact path's directory, else the temp dir.
    tpu_flight_dir: str = ""
    # cluster-scope metrics rollups (obs/clusterobs.py): each rank
    # publishes a compact metrics digest into the coordination-service
    # KV alongside its heartbeat, and rank 0's exporter merges them
    # into first-class cluster/* instruments (summed counters, true
    # cluster histogram quantiles, per-rank straggler gauges)
    # published through the usual Prometheus/JSONL//metrics surfaces.
    # -1 = auto (on whenever the run is multi-process AND a metrics
    # exporter is configured); 0 = off; 1 = force on.
    tpu_cluster_obs: int = -1
    # resumable checkpoints (utils/checkpoint.py): directory for
    # versioned JSON checkpoint bundles — the model text PLUS the
    # training state the model text lacks (iteration, bagging/feature/
    # GOSS/DART RNG streams, current bagging mask, early-stopping
    # bookkeeping, eval history, config fingerprint) — written
    # atomically every tpu_checkpoint_freq iterations by the gbdt.train
    # snapshot loop and engine.train, pruned to tpu_snapshot_keep. A
    # run resumed from a bundle continues BIT-IDENTICALLY to the
    # uninterrupted run (tests/test_faults.py kill-and-resume drill).
    # Empty = no checkpoints.
    tpu_checkpoint_dir: str = ""
    # iterations between checkpoint writes (0 = off). A failed write
    # warns and training continues — the previous complete checkpoint
    # is never corrupted (atomic replace).
    tpu_checkpoint_freq: int = 0
    # resume training from this checkpoint bundle (or directory — the
    # newest valid bundle wins; corrupt ones are skipped with a
    # warning). Refused with an actionable message when the training
    # config fingerprint differs. CLI analog of
    # GBDT.train(resume_from=...).
    tpu_resume_from: str = ""
    # model snapshots (save_period) AND checkpoint bundles retained;
    # older ones are pruned after each successful write (floor 1).
    tpu_snapshot_keep: int = 3
    # deterministic fault injection (utils/faults.py) for recovery
    # drills: "point@N[:action][;...]" — e.g.
    # "lrb.window_train@2:transient;train.iter@17:kill". Tests and
    # game-day drills only; empty = disarmed.
    tpu_faults: str = ""
    # seed for probability-based fault rules (point@p0.25) so drills
    # reproduce exactly
    tpu_fault_seed: int = 0
    # total attempts for transient-failure retries (utils/retry.py
    # bounded exponential backoff + jitter) on the ingest/transfer
    # seams and the lrb window-train path
    tpu_retry_attempts: int = 4
    # pipelined retrain-while-serve for the windowed LRB loop (lrb.py):
    # window K's training runs on a background trainer thread while the
    # main thread keeps ingesting window K+1's requests and deriving
    # its features; the finished model is published with an atomic
    # swap (a failed/degraded window publishes nothing — serving
    # continues on the previous model). Per-window results are
    # field-for-field identical to the sequential loop (model swaps
    # take effect at window boundaries either way). -1 = auto (on);
    # 0 = off (the strictly sequential derive->train->evaluate loop);
    # 1 = force on.
    tpu_lrb_pipeline: int = -1
    # device-resident ingest chunk ring (io/ingest.py ChunkRing) for
    # the per-window training matrix: each chunk slot's device buffers
    # stay resident across windows and only the bucketed live-row
    # region is re-uploaded (the chunk's pad tail — most of a
    # sample-sized window's padded chunk — never crosses the wire
    # again). Bit-identical bins; engages only when the streamed
    # device ingest path is active. -1 = auto (on); 0 = off (full
    # padded-chunk re-ingest every window); 1 = force on.
    tpu_lrb_ring: int = -1
    # sparse histogram kernel tier (ops/hist_wave.py
    # wave_histogram_sparse): wave histograms accumulate by
    # scatter/segment-sum over the nnz explicit entries (plus a
    # default-bin completion from per-leaf totals) instead of the
    # dense one-hot pass — O(nnz) histogram work for CSR-native
    # datasets (io/sparse.py). -1 = auto: engages when the dataset
    # carries sparse coordinates, density clears the autotune rule
    # (ops/autotune.py tune_hist_tier) AND tpu_quantized_hist is on —
    # integer accumulation is order-free, so the tier is BIT-equal to
    # the dense tier; 0 = off (dense tier even for CSR input);
    # 1 = force wherever structurally possible (serial learner, no EFB
    # bundles) — with f32 accumulation the default-bin completion
    # reassociates sums, so final-ulp histogram drift vs the dense
    # tier is possible (documented in docs/Design.md §5f).
    tpu_sparse: int = -1
    # multi-host cluster bootstrap (parallel/cluster.py): number of
    # JAX PROCESSES forming the training cluster. The reference's
    # num_machines counts socket peers on its TCP linkers; this counts
    # jax.distributed processes whose devices form ONE global mesh.
    # 0/1 = single-process (the virtual mesh path). Env twin
    # LGBM_TPU_NUM_MACHINES (launchers) outranks the knob.
    tpu_num_machines: int = 0
    # this process's rank in [0, tpu_num_machines); -1 = take it from
    # the LGBM_TPU_MACHINE_RANK env (how the drill launcher tells N
    # otherwise-identical workers apart)
    tpu_machine_rank: int = -1
    # coordinator address host:port (rank 0's reachable address — the
    # analog of the reference's machine_list first entry). Env twin
    # LGBM_TPU_COORDINATOR. Required when tpu_num_machines > 1.
    tpu_coordinator: str = ""
    # bounded deadline for cross-process sync points (cluster barriers,
    # the training-loop stall watchdog): a dead peer produces a
    # one-line error naming the rank within this budget, never an
    # indefinite hang. The spiritual successor of the reference's
    # ``time_out`` socket knob (minutes there, seconds here).
    tpu_collective_timeout_s: float = 60.0

    def __post_init__(self):
        self._raw_params: Dict[str, str] = {}

    # -- parsing ------------------------------------------------------------

    @staticmethod
    def key_alias_transform(key: str) -> str:
        """ParameterAlias::KeyAliasTransform (config_auto.cpp:4)."""
        k = key.strip().lower().replace("-", "_")
        return ALIAS_TABLE.get(k, k)

    @classmethod
    def str2map(cls, params: str) -> Dict[str, str]:
        """KV2Map over 'k1=v1 k2=v2' strings (src/io/config.cpp:9-36)."""
        out: Dict[str, str] = {}
        for token in params.replace("\n", " ").split():
            if "=" in token:
                k, v = token.split("=", 1)
                out[k] = v
            elif token:
                log.warning("Unknown parameter %s", token)
        return out

    def set(self, params: Dict[str, Any]) -> "Config":
        """Config::Set (src/io/config.cpp:153): alias-resolve, parse, check."""
        resolved: Dict[str, Any] = {}
        for k, v in params.items():
            nk = k.strip().lower().replace("-", "_")
            if nk in DEPRECATED_ALIASES:
                ck, remap = DEPRECATED_ALIASES[nk]
                nv = remap(v)
                log.warning("%s is deprecated; use %s (mapped %s=%s to "
                            "%s=%s)", nk, ck, nk, v, ck, nv)
                v = nv
            else:
                ck = self.key_alias_transform(k)
            if ck in resolved and str(resolved[ck]) != str(v):
                log.warning(
                    "%s is set with %s=%s, will be overridden by %s=%s",
                    ck, k, resolved[ck], k, v)
            resolved[ck] = v
        for k, v in resolved.items():
            self._set_one(k, v)
        self._raw_params.update({k: str(v) for k, v in resolved.items()})
        self.check_param_conflict()
        return self

    def _set_one(self, key: str, value: Any) -> None:
        if not hasattr(self, key):
            # Unknown keys warn (objective-specific passthrough keys allowed)
            log.warning("Unknown parameter: %s", key)
            return
        cur = getattr(self, key)
        try:
            if isinstance(cur, bool):
                setattr(self, key, _parse_bool(value))
            elif isinstance(cur, int):
                setattr(self, key, int(float(value)))
            elif isinstance(cur, float):
                setattr(self, key, float(value))
            elif isinstance(cur, list):
                setattr(self, key, _parse_list(key, value))
            else:
                setattr(self, key, str(value).strip())
        except (TypeError, ValueError) as e:
            log.fatal(f"Bad value for parameter {key}: {value!r} ({e})")

    # -- semantics ----------------------------------------------------------

    # accepted for reference compatibility but not implemented: warn
    # when set to a non-default value instead of silently ignoring
    _UNIMPLEMENTED = {
        "convert_model_language": "",
    }
    # subsumed by the TPU design (documented substitutions, not gaps)
    _SUBSUMED = {
        "num_threads": "XLA owns intra-op parallelism",
        "histogram_pool_size": "histogram pool lives in HBM "
                               "(preallocated, no LRU needed)",
        "gpu_platform_id": "device selection is jax's",
        "gpu_device_id": "device selection is jax's",
        "gpu_use_dp": "see tpu_use_dp",
        "local_listen_port": "collectives ride ICI/DCN via XLA",
        "time_out": "collectives ride ICI/DCN via XLA",
        "machine_list_filename": "host topology comes from the JAX "
                                 "runtime (jax.distributed), not a "
                                 "socket machine list",
        "machines": "host topology comes from the JAX runtime",
    }

    def check_param_conflict(self) -> None:
        """Config::CheckParamConflict (src/io/config.cpp:202)."""
        for key, default in self._UNIMPLEMENTED.items():
            if key in self._raw_params and getattr(self, key) != default:
                log.warning("Parameter %s is accepted for compatibility "
                            "but not implemented yet; it has no effect",
                            key)
        for key, why in self._SUBSUMED.items():
            if key in self._raw_params:
                log.debug("Parameter %s is subsumed by the TPU design: "
                          "%s", key, why)
        if "device_type" in self._raw_params:
            # explicit device routing (the reference's CPU/GPU switch,
            # .ci/test.sh GPU CI pattern): cpu routes the framework's
            # device selection to the CPU backend; gpu/tpu/cuda run on
            # the accelerator. The routing lives in module state
            # (utils/device.py) — an operator's LGBM_TPU_PLATFORM env
            # pin always outranks it and is never modified.
            import os as _os
            from .utils.device import set_config_platform
            dt = self.device_type.lower()
            if dt == "cpu":
                set_config_platform("cpu")
            elif dt in ("gpu", "cuda", "tpu"):
                set_config_platform(None)
                if dt != "tpu":
                    log.info("device_type=%s maps to the accelerator "
                             "backend (TPU)", dt)
            else:
                log.fatal(f"Unknown device type {self.device_type!r}")
            pin = _os.environ.get("LGBM_TPU_PLATFORM")
            if pin and pin != dt and dt != "cpu":
                log.warning("device_type=%s requested but "
                            "LGBM_TPU_PLATFORM=%s pins the backend",
                            dt, pin)
        # reference value aliases first (GetTreeLearnerType,
        # src/io/config.cpp:57-74), THEN the whitelist — a ported
        # "data_parallel" config must select the data learner, not
        # fall through to serial
        tl = self.tree_learner.lower()
        self.tree_learner = {"serial": "serial",
                             "feature": "feature",
                             "feature_parallel": "feature",
                             "data": "data", "data_parallel": "data",
                             "voting": "voting",
                             "voting_parallel": "voting"}.get(tl, tl)
        if self.tree_learner not in ("serial", "feature", "data",
                                     "voting"):
            # warn here, not later in learner selection: the grower
            # factory (parallel/learners.py make_grower_for_mode) only
            # sees the mode after dataset construction, long after the
            # operator could still fix the config (the tpu_ingest
            # pattern below)
            log.warning("Unknown tree_learner %r (want one of "
                        "serial/feature/data/voting); using 'serial'",
                        self.tree_learner)
            self.tree_learner = "serial"
        if self.tpu_count_proxy not in (-1, 0, 1):
            log.warning("tpu_count_proxy=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_count_proxy)
            self.tpu_count_proxy = -1
        if self.tpu_packed_bins not in (-1, 0, 1):
            log.warning("tpu_packed_bins=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_packed_bins)
            self.tpu_packed_bins = -1
        # unsupported tier combinations fail HERE, at param-check time
        # with the knob names — not as a bare NotImplementedError from
        # the kernel dispatch mid-training (ops/hist_wave.py keeps the
        # raises as a backstop for direct kernel callers)
        if self.tpu_count_proxy == 1 and not self.tpu_quantized_hist:
            log.fatal("tpu_count_proxy=1 requires tpu_quantized_hist="
                      "true (the count-proxy tier rides the int8 "
                      "quantized histogram kernels); set "
                      "tpu_quantized_hist=true or drop tpu_count_proxy")
        if self.tpu_packed_bins == 1:
            if self.tpu_quantized_hist and self.tpu_count_proxy == 0:
                log.fatal("tpu_packed_bins=1 with tpu_quantized_hist "
                          "needs the count-proxy tier: leave "
                          "tpu_count_proxy enabled (-1/1) or drop "
                          "tpu_packed_bins")
            if not self.tpu_quantized_hist and not self.tpu_use_dp:
                log.fatal("tpu_packed_bins=1 needs the count-proxy "
                          "int8 tier (tpu_quantized_hist=true) or the "
                          "hi/lo exact tier (tpu_use_dp=true); "
                          "single-bf16 (tpu_use_dp=false) packed bins "
                          "are not implemented")
            if self.max_bin > 16:
                log.fatal(f"tpu_packed_bins=1 needs max_bin <= 16 "
                          f"(two 4-bit bins per byte); max_bin="
                          f"{self.max_bin}")
        if self.tpu_exact_tier not in ("", "hilo5", "hilo4", "hilo3"):
            log.warning("tpu_exact_tier=%r is not one of ''/hilo5/"
                        "hilo4/hilo3; using '' (auto)",
                        self.tpu_exact_tier)
            self.tpu_exact_tier = ""
        if self.tpu_hist_chunk < 0:
            log.warning("tpu_hist_chunk=%d is negative; using 0 "
                        "(auto)", self.tpu_hist_chunk)
            self.tpu_hist_chunk = 0
        if self.tpu_wave_size < 0:
            # the grower clamps the UPPER side against the active lane
            # cap (models/gbdt.py); a negative would flow through
            # ``tpu_wave_size or w_cap`` as a bogus wave width
            log.warning("tpu_wave_size=%d is negative; using 0 "
                        "(auto)", self.tpu_wave_size)
            self.tpu_wave_size = 0
        if self.tpu_stop_check_interval < 1:
            log.warning("tpu_stop_check_interval=%d is below the "
                        "floor; using 1 (check every iteration)",
                        self.tpu_stop_check_interval)
            self.tpu_stop_check_interval = 1
        if self.tpu_dispatch_sync_interval < 0:
            log.warning("tpu_dispatch_sync_interval=%d is negative; "
                        "using 0 (unbounded dispatch queue)",
                        self.tpu_dispatch_sync_interval)
            self.tpu_dispatch_sync_interval = 0
        if self.tpu_ingest_chunk_rows < 0:
            log.warning("tpu_ingest_chunk_rows=%d is negative; using "
                        "0 (auto-sized chunks)",
                        self.tpu_ingest_chunk_rows)
            self.tpu_ingest_chunk_rows = 0
        if self.tpu_quantized_psum not in (-1, 0, 1):
            log.warning("tpu_quantized_psum=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_quantized_psum)
            self.tpu_quantized_psum = -1
        if self.tpu_psum_wire not in (-1, 0, 1):
            log.warning("tpu_psum_wire=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_psum_wire)
            self.tpu_psum_wire = -1
        if self.tpu_async_psum not in (-1, 0, 1):
            log.warning("tpu_async_psum=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_async_psum)
            self.tpu_async_psum = -1
        if self.tpu_ckpt_async not in (-1, 0, 1):
            log.warning("tpu_ckpt_async=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_ckpt_async)
            self.tpu_ckpt_async = -1
        if self.tpu_ingest not in (-1, 0, 1):
            log.warning("tpu_ingest=%d is not one of -1/0/1; using -1 "
                        "(auto)", self.tpu_ingest)
            self.tpu_ingest = -1
        if self.tpu_out_of_core not in (-1, 0, 1):
            log.warning("tpu_out_of_core=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_out_of_core)
            self.tpu_out_of_core = -1
        if self.tpu_ooc_block_rows < 0:
            log.warning("tpu_ooc_block_rows=%d is negative; using 0 "
                        "(auto block size)", self.tpu_ooc_block_rows)
            self.tpu_ooc_block_rows = 0
        if self.tpu_goss_hash not in (-1, 0, 1):
            log.warning("tpu_goss_hash=%d is not one of -1/0/1; "
                        "using -1 (auto: hashed)", self.tpu_goss_hash)
            self.tpu_goss_hash = -1
        if self.tpu_watchdog_factor < 0:
            log.warning("tpu_watchdog_factor=%g is negative; disabling "
                        "the watchdog (0)", self.tpu_watchdog_factor)
            self.tpu_watchdog_factor = 0.0
        if self.tpu_profile_iters < 0:
            log.warning("tpu_profile_iters=%d is negative; tracing the "
                        "whole loop (0)", self.tpu_profile_iters)
            self.tpu_profile_iters = 0
        if self.tpu_autotune not in ("on", "off", "exhaustive"):
            log.warning("tpu_autotune=%r is not one of on/off/exhaustive;"
                        " using 'on'", self.tpu_autotune)
            self.tpu_autotune = "on"
        if self.tpu_step_cache not in (-1, 0, 1):
            log.warning("tpu_step_cache=%d is not one of -1/0/1; using "
                        "-1 (auto)", self.tpu_step_cache)
            self.tpu_step_cache = -1
        if self.tpu_row_bucket < -1:
            log.warning("tpu_row_bucket=%d is negative; using -1 "
                        "(power-of-two buckets)", self.tpu_row_bucket)
            self.tpu_row_bucket = -1
        if self.tpu_predict_cache not in (-1, 0, 1):
            log.warning("tpu_predict_cache=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_predict_cache)
            self.tpu_predict_cache = -1
        if self.tpu_serve_bucket < -1:
            log.warning("tpu_serve_bucket=%d is negative; using -1 "
                        "(power-of-two serve buckets)",
                        self.tpu_serve_bucket)
            self.tpu_serve_bucket = -1
        if self.tpu_compile_cache not in (-1, 0, 1):
            log.warning("tpu_compile_cache=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_compile_cache)
            self.tpu_compile_cache = -1
        if self.tpu_trace_buffer < 1024:
            log.warning("tpu_trace_buffer=%d is below the floor; "
                        "using 1024", self.tpu_trace_buffer)
            self.tpu_trace_buffer = 1024
        if self.tpu_checkpoint_freq < 0:
            log.warning("tpu_checkpoint_freq=%d is negative; disabling "
                        "checkpoints (0)", self.tpu_checkpoint_freq)
            self.tpu_checkpoint_freq = 0
        if self.tpu_checkpoint_freq > 0 and not self.tpu_checkpoint_dir:
            log.warning("tpu_checkpoint_freq=%d but tpu_checkpoint_dir "
                        "is empty; no checkpoints will be written",
                        self.tpu_checkpoint_freq)
        if self.tpu_snapshot_keep < 1:
            log.warning("tpu_snapshot_keep=%d is below the floor; "
                        "using 1", self.tpu_snapshot_keep)
            self.tpu_snapshot_keep = 1
        if self.tpu_retry_attempts < 1:
            log.warning("tpu_retry_attempts=%d is below the floor; "
                        "using 1 (no retries)", self.tpu_retry_attempts)
            self.tpu_retry_attempts = 1
        if self.tpu_lrb_pipeline not in (-1, 0, 1):
            log.warning("tpu_lrb_pipeline=%d is not one of -1/0/1; "
                        "using -1 (auto)", self.tpu_lrb_pipeline)
            self.tpu_lrb_pipeline = -1
        if self.tpu_lrb_ring not in (-1, 0, 1):
            log.warning("tpu_lrb_ring=%d is not one of -1/0/1; using "
                        "-1 (auto)", self.tpu_lrb_ring)
            self.tpu_lrb_ring = -1
        if self.tpu_sparse not in (-1, 0, 1):
            log.warning("tpu_sparse=%d is not one of -1/0/1; using -1 "
                        "(auto)", self.tpu_sparse)
            self.tpu_sparse = -1
        if self.tpu_num_machines < 0:
            log.warning("tpu_num_machines=%d is negative; using 0 "
                        "(single process)", self.tpu_num_machines)
            self.tpu_num_machines = 0
        if self.tpu_machine_rank < -1:
            log.warning("tpu_machine_rank=%d is below -1; using -1 "
                        "(take the rank from LGBM_TPU_MACHINE_RANK)",
                        self.tpu_machine_rank)
            self.tpu_machine_rank = -1
        if (self.tpu_num_machines > 1
                and self.tpu_machine_rank >= self.tpu_num_machines):
            log.fatal(f"tpu_machine_rank={self.tpu_machine_rank} is "
                      f"outside [0, tpu_num_machines="
                      f"{self.tpu_num_machines}) — every process needs "
                      f"a distinct rank below the world size")
        if self.tpu_collective_timeout_s <= 0:
            log.warning("tpu_collective_timeout_s=%g is not positive; "
                        "using 60.0", self.tpu_collective_timeout_s)
            self.tpu_collective_timeout_s = 60.0
        if not 0.0 < self.sparse_threshold <= 1.0:
            # the CSR route gate (io/sparse.py route_sparse): the
            # implicit fraction must reach this threshold
            log.warning("sparse_threshold=%g is outside (0, 1]; using "
                        "0.8", self.sparse_threshold)
            self.sparse_threshold = 0.8
        if self.tpu_metrics_interval_s <= 0:
            log.warning("tpu_metrics_interval_s=%g is not positive; "
                        "using 5.0", self.tpu_metrics_interval_s)
            self.tpu_metrics_interval_s = 5.0
        if not 0 <= self.tpu_metrics_port <= 65535:
            log.warning("tpu_metrics_port=%d is not a port; disabling "
                        "the metrics endpoint (0)", self.tpu_metrics_port)
            self.tpu_metrics_port = 0
        if not 0.0 <= self.tpu_reqlog_sample <= 1.0:
            log.warning("tpu_reqlog_sample=%g is outside [0, 1]; "
                        "clamping", self.tpu_reqlog_sample)
            self.tpu_reqlog_sample = min(
                max(self.tpu_reqlog_sample, 0.0), 1.0)
        if not 0 <= self.tpu_fleet_port <= 65535:
            log.warning("tpu_fleet_port=%d is not a port; using an "
                        "ephemeral port (0)", self.tpu_fleet_port)
            self.tpu_fleet_port = 0
        if not 0 <= self.tpu_fleet_coalesce_us <= 1_000_000:
            log.warning("tpu_fleet_coalesce_us=%d is outside [0, 1e6]; "
                        "clamping", self.tpu_fleet_coalesce_us)
            self.tpu_fleet_coalesce_us = min(
                max(self.tpu_fleet_coalesce_us, 0), 1_000_000)
        if self.tpu_fleet_max_batch < 1:
            log.warning("tpu_fleet_max_batch=%d is below the floor; "
                        "using 1", self.tpu_fleet_max_batch)
            self.tpu_fleet_max_batch = 1
        if self.tpu_fleet_queue < 1:
            log.warning("tpu_fleet_queue=%d is below the floor; "
                        "using 1", self.tpu_fleet_queue)
            self.tpu_fleet_queue = 1
        if self.tpu_fleet_slo_p99_ms < 0:
            log.warning("tpu_fleet_slo_p99_ms=%g is negative; disabling "
                        "the admission SLO (0)", self.tpu_fleet_slo_p99_ms)
            self.tpu_fleet_slo_p99_ms = 0.0
        if not 0.0 <= self.tpu_fleet_shed_budget <= 1.0:
            log.warning("tpu_fleet_shed_budget=%g is outside [0, 1]; "
                        "clamping", self.tpu_fleet_shed_budget)
            self.tpu_fleet_shed_budget = min(
                max(self.tpu_fleet_shed_budget, 0.0), 1.0)
        if self.tpu_flight_buffer < 0:
            log.warning("tpu_flight_buffer=%d is negative; disabling "
                        "the flight recorder (0)", self.tpu_flight_buffer)
            self.tpu_flight_buffer = 0
        if self.tpu_cluster_obs not in (-1, 0, 1):
            log.warning("tpu_cluster_obs=%d is not -1/0/1; using auto "
                        "(-1)", self.tpu_cluster_obs)
            self.tpu_cluster_obs = -1
        if self.tpu_slo:
            # refuse a malformed spec at config time, not in the
            # exporter thread mid-run (the parse error names the
            # offending fragment)
            from .obs.slo import parse_specs
            try:
                parse_specs(self.tpu_slo)
            except ValueError as e:
                log.warning("tpu_slo disabled: %s", e)
                self.tpu_slo = ""
        if self.is_provide_training_metric or self.valid:
            if not self.metric:
                # force defaults from objective later; handled by metric factory
                pass
        if self.num_machines > 1:
            if self.tree_learner == "serial":
                log.warning(
                    "num_machines>1 with serial tree learner; only one machine "
                    "will train")
        if self.tree_learner in ("data", "voting") and self.histogram_pool_size >= 0:
            log.warning(
                "Histogram LRU queue was enabled (histogram_pool_size=%g); "
                "will disable this for distributed learning",
                self.histogram_pool_size)
            self.histogram_pool_size = -1.0
        if self.boosting == "rf":
            if not (self.bagging_freq > 0 and 0.0 < self.bagging_fraction < 1.0):
                log.fatal("Random forest needs bagging_freq > 0 and "
                          "bagging_fraction in (0, 1)")
            if self.feature_fraction >= 1.0:
                # upstream requires feature_fraction < 1 OR bagging; bagging
                # is already enforced above so just warn
                pass
        if self.objective in ("lambdarank", "rank_xendcg") and self.num_class != 1:
            log.fatal("Ranking objectives don't support multiclass")
        if self.max_depth > 0 and self.num_leaves == 31:
            # reference caps leaves by depth implicitly during growth
            pass

    @property
    def device(self) -> str:
        return self.device_type

    def boosting_type(self) -> str:
        """GetBoostingType normalization (src/io/config.cpp:45)."""
        b = self.boosting
        if b in ("gbdt", "gbrt"):
            return "gbdt"
        if b in ("dart",):
            return "dart"
        if b in ("goss",):
            return "goss"
        if b in ("rf", "random_forest"):
            return "rf"
        log.fatal(f"Unknown boosting type {b}")

    def to_string(self) -> str:
        """Config::ToString — saved into the model file `parameters:` block."""
        lines = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, list):
                v = ",".join(str(x) for x in v)
            elif isinstance(v, bool):
                v = "1" if v else "0"
            lines.append(f"[{f.name}: {v}]")
        return "\n".join(lines)

    def copy(self) -> "Config":
        new = Config()
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            setattr(new, f.name, list(v) if isinstance(v, list) else v)
        new._raw_params = dict(self._raw_params)
        return new


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "+", "yes", "on"):
        return True
    if s in ("false", "0", "-", "no", "off"):
        return False
    raise ValueError(f"not a bool: {v!r}")


_INT_LIST_KEYS = {"monotone_constraints", "eval_at"}
_STR_LIST_KEYS = {"valid", "metric", "valid_data_initscores"}


def _parse_list(key: str, v: Any) -> list:
    if isinstance(v, (list, tuple)):
        items = list(v)
    else:
        items = [x for x in str(v).replace(";", ",").split(",") if x != ""]
    if key in _INT_LIST_KEYS:
        return [int(float(x)) for x in items]
    if key in _STR_LIST_KEYS:
        return [str(x).strip() for x in items]
    return [float(x) for x in items]


def param_dict_to_str(params: Optional[Dict[str, Any]]) -> str:
    """Python-side param dict → 'k=v' string (basic.py:123 semantics)."""
    if not params:
        return ""
    pairs = []
    for k, v in params.items():
        if isinstance(v, (list, tuple)):
            pairs.append(f"{k}={','.join(map(str, v))}")
        elif isinstance(v, bool):
            pairs.append(f"{k}={'true' if v else 'false'}")
        elif v is None:
            continue
        else:
            pairs.append(f"{k}={v}")
    return " ".join(pairs)
