"""Embedded-interpreter glue behind the linkable C ABI.

``native/c_api_embed.cpp`` hosts a CPython interpreter and forwards each
``LGBM_*`` export (reference: src/c_api.cpp:47-1568,
include/LightGBM/c_api.h) to a function here. The C side passes raw
buffer addresses as integers; this module wraps them zero-copy with
numpy/ctypes, calls the Python C-API shim (capi.py — the same engine
the Python package uses), and writes results straight back into the
caller's preallocated buffers.

Handles are small integers into a registry (not PyObject pointers), so
the C side never touches refcounts.
"""
from __future__ import annotations

import ctypes
import itertools
from typing import Dict

import numpy as np

from . import capi

_CT = {0: ctypes.c_float, 1: ctypes.c_double,
       2: ctypes.c_int32, 3: ctypes.c_int64}

_registry: Dict[int, object] = {}
# itertools.count is atomic under the GIL: concurrent C-side callers
# (the .so drops the GIL between calls) never share a handle id
_next_id = itertools.count(1)


def _put(obj) -> int:
    h = next(_next_id)
    _registry[h] = obj
    return h


def _get(h: int):
    return _registry[int(h)]


def free_handle(h: int) -> None:
    _registry.pop(int(h), None)


def _arr(ptr: int, n: int, dtype: int) -> np.ndarray:
    """Zero-copy numpy view of a C buffer."""
    if n == 0:
        return np.zeros(0, np.ctypeslib.as_ctypes_type(_CT[dtype]))
    p = ctypes.cast(int(ptr), ctypes.POINTER(_CT[dtype]))
    return np.ctypeslib.as_array(p, (int(n),))


# --- Dataset ---------------------------------------------------------------

def dataset_from_csr(indptr, indptr_type, indices, data, data_type,
                     nindptr, nelem, ncol, params, ref) -> int:
    ip = _arr(indptr, nindptr, indptr_type)
    ix = _arr(indices, nelem, 2)
    dv = _arr(data, nelem, data_type)
    ds = capi.LGBM_DatasetCreateFromCSR(
        ip, int(indptr_type), ix, dv, int(data_type), int(nindptr),
        int(nelem), int(ncol), parameters=params,
        reference=_get(ref) if ref else None)
    return _put(ds)


def dataset_from_mat(data, data_type, nrow, ncol, is_row_major,
                     params, ref) -> int:
    flat = _arr(data, int(nrow) * int(ncol), data_type)
    m = (flat.reshape(nrow, ncol) if is_row_major
         else flat.reshape(ncol, nrow).T)
    ds = capi.LGBM_DatasetCreateFromMat(
        np.ascontiguousarray(m, np.float64), parameters=params,
        reference=_get(ref) if ref else None)
    return _put(ds)


def dataset_from_file(filename, params, ref) -> int:
    ds = capi.LGBM_DatasetCreateFromFile(
        filename, parameters=params,
        reference=_get(ref) if ref else None)
    return _put(ds)


def dataset_set_field(h, name, data, n, dtype) -> None:
    capi.LGBM_DatasetSetField(_get(h), name, _arr(data, n, dtype).copy())


def dataset_num_data(h) -> int:
    return int(capi.LGBM_DatasetGetNumData(_get(h)))


def dataset_num_feature(h) -> int:
    return int(capi.LGBM_DatasetGetNumFeature(_get(h)))


# --- Booster ---------------------------------------------------------------

def booster_create(train, params) -> int:
    return _put(capi.LGBM_BoosterCreate(_get(train), params))


def booster_from_modelfile(filename, out_iters_ptr) -> int:
    bst = capi.LGBM_BoosterCreateFromModelfile(filename)
    n = capi.LGBM_BoosterGetCurrentIteration(bst)
    _arr(out_iters_ptr, 1, 2)[0] = int(n)
    return _put(bst)


def booster_merge(h, other) -> None:
    capi.LGBM_BoosterMerge(_get(h), _get(other))


def booster_add_valid(h, valid) -> None:
    capi.LGBM_BoosterAddValidData(_get(h), _get(valid))


def booster_update(h, out_ptr) -> None:
    fin = capi.LGBM_BoosterUpdateOneIter(_get(h))
    _arr(out_ptr, 1, 2)[0] = int(bool(fin))


def booster_refit(h, leaf_preds, nrow, ncol) -> None:
    lp = _arr(leaf_preds, int(nrow) * int(ncol), 2).reshape(nrow, ncol)
    capi.LGBM_BoosterRefit(_get(h), lp)


def booster_calc_num_predict(h, num_row, predict_type,
                             num_iteration) -> int:
    return int(capi.LGBM_BoosterCalcNumPredict(
        _get(h), int(num_row), int(predict_type), int(num_iteration)))


def booster_predict_csr(h, indptr, indptr_type, indices, data,
                        data_type, nindptr, nelem, ncol, predict_type,
                        num_iteration, params, out_result) -> int:
    ip = _arr(indptr, nindptr, indptr_type)
    ix = _arr(indices, nelem, 2)
    dv = _arr(data, nelem, data_type)
    res = capi.LGBM_BoosterPredictForCSR(
        _get(h), ip, int(indptr_type), ix, dv, int(data_type),
        int(nindptr), int(nelem), int(ncol),
        predict_type=int(predict_type),
        num_iteration=int(num_iteration), parameter=params)
    flat = np.asarray(res, np.float64).reshape(-1)
    _arr(out_result, flat.size, 1)[:] = flat
    return int(flat.size)


def booster_predict_mat(h, data, data_type, nrow, ncol, is_row_major,
                        predict_type, num_iteration, params,
                        out_result) -> int:
    flat = _arr(data, int(nrow) * int(ncol), data_type)
    m = (flat.reshape(nrow, ncol) if is_row_major
         else flat.reshape(ncol, nrow).T)
    res = capi.LGBM_BoosterPredictForMat(
        _get(h), np.ascontiguousarray(m, np.float64),
        predict_type=int(predict_type),
        num_iteration=int(num_iteration), parameter=params)
    out = np.asarray(res, np.float64).reshape(-1)
    _arr(out_result, out.size, 1)[:] = out
    return int(out.size)


def booster_save_model(h, start_iteration, num_iteration,
                       filename) -> None:
    capi.LGBM_BoosterSaveModel(_get(h), num_iteration=int(num_iteration),
                               filename=filename,
                               start_iteration=int(start_iteration))


def booster_get_eval(h, data_idx, out_results) -> int:
    pairs = capi.LGBM_BoosterGetEval(_get(h), int(data_idx))
    vals = np.asarray([v for _, v in pairs], np.float64)
    _arr(out_results, vals.size, 1)[:] = vals
    return int(vals.size)
