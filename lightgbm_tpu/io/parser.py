"""Text parsers: CSV / TSV / LibSVM.

TPU-native counterpart of the reference parser machinery
(reference: src/io/parser.cpp:1-169, src/io/parser.hpp). Format is
auto-detected from delimiter statistics over the first lines
(GetStatistic, parser.cpp:10-23); the label column presence is inferred
the same way the reference does (GetLabelIdxFor{CSV,TSV,Libsvm},
parser.cpp:25-62). Unlike the row-at-a-time C++ parsers, parsing here is
columnar: the whole file is tokenized into a dense float64 matrix up
front — binning immediately consumes full columns, so a row iterator
would just add overhead.
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


class ParsedText:
    """Dense matrix view of a parsed text file.

    ``values``: [N, C] float64 with NaN for missing; ``label``: [N] or
    None when the file has no label column; ``num_columns`` counts the
    feature columns only (label removed).
    """

    def __init__(self, values: np.ndarray, label: Optional[np.ndarray]):
        self.values = values
        self.label = label

    @property
    def num_data(self) -> int:
        return self.values.shape[0]

    @property
    def num_columns(self) -> int:
        return self.values.shape[1]


def _get_statistic(line: str) -> Tuple[int, int, int]:
    """Delimiter counts (parser.cpp:10-23)."""
    return line.count(","), line.count("\t"), line.count(":")


def detect_format(lines: List[str]) -> str:
    """CreateParser's format vote (parser.cpp:87-135): statistics from
    the first lines; ':' wins (libsvm), then tab, then comma."""
    if not lines:
        return "tsv"
    comma, tab, colon = _get_statistic(lines[0])
    if len(lines) > 1:
        c2, t2, l2 = _get_statistic(lines[1])
        # require consistency like the reference's two-line check
        if colon > 0 and l2 > 0:
            return "libsvm"
        if tab > 0 and t2 > 0:
            return "tsv"
        if comma > 0 and c2 > 0:
            return "csv"
    if colon > 0:
        return "libsvm"
    if tab > 0:
        return "tsv"
    if comma > 0:
        return "csv"
    # single column of labels / values
    return "tsv"


_NUM_RE = re.compile(r"^\s*$")


def _to_float_array(tokens: np.ndarray) -> np.ndarray:
    """Vectorized str->float with blanks and na/nan as NaN
    (Common::AtofPrecise semantics for our purposes)."""
    low = np.char.lower(np.char.strip(tokens.astype(str)))
    out = np.full(low.shape, np.nan, np.float64)
    bad = (low == "") | (low == "na") | (low == "nan") | (low == "null") \
        | (low == "none") | (low == "?")
    good = ~bad
    if good.any():
        out[good] = low[good].astype(np.float64)
    return out


def parse_delimited(lines: List[str], delim: str,
                    label_idx: int) -> ParsedText:
    """CSV/TSV parse (parser.hpp CSVParser/TSVParser): every column is
    numeric; ``label_idx`` < 0 means no label column in the file."""
    if not lines:
        return ParsedText(np.zeros((0, 0), np.float64), None)
    rows = [ln.rstrip("\r\n").split(delim) for ln in lines]
    width = max(len(r) for r in rows)
    if min(len(r) for r in rows) != width:
        # ragged rows: pad with blanks (reference errors per-row; we warn)
        log.warning("Text file has ragged rows; padding with NaN")
        rows = [r + [""] * (width - len(r)) for r in rows]
    mat = _to_float_array(np.asarray(rows, dtype=object))
    if label_idx >= 0 and width > label_idx:
        label = mat[:, label_idx].astype(np.float32)
        feats = np.delete(mat, label_idx, axis=1)
        return ParsedText(feats, label)
    return ParsedText(mat, None)


def parse_libsvm(lines: List[str], label_idx: int,
                 num_features_hint: int = 0) -> ParsedText:
    """LibSVM parse (parser.hpp LibSVMParser): 'label i:v j:v ...' with
    0-based feature indices, densified to [N, max_idx+1]."""
    labels: List[float] = []
    entries: List[List[Tuple[int, float]]] = []
    max_idx = num_features_hint - 1
    has_label = label_idx >= 0
    for ln in lines:
        toks = ln.split()
        row: List[Tuple[int, float]] = []
        start = 0
        if has_label and toks and ":" not in toks[0]:
            labels.append(float(toks[0]))
            start = 1
        elif has_label:
            labels.append(0.0)
        for tok in toks[start:]:
            if ":" not in tok:
                continue
            i_s, v_s = tok.split(":", 1)
            idx = int(i_s)
            row.append((idx, float(v_s)))
            if idx > max_idx:
                max_idx = idx
        entries.append(row)
    n, c = len(entries), max(max_idx + 1, 0)
    values = np.zeros((n, c), np.float64)
    for r, row in enumerate(entries):
        for idx, v in row:
            values[r, idx] = v
    label = np.asarray(labels, np.float32) if has_label and labels else None
    return ParsedText(values, label)


def infer_label_idx(lines: List[str], fmt: str, num_features: int,
                    label_idx: int) -> int:
    """GetLabelIdxFor{CSV,TSV,Libsvm} (parser.cpp:25-62): when the
    expected feature count is known (prediction on a model with
    max_feature_idx), a file whose rows carry exactly that many columns
    has no label column."""
    if num_features <= 0 or not lines:
        return label_idx
    first = lines[0].strip()
    if fmt == "libsvm":
        pos_space = re.search(r"\s", first)
        pos_colon = first.find(":")
        if pos_space is None or (pos_colon >= 0
                                 and pos_space.start() < pos_colon):
            return label_idx
        return -1
    delim = "\t" if fmt == "tsv" else ","
    if len(first.split(delim)) == num_features:
        return -1
    return label_idx


def _first_data_lines(filename: str, k: int, header: bool,
                      ignore_comments: bool) -> Tuple[List[str], str]:
    """First k data lines + the raw header line (cheap peek)."""
    head = ""
    out: List[str] = []
    header_pending = header
    from .file_io import open_file
    with open_file(filename, "r") as fh:
        for ln in fh:
            t = ln.strip()
            if not t or (ignore_comments and t.startswith("#")):
                continue
            if header_pending:
                head = ln.rstrip("\r\n")
                header_pending = False
                continue
            out.append(ln.rstrip("\r\n"))
            if len(out) >= k:
                break
    return out, head


def parse_file(filename: str, header: bool = False, label_idx: int = 0,
               num_features_hint: int = 0,
               ignore_comments: bool = True) -> Tuple[ParsedText, List[str]]:
    """Parse a text data file; returns (parsed, header_names).

    header_names is empty when ``header`` is False. Comment lines
    starting with '#' and blank lines are skipped (TextReader parity,
    include/LightGBM/utils/text_reader.h). The heavy tokenization runs
    in the native C++ parser when available (io/native.py); format and
    label detection peek only the first lines either way.
    """
    first, head = _first_data_lines(filename, 2, header,
                                    ignore_comments)
    fmt = detect_format(first)
    label_idx = infer_label_idx(first, fmt, num_features_hint,
                                label_idx)
    names: List[str] = []
    if header and head:
        delim = {"csv": ",", "tsv": "\t"}.get(fmt, "\t")
        names = [t.strip() for t in head.split(delim)]

    from .native import parse_file_native
    native = (parse_file_native(filename, header, label_idx)
              if ignore_comments else None)
    _FMT_CODE = {"tsv": 0, "csv": 1, "libsvm": 2}
    if native is not None and native[2] != _FMT_CODE[fmt]:
        # the native single-line sniff disagrees with the two-line
        # detection (e.g. a ':' inside a CSV field) — trust the python
        # detector and parser
        native = None
    if native is not None:
        values, labels, _ = native
        if fmt == "libsvm" and num_features_hint > values.shape[1]:
            values = np.pad(values, ((0, 0), (0, num_features_hint
                                              - values.shape[1])))
        parsed = ParsedText(values, labels)
    else:
        from .file_io import open_file
        with open_file(filename, "r") as fh:
            raw = fh.read().splitlines()
        lines = [ln for ln in raw if ln.strip()
                 and not (ignore_comments
                          and ln.lstrip().startswith("#"))]
        if header and lines:
            lines.pop(0)
        if fmt == "libsvm":
            parsed = parse_libsvm(lines, label_idx, num_features_hint)
        else:
            delim = "\t" if fmt == "tsv" else ","
            parsed = parse_delimited(lines, delim, label_idx)
    if names and parsed.label is not None \
            and len(names) > parsed.num_columns:
        # drop the label column's name so names align with features
        names.pop(max(label_idx, 0))
    return parsed, names
