"""Binned dataset container.

TPU-native counterpart of the reference Dataset/Metadata/FeatureGroup
(reference: include/LightGBM/dataset.h:36-622, src/io/dataset.cpp:212,
src/io/metadata.cpp). The reference stores per-group CPU bin arrays with
4/8/16/32-bit widths; here the binned matrix is ONE dense device tensor
``[N, F] uint8/int32`` resident in HBM (the GPU learner already did the
dense-only device layout, gpu_tree_learner.cpp:325-357 — we follow that
design and keep every non-trivial feature dense).

Host-side responsibilities: sampling, BinMapper construction
(Dataset::Construct / DatasetLoader::ConstructBinMappersFromTextData),
trivial-feature exclusion, metadata (labels/weights/queries/init scores).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..ops.split import FeatureMeta
from ..utils import log
from .binning import BinMapper, BinType


def default_cache_dir() -> str:
    """Shared on-disk cache directory for engine artifacts that persist
    across processes: the kernel tuning cache (ops/autotune.py) and the
    persistent XLA compile cache live here; dataset binary files
    (save_binary) take explicit paths but share the versioned-token
    discipline. Overridable via LGBM_TPU_CACHE_DIR."""
    import tempfile
    d = os.environ.get("LGBM_TPU_CACHE_DIR") or os.path.join(
        tempfile.gettempdir(), "lgbm_tpu_cache")
    os.makedirs(d, exist_ok=True)
    return d


class Metadata:
    """Labels / weights / queries / init scores (dataset.h:36-249)."""

    def __init__(self, label=None, weight=None, group=None, init_score=None):
        self.label = (None if label is None
                      else np.asarray(label, np.float32).reshape(-1))
        self.weights = (None if weight is None
                        else np.asarray(weight, np.float32).reshape(-1))
        self.init_score = (None if init_score is None
                           else np.asarray(init_score, np.float64))
        self.query_boundaries = None
        if group is not None:
            group = np.asarray(group, np.int64).reshape(-1)
            self.query_boundaries = np.concatenate(
                [[0], np.cumsum(group)]).astype(np.int64)
        self._query_weights = None

    def check_or_partition(self, num_data: int) -> None:
        if self.label is not None and len(self.label) != num_data:
            log.fatal(f"Length of label ({len(self.label)}) is not same "
                      f"as number of data ({num_data})")
        if self.weights is not None and len(self.weights) != num_data:
            log.fatal("Length of weights differs from number of data")
        if (self.query_boundaries is not None
                and self.query_boundaries[-1] != num_data):
            log.fatal("Sum of query counts differs from number of data")

    @property
    def num_queries(self):
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1


def find_column_mappers(X: np.ndarray, config: Config,
                        categorical=(), total_rows: Optional[int] = None,
                        columns: Optional[Sequence[int]] = None,
                        presampled: bool = False
                        ) -> List[Optional[BinMapper]]:
    """Sample rows and find a BinMapper per column (trivial ones
    included) — the shared bin-construction loop of
    DatasetLoader::ConstructBinMappersFromTextData
    (src/io/dataset_loader.cpp:196-235, 388-433).

    ``total_rows`` is the GLOBAL row count when ``X`` is one shard of a
    distributed load: the per-shard sample budget and the
    min_data_in_leaf filter scale by the shard/global ratio, and every
    shard must use the SAME total or their bin boundaries diverge.
    ``columns`` restricts the search to a subset (the distributed
    owner-rule workload split, dataset_loader.cpp:434-466); unowned
    entries come back as None. ``presampled``: ``X`` already IS the
    sample of a ``total_rows``-row dataset (two-round loading) — skip
    re-sampling, scale only the min_data filter."""
    X = np.asarray(X)
    n, nf = X.shape
    cfg = config
    total = n if total_rows is None else max(int(total_rows), 1)
    if presampled:
        sample = X
    else:
        budget = cfg.bin_construct_sample_cnt
        if total > n > 0:
            budget = max(budget * n // total, 1)   # this shard's share
        sample_cnt = min(budget, n)
        rng = np.random.default_rng(cfg.data_random_seed)
        if sample_cnt < n:
            idx = np.sort(rng.choice(n, sample_cnt, replace=False))
            sample = X[idx]
        else:
            sample = X
    snum = sample.shape[0]
    filter_cnt = 0
    if cfg.min_data_in_leaf > 0 and total > 0:
        # dataset_loader.cpp: filter scaled by sample/total ratio
        filter_cnt = max(int(cfg.min_data_in_leaf * snum / total), 1)
    cats = set(categorical)
    wanted = set(range(nf)) if columns is None else set(columns)
    mappers: List[Optional[BinMapper]] = []
    for j in range(nf):
        if j not in wanted:
            mappers.append(None)
            continue
        col = sample[:, j].astype(np.float64)
        # reference samples only non-zero values; zeros are implied
        nonzero = col[(np.abs(col) > 1e-35) | np.isnan(col)]
        m = BinMapper()
        bt = (BinType.CATEGORICAL if j in cats else BinType.NUMERICAL)
        m.find_bin(nonzero, snum, cfg.max_bin, cfg.min_data_in_bin,
                   filter_cnt, bt, cfg.use_missing, cfg.zero_as_missing)
        mappers.append(m)
    return mappers


class TpuDataset:
    """Constructed, binned training matrix + metadata."""

    def __init__(self, config: Config):
        self.config = config
        self.num_data = 0
        self.num_total_features = 0
        self.mappers: List[BinMapper] = []          # per used (inner) feature
        self.used_feature_map: np.ndarray = np.array([], np.int32)
        self.real_to_inner: dict = {}
        self.bins: Optional[np.ndarray] = None      # [N, F_used]
        # device-resident feature-major bins (io/ingest.py streamed
        # ingest): [F_used, N + bins_t_dev_pad] uint8/int32 jax array;
        # exactly one of bins / bins_t_dev is set after construction.
        # When the configured tree learner row-shards (data/voting over
        # a >1-device mesh) the array is assembled ROW-SHARDED under a
        # NamedSharding and bins_t_dev_pad holds the zero-bin columns
        # appended so every shard is the same width (consumers treat
        # them exactly like the grower's own row padding).
        self.bins_t_dev = None
        self.bins_t_dev_pad = 0
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.max_bin_global = 1
        self._reference: Optional["TpuDataset"] = None
        # EFB state (io/efb.py); None = unbundled
        self.bundles = None
        self.bundled_bins: Optional[np.ndarray] = None
        self.member_bundle: Optional[np.ndarray] = None
        self.member_offset: Optional[np.ndarray] = None
        self.bundle_width = 0
        # CSR-native state (io/sparse.py): set by the sparse
        # construction route. ``sparse_coords`` holds the
        # zero-suppressed (code, inner feature, row) planes — numpy on
        # the host path, jax arrays when the sparse device ingest
        # assembled them — retained only when the sparse histogram
        # tier may consume them (sparse.want_coords).
        self.sparse_nnz = 0
        self.sparse_density: Optional[float] = None
        self.sparse_coords = None
        self.sparse_zero_bins: Optional[np.ndarray] = None

    # -- construction -------------------------------------------------------

    def construct_from_matrix(self, X: np.ndarray, metadata: Metadata,
                              categorical: Sequence[int] = (),
                              reference: Optional["TpuDataset"] = None,
                              feature_names: Optional[List[str]] = None,
                              mappers: Optional[List[BinMapper]] = None,
                              ring=None):
        """Build bin mappers (or reuse reference's) and bin the matrix.

        Mirrors DatasetLoader::ConstructFromSampleData
        (src/io/dataset_loader.cpp:499) + Dataset::CreateValid
        (src/io/dataset.cpp:368). ``mappers`` (one per REAL column,
        trivial ones included) injects externally-agreed bin boundaries —
        the distributed loader's synced mappers
        (dataset_loader.cpp:434-466 Allgather of serialized BinMappers).
        """
        # span trace starts HERE when configured (obs/trace.py): ingest
        # runs before any booster exists, and its worker-thread spans
        # must land in the same buffer the training spans will
        from ..obs import trace
        trace.ensure_from_config(self.config)
        from .sparse import SparseMatrix
        if isinstance(X, SparseMatrix):
            return self._construct_from_sparse(
                X, metadata, categorical=categorical,
                reference=reference, feature_names=feature_names,
                mappers=mappers)
        X = np.asarray(X)
        if X.dtype not in (np.float32, np.float64):
            X = X.astype(np.float64)
        n, nf = X.shape
        self.num_data = n
        self.num_total_features = nf
        self.metadata = metadata
        self.metadata.check_or_partition(n)
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(nf)])

        from ..utils import timing
        if reference is not None:
            # valid set: reuse the train set's mappers (CreateValid)
            self._reference = reference
            self.mappers = reference.mappers
            self.used_feature_map = reference.used_feature_map
            self.real_to_inner = reference.real_to_inner
            self.max_bin_global = reference.max_bin_global
            self.feature_names = reference.feature_names
            self.num_total_features = reference.num_total_features
        elif mappers is not None:
            self._set_mappers(mappers)
        else:
            with timing.phase("binning/find_bins"):
                self._construct_mappers(X, set(categorical))
        with timing.phase("binning/bin_matrix") as ph:
            self._bin_matrix(X, efb_possible=(mappers is None
                                              and reference is None),
                             ring=ring)
            if self.bins_t_dev is not None:
                # device phase: sync at phase exit so queued kernel
                # time lands here, not in a later unrelated phase
                ph.watch(self.bins_t_dev)
        if mappers is None and self.bins is not None:
            # distributed shards skip EFB: bundling is data-dependent
            # (find_bundles over LOCAL bins) and would diverge across
            # ranks; parallel learners run unbundled anyway. The device
            # ingest path pre-probed EFB on the reference's own sample
            # (_efb_would_bundle) and only runs when nothing bundles.
            with timing.phase("binning/efb"):
                self._apply_efb()
        return self

    def _construct_from_sparse(self, sm, metadata: Metadata,
                               categorical: Sequence[int] = (),
                               reference: Optional["TpuDataset"] = None,
                               feature_names: Optional[List[str]] = None,
                               mappers: Optional[List[BinMapper]] = None
                               ) -> "TpuDataset":
        """CSR-native construction (io/sparse.py): the host never
        materializes the [N, F] float64 matrix. Mappers sample straight
        from CSR (bit-identical to the densified path's), binning is
        O(nnz) — device-side through the streamed sparse ingest
        (io/ingest.py SparseDeviceBinner) or a host scatter into the
        bin-storage tier — and datasets where EFB actually bundles
        build the host bin matrix (uint8, not float64) so the bundling
        decision and bundled matrix stay bit-identical to the
        densified path. Above ``sparse_threshold`` density the input
        takes the explicit dense fallback (the one place the densify
        cliff warning still fires on this path)."""
        from ..obs import registry as obs
        from ..utils import timing
        from . import sparse as sp
        cfg = self.config
        n, nf = sm.shape
        if not sp.route_sparse(cfg, sm):
            obs.counter("sparse/route_dense").add(1)
            log.info("sparse input density %.4f is above the CSR route "
                     "gate (1 - sparse_threshold = %g): densifying",
                     sm.density, 1.0 - cfg.sparse_threshold)
            return self.construct_from_matrix(
                sm.to_dense(warn=True), metadata,
                categorical=categorical, reference=reference,
                feature_names=feature_names, mappers=mappers)
        obs.counter("sparse/route_sparse").add(1)
        obs.counter("sparse/nnz_rows").add(sm.nnz)
        obs.gauge("sparse/density").set(sm.density)
        self.num_data = n
        self.num_total_features = nf
        self.metadata = metadata
        self.metadata.check_or_partition(n)
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(nf)])
        if reference is not None:
            self._reference = reference
            self.mappers = reference.mappers
            self.used_feature_map = reference.used_feature_map
            self.real_to_inner = reference.real_to_inner
            self.max_bin_global = reference.max_bin_global
            self.feature_names = reference.feature_names
            self.num_total_features = reference.num_total_features
        elif mappers is not None:
            self._set_mappers(mappers)
        else:
            with timing.phase("binning/find_bins"):
                self._set_mappers(sp.find_column_mappers_sparse(
                    sm, cfg, set(categorical)))
        self.sparse_nnz = sm.nnz
        self.sparse_density = sm.density
        if self.mappers:
            self.sparse_zero_bins = sp.zero_bins(self.mappers)
        # coords feed the sparse histogram tier — train sets only (the
        # grower histograms training rows; valid rows ride as weight-0
        # passengers of the dense matrix either way)
        keep_coords = (sp.want_coords(cfg, sm.density)
                       and reference is None)
        efb_possible = mappers is None and reference is None
        with timing.phase("binning/bin_matrix") as ph:
            self._bin_sparse(sm, keep_coords, efb_possible)
            if self.bins_t_dev is not None:
                ph.watch(self.bins_t_dev)
        if mappers is None and self.bins is not None:
            with timing.phase("binning/efb"):
                self._apply_efb()
            if self.bundles is not None:
                # the sparse tier never composes with EFB bundles
                # (models/gbdt.py) — binned coordinates of UNBUNDLED
                # member features would be the wrong layout anyway
                self.sparse_coords = None
        return self

    def _bin_sparse(self, sm, keep_coords: bool,
                    efb_possible: bool) -> None:
        """Bin a CSR matrix: streamed sparse device ingest when enabled
        and reproducible, else an O(nnz) host scatter into the
        bin-storage tier. Either way the dense float64 [N, F] never
        exists."""
        from ..obs import registry as obs
        from . import sparse as sp
        self.bins_t_dev = None
        self.bins_t_dev_pad = 0
        self.bins = None
        n = sm.shape[0]
        if self._sparse_device_ok(sm, efb_possible):
            from .ingest import IngestUnsupported, SparseDeviceBinner
            try:
                binner = SparseDeviceBinner(
                    self.mappers, self.used_feature_map, self.config)
            except IngestUnsupported as e:
                log.debug("sparse device ingest unavailable (%s); "
                          "host scatter", e)
            else:
                self.bins_t_dev, coords = binner.bin_matrix_sparse(
                    sm, want_coords=keep_coords)
                if keep_coords:
                    self.sparse_coords = coords
                log.info("sparse device ingest: %d rows x %d features "
                         "binned on device from nnz=%d (density %.4f) "
                         "in %d-row chunks", n, self.num_features,
                         sm.nnz, sm.density, binner.chunk_rows)
                return
        # host path: one O(nnz) entry binning serves both the bin
        # matrix scatter and (when wanted) the retained coordinates
        dtype = self.bin_dtype()
        if not self.mappers:
            self.bins = np.zeros((n, 1), dtype)
            return
        codes, feat, rows = sp.bin_entries(sm, self.mappers,
                                           self.used_feature_map)
        bins = np.empty((n, len(self.mappers)), dtype)
        bins[:] = self.sparse_zero_bins.astype(dtype)[None, :]
        bins[rows, feat] = codes.astype(dtype)
        self.bins = bins
        if keep_coords:
            self.sparse_coords = (codes, feat, rows)
        obs.counter("ingest/rows_host").add(n)

    def _sparse_device_ok(self, sm, efb_possible: bool) -> bool:
        """Gate for the streamed sparse device path — the sparse twin
        of ``_device_ingest_ok``: config-enabled, usable reproducible
        mappers, no EFB interaction, and no row-sharding mesh (the
        sparse route has no sharded ingest yet; sharded learners get
        the host bins placed under the mesh at booster init)."""
        from .ingest import ingest_enabled, ingest_mesh, mappers_supported
        if not ingest_enabled(self.config):
            return False
        if not self.mappers:
            return False
        if not mappers_supported(self.mappers):
            return False
        ref = self._reference
        if ref is not None and ref.bundles is not None:
            return False
        if ref is None and ingest_mesh(self.config) is not None:
            return False
        if efb_possible and self._efb_would_bundle_sparse(sm):
            log.info("EFB bundles this sparse data; using the host "
                     "scatter so bundling stays bit-identical (set "
                     "enable_bundle=false for device sparse ingest)")
            return False
        return True

    def _efb_would_bundle_sparse(self, sm) -> bool:
        """``_efb_would_bundle`` for CSR input: bin the SAME rng(3) row
        sample find_bundles would draw (O(nnz of the sample)) and ask
        ``would_bundle`` directly — identical verdict to the densified
        path's, binning is row-wise."""
        cfg = self.config
        if not cfg.enable_bundle or self.num_features <= 1:
            return False
        from .efb import sample_rows_for_probe, would_bundle
        from .sparse import host_bins_from_sparse
        idx = sample_rows_for_probe(sm.shape[0])
        sample = sm if idx is None else sm.take_rows(idx)
        return would_bundle(
            host_bins_from_sparse(sample, self.mappers,
                                  self.used_feature_map,
                                  self.bin_dtype()),
            self.mappers, cfg.max_conflict_rate)

    def _construct_mappers(self, X: np.ndarray, categorical: set) -> None:
        self._set_mappers(find_column_mappers(X, self.config, categorical))

    def _set_mappers(self, all_mappers: List[BinMapper]) -> None:
        """Install per-REAL-column mappers: trivial-feature exclusion +
        index maps (shared by local bin finding and distributed-agreed
        injection)."""
        used = [j for j, m in enumerate(all_mappers) if not m.is_trivial]
        if not used:
            log.warning("There are no meaningful features, as all feature "
                        "values are constant.")
        self.mappers = [all_mappers[j] for j in used]
        self.used_feature_map = np.asarray(used, np.int32)
        self.real_to_inner = {r: i for i, r in enumerate(used)}
        self.max_bin_global = max(
            (m.num_bin for m in self.mappers), default=1)

    def _bin_matrix(self, X: np.ndarray, efb_possible: bool = False,
                    ring=None) -> None:
        """Bin the whole matrix: streamed device ingest (io/ingest.py)
        when enabled and reproducible, else the host binner. Train sets
        of a row-sharding learner assemble the bins directly under the
        mesh's NamedSharding (no single-device staging). ``ring``
        (io/ingest.py ChunkRing) lets a windowed retrain loop reuse the
        previous construction's device-resident chunk buffers."""
        self.bins_t_dev = None
        self.bins_t_dev_pad = 0
        if self._device_ingest_ok(X, efb_possible):
            from .ingest import (DeviceBinner, IngestUnsupported,
                                 ingest_mesh)
            try:
                binner = DeviceBinner(self.mappers, self.used_feature_map,
                                      self.config, X.dtype)
            except IngestUnsupported as e:
                log.debug("device ingest unavailable (%s); host binner", e)
            else:
                # valid sets ride as passenger columns of the grower
                # matrix (models/gbdt.py) — only the train set's rows
                # are worth sharding at ingest time
                mesh = (ingest_mesh(self.config)
                        if self._reference is None else None)
                if mesh is not None:
                    self.bins_t_dev = binner.bin_matrix_sharded(X, mesh)
                    self.bins_t_dev_pad = (self.bins_t_dev.shape[1]
                                           - self.num_data)
                    self.bins = None
                    log.info("sharded device ingest: %d rows binned "
                             "across %d device(s) in %d-row chunks",
                             self.num_data, mesh.devices.size,
                             binner.chunk_rows)
                    return
                self.bins_t_dev = binner.bin_matrix(X, ring=ring)
                self.bins = None
                log.info("streamed device ingest: %d rows binned on "
                         "device in %d-row chunks%s", self.num_data,
                         binner.chunk_rows,
                         " (chunk ring)" if ring is not None else "")
                return
        self.bins = self.bin_rows(X)

    def _device_ingest_ok(self, X: np.ndarray, efb_possible: bool) -> bool:
        """Gate for the streamed device path: config-enabled, usable
        features, exact-comparison dtype, no EFB interaction (a valid
        set of a bundled train set must produce bundled host bins; a
        fresh set that WOULD bundle takes the host path so the bundling
        decision and bundled matrix stay bit-identical)."""
        from .ingest import ingest_enabled, mappers_supported
        if not ingest_enabled(self.config):
            return False
        if not self.mappers:
            return False
        if X.dtype not in (np.float32, np.float64):
            return False
        if not mappers_supported(self.mappers):
            return False
        ref = self._reference
        if ref is not None and ref.bundles is not None:
            return False
        if efb_possible and self._efb_would_bundle(X):
            log.info("EFB bundles this data; using the host binner so "
                     "bundling stays bit-identical (set "
                     "enable_bundle=false to stream ingest instead)")
            return False
        return True

    def _efb_would_bundle(self, X: np.ndarray) -> bool:
        """Replicate find_bundles' own sampled decision (io/efb.py
        would_bundle) without a full host bin matrix: bin the SAME
        rng(3) row sample it would draw and ask it directly. Identical
        verdict to the host path by construction — binning is
        row-wise."""
        cfg = self.config
        if not cfg.enable_bundle or self.num_features <= 1:
            return False
        from .efb import sample_rows_for_probe, would_bundle
        idx = sample_rows_for_probe(X.shape[0])
        sample = X if idx is None else X[idx]
        return would_bundle(self.bin_rows(np.asarray(sample)),
                            self.mappers, cfg.max_conflict_rate)

    def host_bins(self) -> Optional[np.ndarray]:
        """The [N, F] host bin matrix in the host storage tier
        (bin_dtype). Device-ingested sets download TRANSIENTLY — the
        result is returned, not stored, so the one-of-bins/bins_t_dev
        invariant (and the device-resident fast path) stays intact."""
        if self.bins is None and self.bins_t_dev is not None:
            log.info("materializing device-binned matrix on host "
                     "(%d rows)", self.num_data)
            return np.ascontiguousarray(
                np.asarray(self.bins_t_dev)[:, :self.num_data].T).astype(
                self.bin_dtype(), copy=False)
        return self.bins

    def bin_rows(self, X: np.ndarray) -> np.ndarray:
        """Bin a block of rows (post-drop feature layout) with this
        dataset's mappers — numerical columns through the threaded C++
        bulk binner, the rest per-column. Used for the whole matrix and
        for two_round's streaming chunks (io/loader.py)."""
        n = X.shape[0]
        f = len(self.mappers)
        dtype = self.bin_dtype()
        bins = np.zeros((n, max(f, 1)), dtype)
        done = self._bin_matrix_native(X, bins, dtype)
        for i, real in enumerate(self.used_feature_map):
            if i in done:
                continue
            bins[:, i] = self.mappers[i].value_to_bin(X[:, real]).astype(dtype)
        from ..obs import registry as obs
        obs.counter("ingest/rows_host").add(n)
        return bins

    def bin_dtype(self):
        """Tiered bin storage width (the reference's Dense{8,16,32}Bin,
        src/io/dense_bin.hpp:43): uint8 up to 256 bins, uint16 to
        65536, int32 beyond. The device tensor upcasts >8-bit tiers to
        int32 at upload (models/gbdt.py) — the tiers size host RAM and
        the binary cache."""
        if self.max_bin_global <= 256:
            return np.uint8
        if self.max_bin_global <= 65536:
            return np.uint16
        return np.int32

    def _bin_matrix_native(self, X, bins, dtype) -> set:
        """Bulk-bin the numerical uint8 columns through the threaded C++
        binner (native/fast_parser.cpp lgbm_tpu_bin_columns) — numpy's
        per-column searchsorted is ~45 s for the 11M x 28 HIGGS shape,
        the native path ~1 s. Returns the set of inner features done
        (categoricals and >256-bin tiers stay on value_to_bin)."""
        if dtype is not np.uint8 or not self.mappers:
            return set()
        from .binning import BinType, MissingType
        from .native import bin_columns_native
        idx, cols, bl, rl, nb = [], [], [], [], []
        for i, real in enumerate(self.used_feature_map):
            m = self.mappers[i]
            if m.bin_type != BinType.NUMERICAL:
                continue
            r = m.num_bin - 1
            nanb = -1
            if m.missing_type == MissingType.NAN:
                r -= 1
                nanb = m.num_bin - 1
            idx.append(i)
            cols.append(real)
            bl.append(np.asarray(m.bin_upper_bound[:r], np.float64))
            rl.append(r)
            nb.append(nanb)
        if not idx:
            return set()
        out = bin_columns_native(
            X, np.asarray(cols, np.int32), bl,
            np.asarray(rl, np.int32), np.asarray(nb, np.int32))
        if out is None:
            return set()
        for k, i in enumerate(idx):
            bins[:, i] = out[:, k]
        return set(idx)

    def _apply_efb(self) -> None:
        """Exclusive feature bundling (Dataset::FindGroups +
        FastFeatureBundling, dataset.cpp:66-210) — see io/efb.py."""
        from .efb import bundle_bins, find_bundles
        cfg = self.config
        if self._reference is not None:
            ref = self._reference
            if ref.bundles is None:
                return
            self.bundles = ref.bundles
            db = np.array([m.default_bin for m in self.mappers], np.int32)
            nb = np.array([m.num_bin for m in self.mappers], np.int32)
            self.bundled_bins, self.member_bundle, self.member_offset, \
                self.bundle_width = bundle_bins(
                    self.bins, ref.bundles, db, nb)
            return
        if not cfg.enable_bundle or self.num_features <= 1:
            return
        db = np.array([m.default_bin for m in self.mappers], np.int32)
        nb = np.array([m.num_bin for m in self.mappers], np.int32)
        bundles = find_bundles(self.bins, db, nb, cfg.max_conflict_rate)
        if len(bundles) >= self.num_features:
            return                       # nothing bundled
        self.bundles = bundles
        self.bundled_bins, self.member_bundle, self.member_offset, \
            self.bundle_width = bundle_bins(self.bins, bundles, db, nb)
        log.info("EFB bundled %d features into %d columns",
                 self.num_features, len(bundles))

    # -- views --------------------------------------------------------------

    @property
    def num_features(self) -> int:
        return len(self.mappers)

    def feature_meta(self) -> FeatureMeta:
        if not self.mappers:
            # all features trivial: one dummy single-bin feature matching
            # the [N, 1] zero bin matrix — never splittable, so the tree
            # stays the constant prior (gbdt.cpp:378-396)
            return FeatureMeta(
                num_bin=np.ones(1, np.int32),
                missing_type=np.zeros(1, np.int32),
                default_bin=np.zeros(1, np.int32),
                monotone=np.zeros(1, np.int32),
                penalty=np.ones(1, np.float32),
                is_cat=np.zeros(1, np.int32))
        mono = None
        if self.config.monotone_constraints:
            mono = [0] * self.num_features
            for i, real in enumerate(self.used_feature_map):
                if real < len(self.config.monotone_constraints):
                    mono[i] = self.config.monotone_constraints[real]
        contri = None
        if self.config.feature_contri:
            contri = [1.0] * self.num_features
            for i, real in enumerate(self.used_feature_map):
                if real < len(self.config.feature_contri):
                    contri[i] = self.config.feature_contri[real]
        meta = FeatureMeta.from_mappers(self.mappers, mono, contri)
        if self.bundles is not None:
            meta = meta._replace(bundle=self.member_bundle,
                                 offset=self.member_offset)
        return meta

    def feature_infos(self) -> List[str]:
        """Per REAL feature; 'none' for unused (model header parity)."""
        infos = []
        for real in range(self.num_total_features):
            inner = self.real_to_inner.get(real)
            infos.append("none" if inner is None
                         else self.mappers[inner].feature_info())
        return infos

    def create_valid(self, X, metadata: Metadata) -> "TpuDataset":
        from .sparse import SparseMatrix
        if not isinstance(X, SparseMatrix):
            X = np.asarray(X)
        v = TpuDataset(self.config)
        v.construct_from_matrix(X, metadata, reference=self)
        # CreateValid's contract (dataset.cpp:368): the valid set BINS
        # with the train set's mappers, never re-derives them — the
        # streamed ingest path rides the same guarantee
        assert v.mappers is self.mappers, \
            "create_valid must never re-derive bin mappers"
        return v

    # -- binary cache (SaveBinaryFile parity, dataset.cpp:542) --------------

    # v2 writes nibble-packed dict bins; the version lives in the token
    # so a pre-v2 reader REJECTS new files instead of loading a dict it
    # cannot use. v1 files (plain array bins) are still readable.
    BINARY_TOKEN = b"______LightGBM_TPU_Binary_File_Tokenv2____\n"
    BINARY_TOKEN_V1 = b"______LightGBM_TPU_Binary_File_Token______\n"

    def _pack_nibble_columns(self, bins: Optional[np.ndarray] = None):
        """4-bit storage tier (the reference's Dense4bitsBin,
        src/io/dense_nbits_bin.hpp:37-58): columns with <= 16 bins are
        nibble-packed two-rows-per-byte in the binary cache. (No
        compute-path tier is needed here: 16-bin features already pack
        8 per 128-row MXU tile in the wave kernel, so packing would
        only inflate the matmul.) Returns (bins_or_packed, packed_cols).
        """
        if bins is None:
            bins = self.bins
        if bins is None or bins.dtype != np.uint8 \
                or not self.mappers:
            return bins, []
        packed_cols = [i for i, m in enumerate(self.mappers)
                       if m.num_bin <= 16]
        if not packed_cols:
            return bins, []
        out = {"shape": bins.shape}
        n = bins.shape[0]
        half = (n + 1) // 2
        for i in packed_cols:
            col = bins[:, i]
            lo = col[0::2]
            hi = np.zeros(half, np.uint8)
            hi[:n // 2] = col[1::2]
            out[i] = (lo | (hi << 4)).astype(np.uint8)
        packed_set = set(packed_cols)
        keep = [i for i in range(bins.shape[1])
                if i not in packed_set]
        out["rest"] = bins[:, keep]
        out["keep"] = keep
        return out, packed_cols

    @staticmethod
    def _unpack_nibble_columns(bins, packed_cols):
        if not packed_cols:
            return bins
        n, f = bins["shape"]
        full = np.zeros((n, f), np.uint8)
        full[:, bins["keep"]] = bins["rest"]
        for i in packed_cols:
            b = bins[i]
            full[0::2, i] = b[: (n + 1) // 2] & 0x0F
            full[1::2, i] = (b[: n // 2] >> 4) & 0x0F
        return full

    def save_binary(self, filename: str) -> None:
        import pickle
        # device-ingested sets download transiently (host_bins keeps
        # the device-resident layout authoritative)
        bins_repr, packed_cols = self._pack_nibble_columns(
            self.host_bins())
        with open(filename, "wb") as fh:
            fh.write(self.BINARY_TOKEN)
            pickle.dump({
                "num_data": self.num_data,
                "num_total_features": self.num_total_features,
                "mappers": [m.to_dict() for m in self.mappers],
                "used_feature_map": self.used_feature_map,
                "bins": bins_repr,
                "packed_cols": packed_cols,
                "label": self.metadata.label,
                "weights": self.metadata.weights,
                "query_boundaries": self.metadata.query_boundaries,
                "init_score": self.metadata.init_score,
                "feature_names": self.feature_names,
            }, fh, protocol=4)
        log.info("Saved binary dataset to %s", filename)

    @classmethod
    def is_binary_file(cls, filename: str) -> bool:
        try:
            with open(filename, "rb") as fh:
                tok = fh.read(len(cls.BINARY_TOKEN))
                return tok in (cls.BINARY_TOKEN, cls.BINARY_TOKEN_V1)
        except OSError:
            return False

    @classmethod
    def load_binary(cls, filename: str, config: Config) -> "TpuDataset":
        import pickle
        with open(filename, "rb") as fh:
            tok = fh.read(len(cls.BINARY_TOKEN))
            if tok not in (cls.BINARY_TOKEN, cls.BINARY_TOKEN_V1):
                log.fatal(f"{filename} is not a lightgbm_tpu binary file")
            d = pickle.load(fh)
        ds = cls(config)
        ds.num_data = d["num_data"]
        ds.num_total_features = d["num_total_features"]
        ds.mappers = [BinMapper.from_dict(m) for m in d["mappers"]]
        ds.used_feature_map = d["used_feature_map"]
        ds.real_to_inner = {r: i for i, r in enumerate(ds.used_feature_map)}
        ds.bins = cls._unpack_nibble_columns(
            d["bins"], d.get("packed_cols", []))
        ds.metadata = Metadata(d["label"], d["weights"], None, d["init_score"])
        ds.metadata.query_boundaries = d["query_boundaries"]
        ds.feature_names = d["feature_names"]
        ds.max_bin_global = max((m.num_bin for m in ds.mappers), default=1)
        return ds
