"""Exclusive Feature Bundling (EFB).

TPU-native counterpart of the reference's feature bundling
(reference: src/io/dataset.cpp:66-210 FindGroups/FastFeatureBundling,
NIPS'17 LightGBM paper §4). Mutually-exclusive sparse features share one
HBM column: member k owns the bin range [offset_k, offset_k + num_bin_k)
and column value 0 means "every member at its default bin".

Where the reference bakes bundling into FeatureGroup bin storage and
per-feature OrderedBin iterators, here it is a pure storage transform
around the wave grower's seams:

- the device bins tensor holds BUNDLE columns (F_bundles x N, narrower
  than F_members x N by the bundling ratio);
- after each wave histogram pass over bundles, member histograms are
  reconstructed by a gather + the default-bin complement
  (member_default = bundle_row_total - sum of the member's other bins
  — the "most frequent bin" trick of dense_bin.hpp);
- the partition decodes a member's bin from the bundle column:
  in-range -> col - offset, out-of-range (another member active or all
  defaults) -> the member's default bin.

Everything downstream (split search, SplitResult, TreeRecord, host
trees) keeps ORIGINAL member features and bin spaces.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..utils import log


EFB_SAMPLE_CNT = 50_000


def sample_rows_for_probe(n: int):
    """Row indices find_bundles would draw from an n-row bin matrix —
    THE sampling contract shared by the streamed-ingest probes
    (io/dataset.py, io/loader.py): same rng(3), same count. Returns
    None when find_bundles would use every row."""
    if n > EFB_SAMPLE_CNT:
        return np.random.default_rng(3).choice(n, EFB_SAMPLE_CNT,
                                               replace=False)
    return None


def would_bundle(sample_bins: np.ndarray, mappers,
                 max_conflict_rate: float) -> bool:
    """Bundling decision from a pre-binned probe sample (the rows
    ``sample_rows_for_probe`` selected): True iff find_bundles on the
    full matrix would bundle anything. One definition for both
    streamed-ingest callers so the bit-identical-bundling guarantee
    cannot de-synchronize."""
    if sample_bins.shape[1] <= 1:
        return False
    db = np.array([m.default_bin for m in mappers], np.int32)
    nb = np.array([m.num_bin for m in mappers], np.int32)
    bundles = find_bundles(sample_bins, db, nb, max_conflict_rate,
                           presampled=True)
    return len(bundles) < sample_bins.shape[1]


def find_bundles(bins: np.ndarray, default_bins: np.ndarray,
                 num_bins: np.ndarray, max_conflict_rate: float,
                 sample_cnt: int = EFB_SAMPLE_CNT,
                 max_bundle_bins: int = 255,
                 presampled: bool = False) -> List[List[int]]:
    """Greedy conflict-bounded grouping (Dataset::FindGroups,
    dataset.cpp:66-159): features ordered by non-default count; each
    joins the first bundle whose accumulated conflicts stay under
    ``max_conflict_rate * n`` and whose total bin width fits.
    ``presampled``: ``bins`` already IS the rng(3) row sample (the
    streamed-ingest probe, io/dataset.py _efb_would_bundle) — skip the
    internal subsample so both callers see identical rows."""
    n, f = bins.shape
    if f <= 1:
        return [[j] for j in range(f)]
    if n > sample_cnt and not presampled:
        idx = np.random.default_rng(3).choice(n, sample_cnt,
                                              replace=False)
        sample = bins[idx]
    else:
        sample = bins
    sn = sample.shape[0]
    nondefault = sample != default_bins[None, :]      # [sn, F] bool
    counts = nondefault.sum(axis=0)
    order = np.argsort(-counts, kind="stable")
    max_conflict = int(max_conflict_rate * sn)

    bundle_masks: List[np.ndarray] = []
    bundle_conflicts: List[int] = []
    bundle_bins_total: List[int] = []
    bundles: List[List[int]] = []
    for j in order:
        placed = False
        fj = nondefault[:, j]
        width = int(num_bins[j])
        for bi in range(len(bundles)):
            conflict = int((bundle_masks[bi] & fj).sum())
            if (bundle_conflicts[bi] + conflict <= max_conflict
                    and bundle_bins_total[bi] + width
                    <= max_bundle_bins):
                bundles[bi].append(int(j))
                bundle_masks[bi] |= fj
                bundle_conflicts[bi] += conflict
                bundle_bins_total[bi] += width
                placed = True
                break
        if not placed:
            bundles.append([int(j)])
            bundle_masks.append(fj.copy())
            bundle_conflicts.append(0)
            bundle_bins_total.append(width)
    # keep member order stable inside each bundle
    return [sorted(b) for b in bundles]


def bundle_bins(bins: np.ndarray, bundles: List[List[int]],
                default_bins: np.ndarray, num_bins: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Encode member bins into bundle columns.

    Returns (bundled [N, F_b], member_bundle [F_m], member_offset [F_m],
    max_bundle_width). Column encoding: 0 = all members at default;
    member k non-default with bin b -> offset_k + b (later members win
    the allowed conflicts, dataset.cpp:186-199 merge semantics).
    """
    n, f = bins.shape
    fb = len(bundles)
    member_bundle = np.zeros(f, np.int32)
    member_offset = np.zeros(f, np.int32)
    width = 1
    for bi, members in enumerate(bundles):
        # offset 0 is reserved for the all-default col value only when
        # a bundle has >1 member; singleton bundles stay identity-coded
        if len(members) == 1:
            j = members[0]
            member_bundle[j] = bi
            member_offset[j] = 0
            width = max(width, int(num_bins[j]))
            continue
        off = 1
        for j in members:
            member_bundle[j] = bi
            member_offset[j] = off
            off += int(num_bins[j])
        width = max(width, off)
    out = np.zeros((n, fb), bins.dtype if width <= 256 else np.int32)
    for bi, members in enumerate(bundles):
        if len(members) == 1:
            out[:, bi] = bins[:, members[0]]
            continue
        col = np.zeros(n, np.int64)
        for j in members:
            nd = bins[:, j] != default_bins[j]
            col[nd] = member_offset[j] + bins[nd, j]
        out[:, bi] = col.astype(out.dtype)
    return out, member_bundle, member_offset, width


def expand_bundle_histogram(bundle_hist, member_bundle, member_offset,
                            member_num_bin, member_default_bin, B_out):
    """[..., F_b, B_bundle, 3] bundle histograms -> member histograms
    [..., F_m, B_out, 3] (jit-traceable; see module docstring for the
    default-bin complement)."""
    import jax.numpy as jnp
    mb = jnp.asarray(member_bundle)
    mo = jnp.asarray(member_offset)
    nb = jnp.asarray(member_num_bin)
    db = jnp.asarray(member_default_bin)
    Bb = bundle_hist.shape[-2]
    bidx = jnp.arange(B_out, dtype=jnp.int32)[None, :]       # [1, B]
    src = jnp.clip(mo[:, None] + bidx, 0, Bb - 1)            # [F_m, B]
    valid = (bidx < nb[:, None]) & ~(bidx == db[:, None])
    # gather member rows out of their bundles
    per_bundle = bundle_hist[..., mb, :, :]                  # [...,F_m,Bb,3]
    member = jnp.take_along_axis(
        per_bundle, src[(None,) * (per_bundle.ndim - 3)
                        + (slice(None), slice(None), None)],
        axis=-2)                                             # [...,F_m,B,3]
    member = member * valid[(None,) * (per_bundle.ndim - 3)
                            + (slice(None), slice(None), None)]
    # default-bin complement: bundle row total - member's other bins
    tot = bundle_hist.sum(axis=-2)[..., mb, :]               # [...,F_m,3]
    rest = member.sum(axis=-2)
    comp = (tot - rest)[..., None, :]                        # [...,F_m,1,3]
    at_default = (bidx == db[:, None])[(None,) * (per_bundle.ndim - 3)
                                       + (slice(None), slice(None),
                                          None)]
    return member + comp * at_default
