"""Distributed data loading: per-host row shards with globally agreed
bin boundaries.

TPU-native counterpart of the reference's distributed loading
(reference: src/io/dataset_loader.cpp:163-167 round-robin/pre-partition
row assignment, :434-466 distributed bin finding — each machine finds
bins from its LOCAL sample and the serialized BinMappers ride an
Allgather; sample-seed sync src/application/application.cpp:112-114).

The TPU redesign: the "network" is the JAX runtime. In a multi-host
program every process loads only its own rows (``pre_partition`` — one
file per host) or its round-robin slice of a shared file, finds bin
mappers locally, and the mapper exchange is a
``multihost_utils.process_allgather`` of the serialized mappers instead
of a socket Allgather. Single-process meshes (one host, many chips) need
none of this — rows are sharded onto devices by ``shard_map`` in
parallel/learners.py and binning is already global — but the loader
also EMULATES S hosts in one process (tests, and the driver's virtual
CPU mesh) by computing every rank's mappers from the data in hand.

``shard_bin_mappers`` is the pure agreement rule; ``find_column_mappers``
(io/dataset.py) is the shared per-column bin search, so single-host and
distributed binning can never drift apart.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils import log
from .binning import BinMapper
from .dataset import Metadata, TpuDataset, find_column_mappers
from .loader import DatasetLoader


def local_bin_mappers(X: np.ndarray, config: Config,
                      categorical: Sequence[int] = (),
                      total_rows: Optional[int] = None,
                      columns: Optional[Sequence[int]] = None
                      ) -> List[BinMapper]:
    """One rank's locally-found mappers (trivial ones included).
    ``total_rows`` is the GLOBAL row count — every rank must pass the
    same value or boundaries diverge (see find_column_mappers).
    ``columns`` restricts to the rank's owned subset."""
    return find_column_mappers(X, config, categorical, total_rows,
                               columns)


def shard_bin_mappers(per_shard_mappers: List[List[BinMapper]]
                      ) -> List[BinMapper]:
    """The agreement rule: feature ``j`` takes shard ``j % S``'s locally
    found mapper (the reference splits bin-finding workload round-robin
    over machines and Allgathers the result, dataset_loader.cpp:434-466)
    — every shard applies the same rule to the same gathered list, so
    all shards end with identical mappers."""
    S = len(per_shard_mappers)
    nf = len(per_shard_mappers[0])
    for ms in per_shard_mappers:
        if len(ms) != nf:
            log.fatal("Shards disagree on column count during "
                      "distributed bin finding")
    return [per_shard_mappers[j % S][j] for j in range(nf)]


def _allgather_rowcount(n_local: int) -> int:
    """Sum of every process's local row count — the exact global total
    every rank must agree on before bin finding."""
    import jax
    if jax.process_count() == 1:
        return n_local
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    # int32 on the wire: JAX would silently downcast an int64 payload
    # anyway (x64 disabled); the sum runs in host int64 either way
    counts = multihost_utils.process_allgather(
        jnp.asarray([n_local], jnp.int32))
    return int(np.sum(np.asarray(counts, np.int64)))


def _allgather_mappers(local: List[Optional[BinMapper]]
                       ) -> List[List[Optional[BinMapper]]]:
    """Exchange serialized mappers across JAX processes
    (multihost_utils.process_allgather as the Allgather wire)."""
    import jax
    if jax.process_count() == 1:
        return [local]
    import pickle
    from jax.experimental import multihost_utils
    import jax.numpy as jnp
    blob = pickle.dumps([None if m is None else m.to_dict()
                         for m in local])
    # pad to the max blob length so the gather is rectangular
    arr = np.frombuffer(blob, np.uint8)
    ln = multihost_utils.process_allgather(
        jnp.asarray([arr.size], jnp.int32))
    maxlen = int(np.max(ln))
    padded = np.zeros(maxlen, np.uint8)
    padded[:arr.size] = arr
    gathered = multihost_utils.process_allgather(jnp.asarray(padded))
    out = []
    for i in range(gathered.shape[0]):
        raw = bytes(np.asarray(gathered[i])[: int(ln[i, 0])])
        out.append([None if d is None else BinMapper.from_dict(d)
                    for d in pickle.loads(raw)])
    return out


def _rank_queries(nq: int, rank: int, world: int,
                  mode: str = "round_robin") -> np.ndarray:
    """Query indices owned by ``rank`` — round_robin (the reference's
    ``i % world`` default) or contiguous ceil(nq/world) blocks (the
    elastic multi-host path: original row order is preserved, so the
    shard-invariant row hashing of the quantized tier lines up with
    the serial run)."""
    if mode == "contiguous":
        b = -(-nq // world) if world else nq
        return np.arange(min(rank * b, nq), min((rank + 1) * b, nq))
    return np.arange(rank, nq, world)


def _rank_rows(n: int, rank: int, world: int,
               query_boundaries: Optional[np.ndarray],
               mode: str = "round_robin") -> np.ndarray:
    """Row assignment (dataset_loader.cpp:163-167 round-robin;
    ``mode="contiguous"`` = order-preserving blocks). With query
    boundaries, whole QUERIES are assigned so no query is split across
    hosts (the reference partitions by query when boundaries exist,
    src/io/metadata.cpp CheckOrPartition)."""
    if query_boundaries is None:
        # rows ARE size-1 queries: one assignment rule, two callers
        return _rank_queries(n, rank, world, mode)
    nq = len(query_boundaries) - 1
    qs = _rank_queries(nq, rank, world, mode)
    return np.concatenate([
        np.arange(query_boundaries[q], query_boundaries[q + 1])
        for q in qs]) if len(qs) else np.zeros(0, np.int64)


def _slice_metadata(meta: Metadata, sel: np.ndarray, n: int,
                    rank: int, world: int,
                    mode: str = "round_robin") -> Metadata:
    """Shard-slice every metadata field. init_score is the flattened
    [K*N] multiclass layout (io/loader.py) — sliced per class. Query
    sizes are re-derived from the whole queries kept by _rank_rows
    (same assignment ``mode``, so rows and groups cannot disagree)."""
    isc = meta.init_score
    if isc is not None:
        k = max(1, len(isc) // max(n, 1))
        isc = np.asarray(isc).reshape(k, n)[:, sel].reshape(-1)
    group = None
    if meta.query_boundaries is not None:
        qb = meta.query_boundaries
        qs = _rank_queries(len(qb) - 1, rank, world, mode)
        group = np.diff(qb)[qs]
    return Metadata(
        label=None if meta.label is None else meta.label[sel],
        weight=None if meta.weights is None else meta.weights[sel],
        group=group, init_score=isc)


class DistributedLoader:
    """Per-host dataset loading with agreed bins.

    ``world``/``rank`` default to the JAX process topology; tests pass
    them explicitly to emulate S hosts in one process."""

    def __init__(self, config: Config, world: Optional[int] = None,
                 rank: Optional[int] = None):
        import jax
        self.config = config
        self.world = jax.process_count() if world is None else world
        self.rank = jax.process_index() if rank is None else rank

    def _owned(self, rank: int, nf: int) -> List[int]:
        """Columns whose bins rank ``rank`` finds (owner rule j % S)."""
        return list(range(rank, nf, self.world))

    def _emulated(self) -> bool:
        import jax
        return jax.process_count() == 1 and self.world > 1

    # -- the one slice → agree → construct path -------------------------

    def _load_shard(self, X: np.ndarray, meta: Metadata,
                    categorical: Sequence[int], pre_partitioned: bool,
                    shard_matrices: Optional[List[np.ndarray]],
                    names: Optional[List[str]] = None,
                    mode: str = "round_robin") -> TpuDataset:
        """``X``/``meta`` are the full data (shared-file mode) or this
        host's rows (pre-partitioned). ``shard_matrices`` = every rank's
        rows for emulated (one-process) agreement; None = the real
        multi-process allgather. ``mode`` picks the shared-file row
        assignment (round_robin | contiguous).

        Each rank finds bins only for its OWNED columns (j % S == rank,
        the reference's workload split, dataset_loader.cpp:434-466);
        the exchange assembles the full agreed set."""
        X = np.asarray(X)
        nf = X.shape[1]
        shared_file = not pre_partitioned and self.world > 1
        if shared_file:
            sel = _rank_rows(X.shape[0], self.rank, self.world,
                             meta.query_boundaries, mode)
            Xl = X[sel]
            ml = _slice_metadata(meta, sel, X.shape[0],
                                 self.rank, self.world, mode)
            total = X.shape[0]
            if shard_matrices is None and self._emulated():
                # shared data, one process: every rank's slice is in
                # hand — true per-rank mappers, exact agreement
                shard_matrices = [
                    X[_rank_rows(X.shape[0], r, self.world,
                                 meta.query_boundaries, mode)]
                    for r in range(self.world)]
        else:
            Xl, ml = X, meta
            total = (sum(s.shape[0] for s in shard_matrices)
                     if shard_matrices is not None
                     else _allgather_rowcount(Xl.shape[0]))
            if (shard_matrices is None and self._emulated()):
                total = Xl.shape[0] * self.world    # best local guess

        if shard_matrices is not None:
            per_shard = [
                find_column_mappers(s, self.config, categorical, total,
                                    columns=self._owned(r, nf))
                for r, s in enumerate(shard_matrices)]
        else:
            local = find_column_mappers(
                Xl, self.config, categorical, total,
                columns=self._owned(self.rank, nf))
            per_shard = _allgather_mappers(local)
            if len(per_shard) == 1 and self.world > 1:
                log.warning(
                    "distributed load with one JAX process and no peer "
                    "data in hand: using this rank's local bins; pass "
                    "all_shards=/peer_files= for emulated agreement")
                # local only covers owned columns — fill the rest
                local = find_column_mappers(Xl, self.config,
                                            categorical, total)
                per_shard = [local] * self.world
        agreed = shard_bin_mappers(per_shard)
        ds = TpuDataset(self.config)
        ds.construct_from_matrix(Xl, ml, categorical=categorical,
                                 feature_names=names, mappers=agreed)
        return ds

    # -- public entry points --------------------------------------------

    def load_rank_matrix(self, X: np.ndarray, metadata: Metadata,
                         categorical: Sequence[int] = (),
                         pre_partitioned: bool = False,
                         all_shards: Optional[List[np.ndarray]] = None,
                         contiguous: bool = False) -> TpuDataset:
        """Construct this rank's shard dataset from an in-memory matrix.

        pre_partitioned=True: ``X``/``metadata`` are ALREADY this host's
        rows (the reference's pre_partition=true file-per-machine mode).
        Otherwise rows (whole queries for ranking data) are assigned
        round-robin ``i % world == rank``
        (dataset_loader.cpp:163-167 used_data_indices), or as
        order-preserving contiguous blocks with ``contiguous=True``
        (the elastic multi-host trainer's assignment — see
        _rank_queries).

        ``all_shards`` supplies every shard's rows so the mapper
        exchange can be emulated without multiple processes.
        """
        return self._load_shard(X, metadata, categorical,
                                pre_partitioned, all_shards,
                                mode=("contiguous" if contiguous
                                      else "round_robin"))

    def load_rank_file(self, filename: str,
                       pre_partitioned: Optional[bool] = None,
                       peer_files: Optional[List[str]] = None
                       ) -> TpuDataset:
        """Text-file variant. pre_partition=true (config) = ``filename``
        holds only this host's rows; otherwise every host parses the
        shared file and keeps its round-robin slice. ``peer_files``
        (single-process emulation/tests) lists EVERY host's
        pre-partitioned file so the mapper exchange can run without
        multiple JAX processes."""
        cfg = self.config
        if pre_partitioned is None:
            pre_partitioned = cfg.pre_partition
        ldr = DatasetLoader(cfg)
        X, meta, names, categorical = ldr._parse_with_metadata(filename)
        shard_matrices = None
        if peer_files is not None:
            shard_matrices = [ldr._parse_with_metadata(pf)[0]
                              for pf in peer_files]
        ds = self._load_shard(X, meta, categorical, pre_partitioned,
                              shard_matrices, names or None)
        log.info("Distributed load rank %d/%d: %d local rows",
                 self.rank, self.world, ds.num_data)
        return ds

    # -- real multi-process construction (parallel/cluster.py) ----------

    def construct_multihost(self, X_local: np.ndarray,
                            meta_global: Metadata, *, n_global: int,
                            row_start: int, mesh,
                            categorical: Sequence[int] = (),
                            feature_names: Optional[List[str]] = None,
                            mappers: Optional[List[BinMapper]] = None
                            ) -> TpuDataset:
        """Per-host ingest under a REAL multi-process mesh: this rank
        holds only the contiguous global rows [row_start, row_start +
        len(X_local)) (cut by io/ingest.host_row_block so host blocks
        cover the mesh's device shard blocks), bin boundaries are
        agreed over the real allgather wire (each rank finds its OWNED
        columns' mappers from its LOCAL rows, exactly the reference's
        distributed bin finding), and the [F, N_pad] bin matrix
        assembles ACROSS processes — every host streams its block
        through the double-buffered device ingest onto its own
        devices; no host ever materializes (or transfers) the full
        matrix.

        The returned dataset is GLOBAL-shaped (``num_data=n_global``,
        global metadata): models/gbdt.py keeps its host-side vectors
        host-global under SPMD, and only the bins are row-sharded
        device state. ``meta_global`` must carry full-length fields —
        assemble per-host label files with ``allgather_row_slices``."""
        X_local = np.asarray(X_local)
        if X_local.dtype not in (np.float32, np.float64):
            X_local = X_local.astype(np.float64)
        nf = X_local.shape[1]
        total = int(n_global)
        if mappers is not None:
            # externally-agreed boundaries (an elastic resume injects
            # the checkpoint bundle's mappers): no bin finding, no
            # exchange — every rank installs the same list
            agreed = mappers
        else:
            # owned-column local mappers from LOCAL rows; the exchange
            # assembles every rank's contribution (j % world owner
            # rule)
            local = find_column_mappers(
                X_local, self.config, categorical, total,
                columns=self._owned(self.rank, nf))
            per_shard = _allgather_mappers(local)
            if len(per_shard) != self.world:
                log.fatal(f"multihost bin agreement saw "
                          f"{len(per_shard)} processes, expected "
                          f"{self.world} — every rank must construct "
                          f"the dataset collectively")
            agreed = shard_bin_mappers(per_shard)

        ds = TpuDataset(self.config)
        ds.num_data = total
        ds.num_total_features = nf
        ds.metadata = meta_global
        ds.metadata.check_or_partition(total)
        ds.feature_names = (list(feature_names) if feature_names
                            else [f"Column_{i}" for i in range(nf)])
        ds._set_mappers(agreed)

        from .ingest import (DeviceBinner, IngestUnsupported,
                             host_row_block, mappers_supported,
                             shard_width)
        binner = None
        if ds.mappers and mappers_supported(ds.mappers):
            try:
                binner = DeviceBinner(ds.mappers, ds.used_feature_map,
                                      self.config, X_local.dtype)
            except IngestUnsupported as e:
                log.debug("multihost device ingest unavailable (%s); "
                          "host binner per block", e)
        hist_chunk = int(getattr(self.config, "tpu_hist_chunk", 0) or 0)
        lo, hi, S = host_row_block(total, mesh, hist_chunk)
        if not (row_start <= lo and hi <= row_start + X_local.shape[0]):
            raise ValueError(
                f"rank {self.rank}: local rows [{row_start}, "
                f"{row_start + X_local.shape[0]}) do not cover this "
                f"host's device blocks [{lo}, {hi}) — cut per-host "
                f"data with io/ingest.host_row_block")
        if binner is not None:
            ds.bins_t_dev = binner.bin_matrix_multihost(
                X_local, mesh, total, row_start)
        else:
            # host-binner fallback: bin the local block on host, then
            # assemble the same global layout from per-device shards
            import jax
            import jax.numpy as jnp
            from ..parallel import cluster
            from ..parallel.learners import AXIS
            positions = list(mesh.devices.reshape(-1))
            D = len(positions)
            S = shard_width(total, D, hist_chunk)
            dtype = (np.uint8 if ds.max_bin_global <= 256 else np.int32)
            proc_shards = []
            for gd, dev in enumerate(positions):
                if dev.process_index != jax.process_index():
                    continue
                blk_lo, blk_hi = gd * S, min(gd * S + S, total)
                blk = np.zeros((max(len(ds.mappers), 1), S), dtype)
                if blk_lo < blk_hi:
                    rows = ds.bin_rows(
                        X_local[blk_lo - row_start:blk_hi - row_start])
                    blk[:, :blk_hi - blk_lo] = rows.T
                proc_shards.append(jax.device_put(jnp.asarray(blk),
                                                  dev))
            ds.bins_t_dev = cluster.local_shards_to_global(
                proc_shards, (max(len(ds.mappers), 1), D * S), mesh,
                None, AXIS)
        ds.bins_t_dev_pad = ds.bins_t_dev.shape[1] - total
        ds.bins = None
        log.info("multihost load rank %d/%d: %d global rows, this "
                 "host's block [%d, %d)", self.rank, self.world, total,
                 lo, hi)
        return ds


def allgather_row_slices(values: Optional[np.ndarray], row_start: int,
                         n_global: int) -> Optional[np.ndarray]:
    """Assemble a GLOBAL row-aligned vector (labels, weights) from
    every rank's contiguous slice over the coordination allgather —
    how per-host label files become the host-global metadata
    models/gbdt.py keeps under SPMD. None passes through (every rank
    must agree it is None)."""
    import jax
    if jax.process_count() == 1:
        return values
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    have = values is not None
    flags = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([1 if have else 0], jnp.int32)))
    if int(flags.sum()) == 0:
        return None
    if int(flags.sum()) != flags.size:
        log.fatal("allgather_row_slices: some ranks passed None and "
                  "others data — metadata fields must be consistently "
                  "present across hosts")
    v = np.asarray(values, np.float64).reshape(-1)
    lens = np.asarray(multihost_utils.process_allgather(
        jnp.asarray([int(row_start), v.size], jnp.int32)))
    maxlen = int(lens[:, 1].max())
    # float64 rides the wire as BYTES: jnp.asarray of a float64 host
    # buffer silently downcasts to float32 with x64 disabled (the
    # same reason _allgather_mappers ships pickled uint8) — a direct
    # gather would truncate every value
    padded = np.zeros(maxlen * 8, np.uint8)
    raw = np.frombuffer(v.tobytes(), np.uint8)
    padded[:raw.size] = raw
    gathered = np.asarray(multihost_utils.process_allgather(
        jnp.asarray(padded)))
    out = np.zeros(int(n_global), np.float64)
    seen = np.zeros(int(n_global), bool)
    for r in range(gathered.shape[0]):
        lo, ln = int(lens[r, 0]), int(lens[r, 1])
        vals = np.frombuffer(
            gathered[r, :ln * 8].tobytes(), np.float64)
        # host blocks may OVERLAP at shard-alignment boundaries
        # (host_row_block clamps to n); last writer wins — the slices
        # agree wherever they overlap by construction
        out[lo:lo + ln] = vals
        seen[lo:lo + ln] = True
    if not seen.all():
        log.fatal(f"allgather_row_slices: assembled slices leave "
                  f"{int((~seen).sum())} of {n_global} rows uncovered "
                  f"— per-host slices must tile [0, n_global)")
    return out
